"""Headline benchmark: IVF-Flat vector search on one TPU chip.

Mirrors the reference's first-party benchmark (cgo/cuvs/blog.md: wiki_all
768-d, top-20, IVF-Flat CPU search = 768 QPS @ recall 0.86 at 1M rows,
nprobe=8 — BASELINE.md). Same shape here: 1M x 768 synthetic clustered
embeddings, top-20, batched queries on a single TPU v5e.

Prints ONE JSON line:
  {"metric": ..., "value": QPS, "unit": "qps", "vs_baseline": QPS/768,
   ...aux fields (recall, build seconds)}

Env overrides: MO_BENCH_N (rows), MO_BENCH_D (dim), MO_BENCH_Q (queries),
MO_BENCH_SMOKE=1 (tiny shapes, CPU-friendly sanity run).
"""

import json
import os
import sys
import threading
import time

import jax

# The image's sitecustomize pins JAX_PLATFORMS=axon; for the CPU fallback
# run the env var alone is not enough (same reason as tests/conftest.py) —
# must force the platform before the backend initializes.
if os.environ.get("MO_BENCH_CPU_FALLBACK") == "1":
    jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (MO_JAX_CACHE=0 disables): build and
# search compiles are part of the timed numbers, and the cuVS worker the
# design chases caches its compiled kernels the same way.
from matrixone_tpu.utils import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import jax.numpy as jnp
import numpy as np

import matrixone_tpu  # noqa: F401  (enables x64)
from matrixone_tpu.vectorindex import brute_force, ivf_flat
from matrixone_tpu.vectorindex.recall import recall_at_k

SMOKE = os.environ.get("MO_BENCH_SMOKE") == "1"
INDEX_KIND = os.environ.get("MO_BENCH_INDEX", "ivfflat")   # ivfflat | ivfpq
METRIC = os.environ.get("MO_BENCH_METRIC", "ivf")          # ivf | q1
N = int(os.environ.get("MO_BENCH_N", 20_000 if SMOKE else 1_000_000))
D = int(os.environ.get("MO_BENCH_D", 64 if SMOKE else 768))
NQ = int(os.environ.get("MO_BENCH_Q", 256 if SMOKE else 1024))
K = 20
NLIST = 64 if SMOKE else 1024
NPROBE = 8
BATCH = 128 if SMOKE else 256
# measured on the 2-core CPU fallback: chunk 64 beats 32/128 (bigger
# chunks thrash the gather working set, smaller ones underfill threads)
QUERY_CHUNK = int(os.environ.get("MO_BENCH_QC", 64))
BASELINE_QPS = 768.0  # cgo/cuvs/blog.md:149 — IVF-Flat CPU search, 1M, nprobe=8


def make_data(key, n, d, n_centers=2048):
    """Clustered synthetic embeddings (recall on structureless uniform data
    is meaningless; wiki_all embeddings are strongly clustered)."""
    kc, kl, kn, kq = jax.random.split(key, 4)
    centers = jax.random.normal(kc, (min(n_centers, n // 4 or 1), d),
                                jnp.float32) * 1.0
    # generate in chunks to bound peak memory
    chunks = []
    step = 1 << 17
    for i in range(0, n, step):
        m = min(step, n - i)
        lab = jax.random.randint(jax.random.fold_in(kl, i), (m,), 0,
                                 centers.shape[0])
        noise = jax.random.normal(jax.random.fold_in(kn, i), (m, d),
                                  jnp.float32) * 0.35
        chunks.append(centers[lab] + noise)
    data = jnp.concatenate(chunks)
    qlab = jax.random.randint(kq, (NQ,), 0, centers.shape[0])
    qnoise = jax.random.normal(jax.random.fold_in(kq, 1), (NQ, d),
                               jnp.float32) * 0.35
    queries = centers[qlab] + qnoise
    return data, queries


def bench_q1(n: int = None) -> dict:
    """TPC-H Q1 rows/sec through the full SQL engine (BASELINE config #1),
    measured WITH the object-backed storage path enabled: the table is
    loaded, checkpointed to objectio objects on a LocalFS object store,
    and its segments demoted to blockcache-served lazy views — every
    timed scan goes through the out-of-core read path, no bypass.

    The reference publishes no first-party Q1 throughput (BASELINE.md), so
    vs_baseline is null; the number itself is the tracked metric."""
    import tempfile

    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage import blockcache
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.storage.fileservice import LocalFS
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.utils import tpch
    if n is None:
        n = int(os.environ.get("MO_BENCH_N",
                               100_000 if SMOKE else 6_001_215))
    # size the decoded-column cache to the working set (~96 B/row over
    # the scanned columns + validity) so the warm loop measures the hot
    # path, not eviction thrash; an explicit MO_BLOCK_CACHE_MB wins
    os.environ.setdefault("MO_BLOCK_CACHE_MB",
                          str(max(256, n * 96 >> 20)))
    fs = LocalFS(tempfile.mkdtemp(prefix="mo_bench_q1_"))
    eng = Engine(fs)
    s = Session(catalog=eng)
    # load = generate + insert + checkpoint-to-objects + demote: the
    # number includes every byte reaching the object store (r5 measured
    # 23.74 s here; the coalesced lz4 write path is the fix)
    t0 = time.time()
    arrays = tpch.load_lineitem(s.catalog, n)
    eng.checkpoint(demote=True)
    t_load = time.time() - t0
    lazy = [seg.is_lazy for seg in eng.get_table("lineitem").segments]
    assert lazy and all(lazy), "bench must run object-backed (no bypass)"
    oracle = tpch.q1_oracle(arrays)
    t0 = time.time()
    rows = s.execute(tpch.Q1_SQL).rows()      # cold: decode + compile
    t_cold = time.time() - t0
    exact = tpch.q1_check(rows, oracle)
    blockcache.CACHE.reset_stats()            # warm loop accounting
    # ---- warm fused loop (MO_PLAN_FUSION default on): one compiled
    # device program per fragment per batch; dispatch + trace deltas
    # ride the JSON line as the fusion evidence
    disp0 = M.fusion_dispatch.get(kind="step")
    trace0 = M.fusion_trace_seconds.get()
    best = 0.0
    for _ in range(3):
        t0 = time.time()
        s.execute(tpch.Q1_SQL)
        best = max(best, n / (time.time() - t0))
    fused_dispatches = M.fusion_dispatch.get(kind="step") - disp0
    trace_seconds = M.fusion_trace_seconds.get() - trace0
    # ---- warm-RESIDENT loop: after the reps above the blockcache's
    # device tier holds every decoded column as a ready device array,
    # so this window measures pure device residency — the tentpole
    # claim is device_cache_hit_rate >= 0.99 with ~0 re-upload bytes
    # (every byte staged host->device during the window is counted)
    blockcache.CACHE.reset_stats()
    best_res = 0.0
    for _ in range(2):
        t0 = time.time()
        s.execute(tpch.Q1_SQL)
        best_res = max(best_res, n / (time.time() - t0))
    cache_res = blockcache.CACHE.stats()
    dev_tier = cache_res["device_tier"]
    # ---- per-stage device vs host split: one diagnostic re-execution
    # with the fragment's profile hooks armed (block_until_ready around
    # the compiled step, host bookkeeping timed separately)
    dev0 = M.fusion_step_seconds.get(kind="device")
    host0 = M.fusion_step_seconds.get(kind="host")
    profile_was = os.environ.get("MO_FUSION_PROFILE")
    os.environ["MO_FUSION_PROFILE"] = "1"
    try:
        s.execute(tpch.Q1_SQL)
    finally:
        if profile_was is None:
            os.environ.pop("MO_FUSION_PROFILE", None)
        else:
            os.environ["MO_FUSION_PROFILE"] = profile_was
    stage_device_s = round(
        M.fusion_step_seconds.get(kind="device") - dev0, 4)
    stage_host_s = round(
        M.fusion_step_seconds.get(kind="host") - host0, 4)
    # ---- the pre-fusion per-operator path, kept as its own
    # non-comparable metric family (same convention as the r04->r05
    # object-backed methodology split): trends continue for both
    fusion_was = os.environ.get("MO_PLAN_FUSION")
    os.environ["MO_PLAN_FUSION"] = "0"
    try:
        s.execute(tpch.Q1_SQL)                # re-warm the unfused jits
        best_unfused = 0.0
        for _ in range(2):
            t0 = time.time()
            s.execute(tpch.Q1_SQL)
            best_unfused = max(best_unfused, n / (time.time() - t0))
    finally:
        if fusion_was is None:
            os.environ.pop("MO_PLAN_FUSION", None)
        else:
            os.environ["MO_PLAN_FUSION"] = fusion_was
    # ---- MO_TRACE_PROFILE=1: one diagnostic rep with motrace armed —
    # the fused run's full span tree (statement -> fusion.compile /
    # fusion.dispatch / txn spans) lands as a Perfetto-loadable Chrome
    # trace artifact next to the JSON line
    trace_artifact = None
    trace_spans = 0
    if os.environ.get("MO_TRACE_PROFILE") == "1":
        import tempfile as _tf
        from matrixone_tpu.utils import motrace
        was_armed, was_sample = (motrace.TRACER.armed,
                                 motrace.TRACER.sample)
        motrace.TRACER.arm(sample=1.0)
        motrace.TRACER.clear()
        try:
            s.execute(tpch.Q1_SQL)
            tids = motrace.TRACER.trace_ids()
            if tids:
                trace_spans = len(motrace.TRACER.spans_of(tids[-1]))
            paths = motrace.dump(_tf.mkdtemp(prefix="mo_q1_trace_"))
            trace_artifact = paths[-1] if paths else None
        finally:
            # restore BOTH armed and sample: an MO_TRACE=1 run at 1%
            # sampling must not leave later families tracing at 100%
            motrace.TRACER.armed = was_armed
            motrace.TRACER.sample = was_sample
            motrace.TRACER.clear()
    cache = blockcache.CACHE.stats()
    # roofline-style evidence for the scan+agg path: Q1 touches 7
    # columns (l_quantity/extendedprice/discount/tax as decimal64,
    # returnflag/linestatus codes, shipdate) — effective scan bandwidth
    # is the honest "how close to HBM" number for a bandwidth-bound query
    q1_bytes = n * (4 * 8 + 2 * 4 + 4)
    # analytic flop count per row: 7 agg lanes (sum/avg inputs, the
    # disc_price/charge products, predicates and the group scatter) —
    # ~40 flops/row is the honest order of magnitude for Q1's arithmetic
    q1_flops = n * 40
    from matrixone_tpu.utils import roofline as _rf
    pb = _rf.peak_bytes_per_s()
    # roofline promotion: achieved bytes/s + flops/s for the fused
    # family vs MO_PEAK_TFLOPS / MO_PEAK_GBPS (utilizations stay null
    # on backends without a declared peak; the achieved rates trend)
    rf_q1 = _rf.mfu(q1_flops, q1_bytes, 1.0, n / best) if best else {}
    serving = None
    if os.environ.get("MO_BENCH_NO_SERVING") != "1":
        try:
            serving = bench_serving(s, n)
        except Exception as e:               # noqa: BLE001
            serving = {"metric": "serving_hot_qps", "value": 0,
                       "unit": "error", "vs_baseline": None,
                       "error": f"{type(e).__name__}: {e}"}
    udf_entry = None
    if os.environ.get("MO_BENCH_NO_UDF") != "1":
        try:
            udf_entry = bench_udf()
        except Exception as e:               # noqa: BLE001
            udf_entry = {"metric": "udf_qps", "value": 0,
                         "unit": "error", "vs_baseline": None,
                         "error": f"{type(e).__name__}: {e}"}
    mview_entry = None
    if os.environ.get("MO_BENCH_NO_MVIEW") != "1":
        try:
            mview_entry = bench_mview()
        except Exception as e:               # noqa: BLE001
            mview_entry = {"metric": "mview_delta_refresh_speedup",
                           "value": 0, "unit": "error",
                           "vs_baseline": None,
                           "error": f"{type(e).__name__}: {e}"}
    q3_entries = []
    if os.environ.get("MO_BENCH_NO_Q3") != "1":
        try:
            q3_entry = bench_q3()
            # hoist the nested unfused family: the driver contract and
            # bench_guard read one level of extra_metrics
            q3_entries = [q3_entry] + q3_entry.pop("extra_metrics", [])
        except Exception as e:               # noqa: BLE001
            q3_entries = [{"metric": "tpch_q3_fused_rows_per_sec",
                           "value": 0, "unit": "error",
                           "vs_baseline": None,
                           "error": f"{type(e).__name__}: {e}"}]
    if os.environ.get("MO_BENCH_NO_Q3S") != "1":
        try:
            q3s_entry = bench_q3_sharded()
            q3_entries += [q3s_entry] + q3s_entry.pop("extra_metrics",
                                                      [])
        except Exception as e:               # noqa: BLE001
            q3_entries.append({
                "metric": "tpch_q3_sharded_rows_per_sec",
                "value": 0, "unit": "error", "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}"})
    unfused_entry = {
        # the per-operator path's own family: the absolute floor for it
        # stays in BENCH_FLOORS.json, the fused family gets its own
        "metric": f"tpch_q1_rows_per_sec_{n}",
        "value": round(best_unfused, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "plan_fusion": 0,
        "backend": jax.default_backend(),
    }
    warmres_entry = {
        # the device-residency family: same query, measured in the
        # window where the blockcache's device tier is fully hot —
        # the floor for it guards the zero-re-upload property, the
        # hit-rate/upload fields ARE the acceptance evidence
        "metric": f"tpch_q1_warmres_rows_per_sec_{n}",
        "value": round(best_res, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "device_cache_hit_rate": dev_tier["hit_rate"],
        "upload_bytes": cache_res["uploaded_bytes"],
        "device_cache_used_bytes": dev_tier["used_bytes"],
        "device_cache_budget_bytes": dev_tier["budget_bytes"],
        "backend": jax.default_backend(),
    }
    extras = [m for m in (unfused_entry, warmres_entry, serving,
                          udf_entry, mview_entry) if m] + q3_entries
    return {
        **({"extra_metrics": extras} if extras else {}),
        "metric": f"tpch_q1_fused_rows_per_sec_{n}",
        "value": round(best, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "exact_vs_oracle": exact,
        "fused_dispatches": int(fused_dispatches),
        "trace_seconds": round(trace_seconds, 4),
        "stage_device_seconds": stage_device_s,
        "stage_host_seconds": stage_host_s,
        "fused_over_unfused": (round(best / best_unfused, 2)
                               if best_unfused else None),
        "load_seconds": round(t_load, 2),
        "cold_run_seconds": round(t_cold, 2),
        "object_backed": True,
        "object_write_seconds": round(M.object_write_seconds.get(), 3),
        "blockcache_hits": cache["hits"],
        "blockcache_misses": cache["misses"],
        "blockcache_hit_rate": cache["hit_rate"],
        "decode_seconds": cache["decode_seconds"],
        "device_cache_hit_rate": dev_tier["hit_rate"],
        "warm_upload_bytes": cache_res["uploaded_bytes"],
        "prefetch_ready": M.scan_prefetch.get(outcome="ready"),
        "prefetch_waited": M.scan_prefetch.get(outcome="waited"),
        "backend": jax.default_backend(),
        "scan_gbps": round(q1_bytes * best / n / 1e9, 2),
        "hbm_util": (round(q1_bytes * best / n / pb, 4) if pb else None),
        **({"roofline": rf_q1} if rf_q1 else {}),
        **({"trace_artifact": trace_artifact,
            "trace_spans": trace_spans} if trace_artifact else {}),
    }


def bench_q3(n: int = None) -> dict:
    """TPC-H Q3 rows/sec: the multi-join family the fused join/topk
    fragments exist for — customer ⋈ orders ⋈ lineitem with a grouped
    aggregate and an ORDER BY … LIMIT 10 tail, over object-backed
    tables.  Reports the fused headline next to an unfused lockstep
    re-measure (MO_PLAN_FUSION=0, same r04->r05 separate-family
    convention as Q1) plus the fused dispatch count per probe batch —
    the "whole query in single-digit dispatches" evidence.  Results
    are checked exactly: fused == unfused == the integer-domain
    q3_oracle."""
    import tempfile

    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.storage.fileservice import LocalFS
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.utils import tpch
    if n is None:
        n = int(os.environ.get("MO_BENCH_Q3_N",
                               50_000 if SMOKE else 1_500_000))
    os.environ.setdefault("MO_BLOCK_CACHE_MB",
                          str(max(256, n * 160 >> 20)))
    fs = LocalFS(tempfile.mkdtemp(prefix="mo_bench_q3_"))
    eng = Engine(fs)
    s = Session(catalog=eng)
    t0 = time.time()
    arrays = tpch.load_lineitem(s.catalog, n)
    q3data = tpch.load_tpch_q3(s.catalog, max(n // 4, 100))
    eng.checkpoint(demote=True)
    t_load = time.time() - t0
    lazy = [seg.is_lazy for seg in eng.get_table("lineitem").segments]
    assert lazy and all(lazy), "bench must run object-backed (no bypass)"
    t0 = time.time()
    rows = s.execute(tpch.Q3_SQL).rows()      # cold: decode + compile
    t_cold = time.time() - t0
    # exactness: engine rows vs the integer-domain oracle (revenue is
    # decimal scale-4 exact, dates compare as day counts)
    import datetime as _dt
    epoch = _dt.date(1970, 1, 1)
    exp = tpch.q3_oracle(arrays, q3data)
    exact = (len(rows) == len(exp) and all(
        g[0] == e[0] and round(g[1] * 10000) == e[1]
        and (g[2] - epoch).days == e[2]
        for g, e in zip(rows, exp)))
    disp0 = M.fusion_dispatch.get(kind="step")
    best = 0.0
    reps = 2 if SMOKE else 3
    for _ in range(reps):
        t0 = time.time()
        s.execute(tpch.Q3_SQL)
        best = max(best, n / (time.time() - t0))
    fused_dispatches = M.fusion_dispatch.get(kind="step") - disp0
    # warm-resident window: device tier is hot after the reps above —
    # measure the residency evidence (hit rate / re-upload bytes) over
    # one more fused execution
    from matrixone_tpu.storage import blockcache
    blockcache.CACHE.reset_stats()
    s.execute(tpch.Q3_SQL)
    cache_res = blockcache.CACHE.stats()
    dev_tier = cache_res["device_tier"]
    # lineitem streams in ceil(n / 2^20)-row batches; the dim sides add
    # their own (one-batch) builds — per-batch is the honest form of
    # the single-digit-dispatches claim
    n_batches = max(1, -(-n // (1 << 20))) * reps
    # ---- unfused lockstep: same engine, same data, per-operator path,
    # bit-identical rows (exact_vs_oracle holds for both)
    fusion_was = os.environ.get("MO_PLAN_FUSION")
    os.environ["MO_PLAN_FUSION"] = "0"
    try:
        rows_unfused = s.execute(tpch.Q3_SQL).rows()   # re-warm jits
        best_unfused = 0.0
        for _ in range(reps - 1):
            t0 = time.time()
            s.execute(tpch.Q3_SQL)
            best_unfused = max(best_unfused, n / (time.time() - t0))
    finally:
        if fusion_was is None:
            os.environ.pop("MO_PLAN_FUSION", None)
        else:
            os.environ["MO_PLAN_FUSION"] = fusion_was
    s.close()
    # roofline promotion for the fused-join family: analytic bytes over
    # the three tables' touched columns (~56B/lineitem row + the
    # n/4-row dim sides) and ~30 flops/row of join+agg math
    from matrixone_tpu.utils import roofline as _rf
    rf_q3 = (_rf.mfu(n * 30, n * 56 + (n // 4) * 32, 1.0, n / best)
             if best else {})
    return {
        "metric": f"tpch_q3_fused_rows_per_sec_{n}",
        "value": round(best, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "exact_vs_oracle": bool(exact and rows == rows_unfused),
        "fused_dispatches": int(fused_dispatches),
        "fused_dispatches_per_batch": round(fused_dispatches
                                            / n_batches, 2),
        "fused_over_unfused": (round(best / best_unfused, 2)
                               if best_unfused else None),
        "device_cache_hit_rate": dev_tier["hit_rate"],
        "warm_upload_bytes": cache_res["uploaded_bytes"],
        "load_seconds": round(t_load, 2),
        "cold_run_seconds": round(t_cold, 2),
        "object_backed": True,
        "backend": jax.default_backend(),
        **({"roofline": rf_q3} if rf_q3 else {}),
        "extra_metrics": [{
            "metric": f"tpch_q3_rows_per_sec_{n}",
            "value": round(best_unfused, 1),
            "unit": "rows/s",
            "vs_baseline": None,
            "plan_fusion": 0,
            "backend": jax.default_backend(),
        }],
    }


def bench_q3_sharded(n: int = None) -> dict:
    """TPC-H Q3 across the simulated device mesh (parallel/dist_query.py
    shard executor): the same fused fragment compiled per shard over a
    hash/rr-routed scan, partial group tables merged in one traced
    dispatch.  Headline is rows/sec at the widest mesh the box offers,
    with per-shard-count scaling entries (1/2/4/8) as extras — all
    checked bit-identical to the single-device rows.

    On the 1-core CI box the 8 simulated devices SHARE one core, so the
    sharded path pays XLA:CPU collective + per-shard dispatch overhead
    with zero real parallelism and the speedup target is out of reach
    by construction; when speedup < 1.5x the result documents that
    overhead instead, with per-stage motrace attribution
    (shard.partial / shard.merge / shard.broadcast) so the cost is
    visible, not guessed."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.utils import motrace, tpch
    if n is None:
        n = int(os.environ.get("MO_BENCH_Q3S_N",
                               40_000 if SMOKE else 400_000))
    eng = Engine()
    s = Session(catalog=eng)
    t0 = time.time()
    tpch.load_lineitem(s.catalog, n)
    tpch.load_tpch_q3(s.catalog, max(n // 4, 100))
    t_load = time.time() - t0
    local = s.execute(tpch.Q3_SQL).rows()
    s.execute("set dist_min_rows = 0")
    # rr scan routing is chunk-granular: carve segments into ~2 chunks
    # per shard so every shard of the widest mesh owns real data
    s.execute(f"set batch_rows = {max(4096, n // 16)}")
    n_dev = len(jax.devices())
    reps = 2 if SMOKE else 3
    per_shard = {}
    for shards in (1, 2, 4, 8):
        if shards > 1 and n_dev < shards:
            continue
        s.execute(f"set query_shards = {shards}")
        rows = s.execute(tpch.Q3_SQL).rows()       # warm: compile path
        exact = rows == local
        best = 0.0
        for _ in range(reps):
            t0 = time.time()
            s.execute(tpch.Q3_SQL)
            best = max(best, n / (time.time() - t0))
        per_shard[shards] = (best, exact)
    widest = max(per_shard)
    best, exact = per_shard[widest]
    speedup = (round(best / per_shard[1][0], 2)
               if per_shard.get(1, (0, 0))[0] else None)
    # ---- per-stage attribution: one traced run at the widest mesh
    was_armed = motrace.TRACER.armed
    motrace.TRACER.arm(sample=1.0)
    try:
        mark = len(motrace.TRACER._ring)
        s.execute(tpch.Q3_SQL)
        stages = {}
        for rec in list(motrace.TRACER._ring)[mark:]:
            if rec["name"].startswith("shard."):
                stages[rec["name"]] = round(
                    stages.get(rec["name"], 0.0)
                    + rec["dur_us"] / 1000.0, 2)
    finally:
        if not was_armed:
            motrace.TRACER.disarm()
    # ---- sharded Q1 on the same lineitem (the other headline shape)
    s.execute("set query_shards = 0")
    q1_local_rows = s.execute(tpch.Q1_SQL).rows()
    t0 = time.time()
    s.execute(tpch.Q1_SQL)
    q1_local = n / (time.time() - t0)
    s.execute(f"set query_shards = {widest}")
    q1_rows = s.execute(tpch.Q1_SQL).rows()        # warm: compile path
    t0 = time.time()
    s.execute(tpch.Q1_SQL)
    q1_best = n / (time.time() - t0)
    s.execute("set query_shards = 0")
    s.close()
    # ---- breadth: Q5/Q9/Q18 (multi-join + shuffle shapes) at the
    # widest mesh, exact vs the sqlite oracle AND vs the local rows
    from matrixone_tpu.utils import tpch_full as TF
    s2 = Session()
    sf = 0.005 if SMOKE else 0.02
    tables = TF.load_tpch(s2.catalog, sf=sf, seed=1)
    conn = TF.to_sqlite(tables)
    n_li = int(len(tables["lineitem"]["l_orderkey"]))
    s2.execute("set dist_min_rows = 0")
    s2.execute(f"set batch_rows = {max(1024, n_li // (2 * widest))}")
    breadth = []
    for qnum in (5, 9, 18):
        sql = TF.QUERIES[qnum]
        local_rows = s2.execute(sql).rows()
        want = conn.execute(TF.to_sqlite_sql(sql)).fetchall()
        oracle_ok = TF.rows_match(TF.normalize_rows(local_rows),
                                  TF.normalize_rows(want))
        t0 = time.time()
        s2.execute(sql)
        t_local = time.time() - t0
        s2.execute(f"set query_shards = {widest}")
        sh_rows = s2.execute(sql).rows()           # warm: compile path
        t0 = time.time()
        s2.execute(sql)
        t_sh = time.time() - t0
        s2.execute("set query_shards = 0")
        breadth.append({
            "metric": f"tpch_q{qnum}_sharded_rows_per_sec_{widest}dev",
            "value": round(n_li / t_sh, 1),
            "unit": "rows/s",
            "vs_baseline": None,
            "local_rows_per_sec": round(n_li / t_local, 1),
            "exact_vs_local": bool(TF.rows_match(
                TF.normalize_rows(sh_rows),
                TF.normalize_rows(local_rows))),
            "exact_vs_oracle": bool(oracle_ok),
            "shards": widest,
            "backend": jax.default_backend(),
        })
    conn.close()
    s2.close()
    return {
        "metric": f"tpch_q3_sharded_rows_per_sec_{n}x{widest}dev",
        "value": round(best, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "exact_vs_local": bool(exact
                               and all(e for _, e in per_shard.values())),
        "shards": widest,
        "sharded_over_local": speedup,
        # the 1-core escape hatch: when < 1.5x, the per-stage spans ARE
        # the documented XLA:CPU collective/dispatch overhead breakdown
        "stage_ms": stages,
        "simulated_devices_share_cores": os.cpu_count(),
        "load_seconds": round(t_load, 2),
        "q1_sharded_rows_per_sec": round(q1_best, 1),
        "q1_local_rows_per_sec": round(q1_local, 1),
        "q1_sharded_over_local": round(q1_best / q1_local, 2),
        "q1_exact_vs_local": q1_rows == q1_local_rows,
        "backend": jax.default_backend(),
        "extra_metrics": [{
            "metric": f"tpch_q3_sharded_rows_per_sec_{n}x{sc}dev",
            "value": round(v, 1),
            "unit": "rows/s",
            "vs_baseline": None,
            "shards": sc,
            "exact_vs_local": bool(e),
            "backend": jax.default_backend(),
        } for sc, (v, e) in sorted(per_shard.items())
            if sc != widest] + breadth,
    }


def bench_mview(n: int = None) -> dict:
    """Materialized-view maintenance: delta apply vs full
    rematerialization on a Q1-shaped view (group by two dict-coded
    dims, SUM/AVG/COUNT over decimals).  The headline is the SPEEDUP of
    applying one 1k-row commit's delta over re-running the defining
    SELECT and rewriting the table — the path every refresh paid before
    matrixone_tpu/mview existed."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.utils import metrics as M
    if n is None:
        n = int(os.environ.get("MO_BENCH_N",
                               50_000 if SMOKE else 1_000_000))
    delta_rows = 1000
    reps = 3 if SMOKE else 5
    rng = np.random.default_rng(7)
    eng = Engine()
    s = Session(catalog=eng)
    s.execute("create table mv_src (flag varchar(1), status varchar(1),"
              " qty decimal(12,2), price decimal(12,2))")
    t = eng.get_table("mv_src")
    flags, statuses = ["A", "N", "R"], ["F", "O"]

    def chunk(m):
        return (
            {"qty": rng.integers(100, 10000, m).astype(np.int64),
             "price": rng.integers(100, 1000000, m).astype(np.int64)},
            {"flag": (rng.integers(0, len(flags), m).astype(np.int32),
                      list(flags)),
             "status": (rng.integers(0, len(statuses),
                                     m).astype(np.int32),
                        list(statuses))})
    step = 1 << 19
    for i in range(0, n, step):
        arrays, strings = chunk(min(step, n - i))
        t.insert_numpy(arrays, strings=strings)
    sql = ("select flag, status, sum(qty) sq, avg(price) ap,"
           " count(*) cnt from mv_src group by flag, status")
    t0 = time.time()
    s.execute(f"create materialized view mv_q1 as {sql}")
    t_create = time.time() - t0
    # warm the delta step's compile cache (one trace per view shape —
    # steady-state production cost is what the metric tracks)
    arrays, strings = chunk(delta_rows)
    t.insert_numpy(arrays, strings=strings)
    # ---- delta apply: maintenance seconds around 1k-row commits (the
    # mo_mview_apply_seconds counter brackets exactly the maintenance
    # work: partial eval + state merge + changed-group rewrite)
    d0 = M.mview_apply_seconds.get(kind="delta")
    dense0 = M.mview_apply.get(tier="dense")
    for _ in range(reps):
        arrays, strings = chunk(delta_rows)
        t.insert_numpy(arrays, strings=strings)
    delta_s = (M.mview_apply_seconds.get(kind="delta") - d0) / reps
    dense_applies = M.mview_apply.get(tier="dense") - dense0
    # ---- full rematerialization: the pre-mview refresh path (run the
    # SELECT over the full source, DELETE + INSERT the result)
    from matrixone_tpu.stream import rematerialize
    best_full = None
    for _ in range(2):
        t0 = time.time()
        rematerialize(s, "mv_q1", sql)
        dt_full = time.time() - t0
        best_full = dt_full if best_full is None else min(best_full,
                                                          dt_full)
    rows = s.execute("select * from mv_q1").rows()
    # the metric exists to catch the delta path regressing to full
    # refresh — a run where it never fired must FAIL the floor, not
    # divide by ~zero into a fantastic pass
    from matrixone_tpu.mview import catalog as _vcat
    mode = _vcat.lookup(eng, "mv_q1").mode
    if mode != "incremental" or delta_s <= 0 or dense_applies < reps:
        return {"metric": f"mview_delta_refresh_speedup_{n}",
                "value": 0, "unit": "error", "vs_baseline": None,
                "error": f"delta path did not run (mode={mode}, "
                         f"delta_s={delta_s}, dense={dense_applies})"}
    speedup = best_full / delta_s
    return {
        "metric": f"mview_delta_refresh_speedup_{n}",
        "value": round(speedup, 1),
        "unit": "x",
        "vs_baseline": None,
        "delta_apply_seconds": round(delta_s, 5),
        "full_refresh_seconds": round(best_full, 3),
        "delta_rows": delta_rows,
        "source_rows": n,
        "view_groups": len(rows),
        "dense_applies": int(dense_applies),
        "create_seconds": round(t_create, 2),
        "backend": jax.default_backend(),
    }


def bench_ingest(rounds: int = None, rows_per_round: int = None) -> dict:
    """Sustained ingest under background compaction (the weeks-of-write-
    traffic scenario shrunk to a bench): R commit rounds with a rolling
    delete churn into one table, measured with the merge scheduler OFF
    (segments accumulate unboundedly) vs ON (compaction cycles interleave
    with the ingest, their cost paid inline).  The headline is sustained
    rows/s WITH the scheduler; the off-run's segment count vs the on-
    run's is the read-amplification the scheduler exists to bound, and
    the timed full-table aggregate under both shapes prices it."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.storage.fileservice import MemoryFS
    from matrixone_tpu.storage.merge_sched import MergeScheduler
    if rounds is None:
        rounds = int(os.environ.get("MO_BENCH_INGEST_ROUNDS",
                                    24 if SMOKE else 64))
    if rows_per_round is None:
        rows_per_round = int(os.environ.get("MO_BENCH_INGEST_ROWS",
                                            5_000 if SMOKE else 50_000))
    total = rounds * rows_per_round
    churn = max(1, rows_per_round // 8)     # rows retired per 4 rounds

    def run(with_sched: bool) -> dict:
        rng = np.random.default_rng(11)     # identical row streams
        eng = Engine(MemoryFS())
        s = Session(catalog=eng)
        s.execute("create table ing (id bigint, v bigint)")
        t = eng.get_table("ing")
        sched = MergeScheduler(eng)
        cycles = merges = deleted = 0
        base = 0
        t0 = time.time()
        for r in range(rounds):
            ids = np.arange(base, base + rows_per_round, dtype=np.int64)
            base += rows_per_round
            t.insert_numpy(
                {"id": ids,
                 "v": rng.integers(0, 1000, rows_per_round
                                   ).astype(np.int64)})
            if r % 4 == 3:                  # rolling churn window
                s.execute(f"delete from ing where id >= {deleted} and "
                          f"id < {deleted + churn}")
                deleted += churn
            if with_sched and r % 4 == 3:
                summary = sched.run_cycle()
                cycles += 1
                merges += len(summary["merged"])
        wall = time.time() - t0
        if with_sched:                      # drain: final merge + GC
            merges += len(sched.run_cycle()["merged"])
            cycles += 1
        # read amplification: segments a full scan touches, priced by
        # the aggregate every dashboard query pays
        s.execute("select sum(v), count(*) from ing")      # warm/compile
        best_read = None
        for _ in range(3):
            r0 = time.time()
            (sv, cnt), = s.execute(
                "select sum(v), count(*) from ing").rows()
            dt = time.time() - r0
            best_read = dt if best_read is None else min(best_read, dt)
        assert cnt == total - deleted, "ingest lost rows"
        return {"rows_per_sec": total / wall, "segments": len(t.segments),
                "read_seconds": best_read, "merges": merges,
                "cycles": cycles, "deleted": deleted}

    off = run(with_sched=False)
    on = run(with_sched=True)
    if on["merges"] == 0 or on["segments"] >= off["segments"]:
        # the scheduler never compacted: a floor pass at the off-path's
        # shape would guard nothing — fail loudly instead
        return {"metric": f"sustained_ingest_rows_per_sec_{total}",
                "value": 0, "unit": "error", "vs_baseline": None,
                "error": f"scheduler did not compact (merges="
                         f"{on['merges']}, segments {on['segments']} vs "
                         f"{off['segments']} off)"}
    return {
        "metric": f"sustained_ingest_rows_per_sec_{total}",
        "value": round(on["rows_per_sec"], 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "rows_per_sec_sched_on": round(on["rows_per_sec"], 1),
        "rows_per_sec_sched_off": round(off["rows_per_sec"], 1),
        "segments_sched_on": on["segments"],
        "segments_sched_off": off["segments"],
        "read_amplification": round(off["segments"] / on["segments"], 1),
        "read_seconds_sched_on": round(on["read_seconds"], 4),
        "read_seconds_sched_off": round(off["read_seconds"], 4),
        "merge_cycles": on["cycles"],
        "merges": on["merges"],
        "rounds": rounds,
        "rows_per_round": rows_per_round,
        "deleted_rows": on["deleted"],
        "backend": jax.default_backend(),
    }


def bench_serving(s, n: int) -> dict:
    """Serving-layer hot path: a repeated parameterized point query plus
    the Q1 shape, cold (caches off) vs warm (plan + result cache on),
    with the cache hit rates that explain the ratio. Reuses bench_q1's
    loaded lineitem session so the workload is the object-backed path."""
    from matrixone_tpu.serving import serving_for
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.utils import tpch

    sv = serving_for(s.catalog)
    point = ("select count(*), sum(l_quantity) from lineitem"
             " where l_orderkey = ?")
    keys = [1 + 8 * i for i in range(8)]        # 8 distinct params
    n_rounds = 4 if SMOKE else 5

    def one_pass():
        for k in keys:
            s.execute(point, [k])
        s.execute(tpch.Q1_SQL)

    stmts_per_pass = len(keys) + 1

    plan_was = sv.plan_cache.enabled
    mb_was = sv.result_cache.max_bytes
    try:
        # ---- cold: serving caches off, every execution pays full price
        sv.plan_cache.enabled = False
        sv.result_cache.max_bytes = 0
        sv.clear()
        one_pass()                              # compile warm-up
        t0 = time.time()
        for _ in range(n_rounds):
            one_pass()
        cold_qps = n_rounds * stmts_per_pass / (time.time() - t0)

        # ---- plan-only: isolates the bind/optimize savings (a result
        # hit would short-circuit the plan lookup and zero its hit rate)
        sv.plan_cache.enabled = True
        sv.result_cache.max_bytes = 0
        sv.clear()
        one_pass()                              # note templates
        one_pass()                              # activate + store
        h0p = M.plan_cache_ops.get(outcome="hit")
        m0p = M.plan_cache_ops.get(outcome="miss")
        t0 = time.time()
        for _ in range(n_rounds):
            one_pass()
        plan_qps = n_rounds * stmts_per_pass / (time.time() - t0)
        ph = M.plan_cache_ops.get(outcome="hit") - h0p
        pm = M.plan_cache_ops.get(outcome="miss") - m0p

        # ---- warm: both caches on; first pass populates, then measure
        sv.result_cache.max_bytes = 256 << 20
        one_pass()                              # populate results
        h0 = M.result_cache_ops.get(outcome="hit")
        m0 = (M.result_cache_ops.get(outcome="miss")
              + M.result_cache_ops.get(outcome="stale"))
        q_before = M.query_seconds.snapshot()
        t0 = time.time()
        for _ in range(n_rounds):
            one_pass()
        warm_qps = n_rounds * stmts_per_pass / (time.time() - t0)
        rh = M.result_cache_ops.get(outcome="hit") - h0
        rm = (M.result_cache_ops.get(outcome="miss")
              + M.result_cache_ops.get(outcome="stale") - m0)
        # statement-latency percentiles of the WARM loop only, via the
        # registry's public snapshot delta API (utils/metrics.py) —
        # never by poking histogram internals, and never polluted by
        # the process's earlier Q1/load history (same delta discipline
        # as the h0/m0 cache counters above)
        q_after = M.query_seconds.snapshot()
        p50 = M.histogram_delta_quantile(q_before, q_after, 0.50)
        p99 = M.histogram_delta_quantile(q_before, q_after, 0.99)
        q_count = q_after["count"] - q_before["count"]
    finally:
        # restore the caller's configuration even when a pass raises (a
        # deployment-enabled result cache must survive the bench)
        sv.plan_cache.enabled = plan_was
        sv.result_cache.max_bytes = mb_was
        sv.clear()
    return {
        "metric": "serving_hot_qps",
        "value": round(warm_qps, 1),
        "unit": "qps",
        "vs_baseline": None,
        "cold_qps": round(cold_qps, 2),
        "plan_only_qps": round(plan_qps, 2),
        "warm_over_cold": round(warm_qps / cold_qps, 1) if cold_qps else None,
        "result_cache_hit_rate": round(rh / (rh + rm), 4) if rh + rm else 0,
        "plan_cache_hit_rate": round(ph / (ph + pm), 4) if ph + pm else 0,
        "query_p50_s": p50,
        "query_p99_s": p99,
        "query_observations": int(q_count),
        "statements": int((3 * n_rounds + 4) * stmts_per_pass),
        "rows": n,
        "backend": jax.default_backend(),
    }


def bench_udf(n: int = None) -> dict:
    """Python/JAX UDF subsystem: a scalar arithmetic UDF over an n-row
    DOUBLE column through the full SQL engine, jit tier vs row-loop tier
    (matrixone_tpu/udf).  The query aggregates the UDF output
    (sum(f(x))) so the measurement is scan + UDF + reduce on device, not
    a host materialization of n rows.

    The row tier runs the SAME body per row in Python — measured on a
    smaller slice (its rows/s is scale-free) so the bench stays bounded.
    `jit_over_row` is the rows/s ratio; the acceptance bar is >= 50x at
    1M rows."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.udf.executor import COMPILE_CACHE
    if n is None:
        n = int(os.environ.get("MO_BENCH_UDF_N",
                               50_000 if SMOKE else 1_000_000))
    n_row = min(n, int(os.environ.get("MO_BENCH_UDF_ROW_N", 50_000)))
    s = Session()
    s.execute("create table udf_bench (x double)")
    t = s.catalog.get_table("udf_bench")
    xs = np.random.default_rng(7).normal(size=n)
    t.insert_numpy({"x": xs})
    s.execute("create table udf_bench_small (x double)")
    s.catalog.get_table("udf_bench_small").insert_numpy(
        {"x": xs[:n_row]})
    s.execute("create function bench_fma(x DOUBLE) returns DOUBLE "
              "language python as $$ x * 1.0000001 + 0.5 $$")
    q = "select sum(bench_fma(x)) from udf_bench"
    q_small = "select sum(bench_fma(x)) from udf_bench_small"

    jit_was = os.environ.get("MO_UDF_JIT")
    try:
        # ---- jit tier (the subsystem's reason to exist)
        os.environ["MO_UDF_JIT"] = "1"
        COMPILE_CACHE.clear()
        s.execute(q)                         # compile + warm
        best = 0.0
        # a jit rep is only ~20-40ms at 1M rows, so a single scheduler
        # hiccup halves one sample: best-of-7 keeps the headline from
        # under-reporting on a loaded box (adds ~0.2s total)
        for _ in range(7):
            t0 = time.time()
            s.execute(q)
            best = max(best, n / (time.time() - t0))
        jit_qps = best / n                    # queries/s at this shape

        # ---- row tier (the correctness fallback, deliberately slow)
        os.environ["MO_UDF_JIT"] = "0"
        s.execute(q_small)                   # warm the scan path
        row_rps = 0.0
        for _ in range(2):                   # best-of, same as the jit
            t0 = time.time()                 # tier: its BEST honestly
            s.execute(q_small)               # shrinks the ratio
            row_rps = max(row_rps, n_row / (time.time() - t0))
    finally:
        if jit_was is None:
            os.environ.pop("MO_UDF_JIT", None)
        else:
            os.environ["MO_UDF_JIT"] = jit_was
    return {
        "metric": f"udf_qps_{n}",
        "value": round(best, 1),
        "unit": "rows/s",
        "vs_baseline": None,
        "jit_rows_per_sec": round(best, 1),
        "row_rows_per_sec": round(row_rps, 1),
        "jit_over_row": round(best / row_rps, 1) if row_rps else None,
        "jit_queries_per_sec": round(jit_qps, 2),
        "rows": n,
        "row_tier_rows": n_row,
        "backend": jax.default_backend(),
    }


PREFLIGHT_S = float(os.environ.get("MO_BENCH_PREFLIGHT_S", 120))
_LAST_PREFLIGHT_ERR = [None]   # concrete backend error for wedge triage


def _device_preflight(timeout_s: float = None, announce: bool = True):
    """Prove the backend answers a trivial op before committing to the
    full run — a wedged accelerator tunnel must produce a diagnostic JSON
    line, not an eternal hang (observed: axon tunnel outages)."""
    if timeout_s is None:
        timeout_s = PREFLIGHT_S
    done = threading.Event()
    err = []

    def probe():
        try:
            jax.block_until_ready(jnp.ones((8,)).sum())
            done.set()
        except Exception as e:               # noqa: BLE001
            err.append(repr(e))
            done.set()
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s) or err:
        _LAST_PREFLIGHT_ERR[0] = (err[0] if err else
                                  f"device unresponsive after {timeout_s}s")
        if announce:
            print(json.dumps({
                "metric": "bench_unavailable",
                "value": 0,
                "unit": "error",
                "vs_baseline": None,
                # NOTE: no jax.* calls here — backend queries block on
                # the very wedge this branch reports
                "error": (err[0] if err else
                          f"device unresponsive after {timeout_s}s"),
            }))
        return False
    return True


def _cpu_fallback():
    """TPU tunnel dead: re-exec ourselves on the CPU backend at reduced
    scale so the round still records an honest trend line (VERDICT r2 #1:
    'a scoreboard with honest CPU numbers beats an empty one').  The JSON
    line carries backend=cpu so nobody mistakes it for a chip number."""
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MO_BENCH_CPU_FALLBACK"] = "1"
    # CPU-tractable shapes: 200k x 256 IVF (still >toy), or 1M-row Q1
    if not SMOKE:
        if METRIC != "q1":
            env.setdefault("MO_BENCH_N", "200000")
            env.setdefault("MO_BENCH_D", "256")
            env.setdefault("MO_BENCH_Q", "512")
        else:
            env.setdefault("MO_BENCH_N", "1000000")
    r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                       env=env, timeout=4500)
    return r.returncode


def main():
    if os.environ.get("MO_BENCH_CPU_FALLBACK") != "1" and \
            not _device_preflight(announce=False):
        sys.stdout.flush()
        try:
            rc = _cpu_fallback()
        except Exception:                     # noqa: BLE001
            rc = 1
        if rc != 0:
            # fallback also failed: emit the diagnostic line so shell
            # consumers never mistake a dead device for a success
            print(json.dumps({
                "metric": "bench_unavailable", "value": 0,
                "unit": "error", "vs_baseline": None,
                "error": f"{_LAST_PREFLIGHT_ERR[0]}; "
                         "cpu fallback also failed",
            }))
            sys.stdout.flush()
        # _exit (not exit) skips jax's hanging atexit sync
        os._exit(rc)
    if METRIC == "q1":
        print(json.dumps(bench_q1()))
        return
    if METRIC == "q3":
        print(json.dumps(bench_q3()))
        return
    if METRIC == "q3s":
        print(json.dumps(bench_q3_sharded()))
        return
    if METRIC == "mview":
        print(json.dumps(bench_mview()))
        return
    if METRIC == "ingest":
        print(json.dumps(bench_ingest()))
        return
    key = jax.random.PRNGKey(1234)
    t0 = time.time()
    data, queries = make_data(key, N, D)
    jax.block_until_ready(data)
    t_data = time.time() - t0

    # ---- build
    from matrixone_tpu.utils import metrics as MM
    from matrixone_tpu.vectorindex import ivf_pq
    # dtype split is backend-aware: bf16 storage/compute halves HBM
    # traffic and doubles MXU rate on TPU, but XLA:CPU has no native bf16
    # — it pays an upcast pass over every gathered candidate tile
    # (measured: f32 storage 434 qps vs bf16 375 on the 2-core fallback)
    on_cpu = jax.default_backend() == "cpu"
    storage_dtype = None if on_cpu else jnp.bfloat16
    compute_dtype = jnp.float32 if on_cpu else jnp.bfloat16
    t0 = time.time()
    if INDEX_KIND == "ivfpq":
        from matrixone_tpu.indexing import _pick_subspaces
        index = ivf_pq.build(data, nlist=NLIST,
                             n_subspaces=_pick_subspaces(D),
                             n_iter=10, balance_weight=0.3,
                             kmeans_sample=min(N, 262144),
                             compute_dtype=jnp.bfloat16)
        jax.block_until_ready(index.codes)
    else:
        # split-balanced build: minibatch Lloyd + local splitting of
        # oversized lists (see kmeans.split_oversized) — both the
        # build_seconds and the search gather budget levers. 6 minibatch
        # iterations: recall@20 is flat (~0.88) from 6 to 10 iters at
        # these shapes because the split stage absorbs residual
        # imbalance, and the 2-core fallback box is share-throttled —
        # build_seconds needs headroom under the 15s acceptance bar
        index = ivf_flat.build(data, nlist=NLIST, n_iter=6,
                               storage_dtype=storage_dtype,
                               balance_weight=0.3,
                               kmeans_sample=min(N, 262144),
                               kmeans_minibatch=65536,
                               balance_mode="split",
                               compute_dtype=jnp.bfloat16)
        jax.block_until_ready(index.vectors)
    t_build = time.time() - t0
    build_stages = {
        s: round(MM.vector_build_seconds.get(stage=s), 2)
        for s in ("kmeans", "assign", "pack")}
    search_fn = ivf_pq.search if INDEX_KIND == "ivfpq" else ivf_flat.search

    # ---- ground truth: exact f32 at HIGHEST matmul precision (bf16 truth
    # would bias the recall measurement)
    chunk = 8192 if SMOKE else 65536
    padded, n_real = brute_force.pad_dataset(data, chunk_size=chunk)
    truth_batches = []
    for i in range(0, NQ, BATCH):
        _, tidx = brute_force.search(padded, queries[i:i + BATCH], k=K,
                                     n_valid=n_real, chunk_size=chunk,
                                     compute_dtype=None)
        truth_batches.append(np.asarray(tidx))
    truth = np.concatenate(truth_batches)
    # raw dataset + padded copy are dead weight from here (the index holds
    # its own residual-encoded storage) — free ~6 GB of HBM before search
    del padded, truth_batches, data

    # ---- search: warmup (compile) then timed
    def run_all():
        outs = []
        for i in range(0, NQ, BATCH):
            _, ids = search_fn(index, queries[i:i + BATCH], k=K,
                               nprobe=NPROBE, query_chunk=QUERY_CHUNK,
                               compute_dtype=compute_dtype)
            outs.append(ids)
        jax.block_until_ready(outs[-1])
        return outs

    outs = run_all()  # compile + first measure of recall
    found = np.concatenate([np.asarray(o) for o in outs])
    rec = recall_at_k(found, truth)

    best_qps = 0.0
    for _ in range(3):
        t0 = time.time()
        run_all()
        dt = time.time() - t0
        best_qps = max(best_qps, NQ / dt)

    # per-stage attribution (probe/score/merge): a diagnostic staged
    # re-execution of one batch with a device sync between stages —
    # fills mo_vector_search_seconds and the JSON breakdown below
    search_stages = prof = None
    sidx = s_outs = s_found = None
    if INDEX_KIND == "ivfflat":
        prof = ivf_flat.search_profiled(index, queries[:BATCH], k=K,
                                        nprobe=NPROBE,
                                        query_chunk=QUERY_CHUNK,
                                        compute_dtype=compute_dtype)
        search_stages = {s: round(prof[f"{s}_seconds"], 4)
                         for s in ("probe", "score", "merge")}

    # ---- multichip: cluster-sharded serving over the device mesh
    # (vectorindex/sharded.py). Only measured when the backend exposes
    # >1 device — virtual host devices share the same cores, so a CPU
    # "mesh" measures overhead, not scaling.
    multichip = None
    if INDEX_KIND == "ivfflat" and len(jax.devices()) > 1:
        try:
            from matrixone_tpu.parallel.mesh import make_mesh
            from matrixone_tpu.vectorindex import sharded as shmod
            n_dev = len(jax.devices())
            sidx = shmod.shard_ivf(index, make_mesh(n_dev))

            def run_sharded():
                outs = []
                for i in range(0, NQ, BATCH):
                    _, ids = shmod.search_sharded(
                        sidx, queries[i:i + BATCH], k=K, nprobe=NPROBE,
                        query_chunk=QUERY_CHUNK,
                        compute_dtype=compute_dtype)
                    outs.append(ids)
                jax.block_until_ready(outs[-1])
                return outs

            s_outs = run_sharded()
            s_found = np.concatenate([np.asarray(o) for o in s_outs])
            s_qps = 0.0
            for _ in range(3):
                t0 = time.time()
                run_sharded()
                s_qps = max(s_qps, NQ / (time.time() - t0))
            multichip = {
                "metric": f"ivfflat_sharded_qps_{N}x{D}_top{K}"
                          f"_nprobe{NPROBE}x{n_dev}dev",
                "value": round(s_qps, 1),
                "unit": "qps",
                "vs_baseline": None,
                "devices": n_dev,
                "recall_at_20": round(recall_at_k(s_found, truth), 4),
                "shard_imbalance": round(
                    MM.vector_shard_imbalance.get(), 3),
            }
        except Exception as e:               # noqa: BLE001
            multichip = {"metric": "ivfflat_sharded_qps", "value": 0,
                         "unit": "error", "vs_baseline": None,
                         "error": f"{type(e).__name__}: {e}"}

    # vs_baseline only when the config actually matches the published
    # baseline (IVF-Flat, 1M x 768, chip run) — a reduced-scale CPU
    # fallback ratio would be apples-to-oranges
    comparable = (INDEX_KIND == "ivfflat" and N == 1_000_000 and D == 768
                  and jax.default_backend() not in ("cpu",))
    result = {
        "metric": f"{INDEX_KIND}_search_qps_{N}x{D}_top{K}_nprobe{NPROBE}",
        "value": round(best_qps, 1),
        "unit": "qps",
        "vs_baseline": (round(best_qps / BASELINE_QPS, 2)
                        if comparable else None),
        "recall_at_20": round(rec, 4),
        "build_seconds": round(t_build, 2),
        "build_stages": build_stages,
        "data_seconds": round(t_data, 2),
        "backend": jax.default_backend(),
        "batch": BATCH,
        "query_chunk": QUERY_CHUNK,
    }
    if search_stages:
        result["search_stages"] = search_stages
    if multichip:
        result.setdefault("extra_metrics", []).append(multichip)
    # roofline evidence (VERDICT r4 #1b): XLA's own FLOPs/bytes for the
    # search step + achieved rates and MFU/HBM utilization vs chip peak
    import functools as _ft
    from matrixone_tpu.utils import roofline
    rf = roofline.report(
        _ft.partial(search_fn, k=K, nprobe=NPROBE,
                    query_chunk=QUERY_CHUNK, compute_dtype=compute_dtype),
        (index, queries[:BATCH]),
        calls=NQ / BATCH, seconds=NQ / best_qps)
    if rf:
        result["roofline"] = rf
    # second trend line (VERDICT r3 #7: the scoreboard must trend with
    # >=2 comparable metrics): TPC-H Q1 rows/s rides in the SAME JSON
    # line so the one-line driver contract holds.  The already-measured
    # IVF number must survive a mid-Q1 tunnel wedge (a hang, not an
    # exception), so Q1 runs under a watchdog thread with a deadline —
    # on timeout the combined line still prints with an error entry.
    if os.environ.get("MO_BENCH_NO_Q1") != "1":
        # free the index/query HBM before loading lineitem: the chip has
        # ~16 GB and a resident 1M x 768 index + 6M-row table can OOM
        del index, outs, queries, truth, found
        sidx = s_outs = s_found = prof = None  # noqa: F841 (drop HBM refs)
        q1_n = (50_000 if SMOKE else
                1_000_000 if jax.default_backend() == "cpu"
                else 6_001_215)
        box = []

        def _q1():
            try:
                box.append(bench_q1(q1_n))
            except Exception as e:           # noqa: BLE001
                box.append({
                    "metric": "tpch_q1_rows_per_sec", "value": 0,
                    "unit": "error", "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}"})
        t = threading.Thread(target=_q1, daemon=True)
        t.start()
        t.join(float(os.environ.get("MO_BENCH_Q1_TIMEOUT_S", 1200)))
        q1_entry = box[0] if box else {
            "metric": "tpch_q1_rows_per_sec", "value": 0,
            "unit": "error", "vs_baseline": None,
            "error": "q1 timed out (device wedge?)"}
        # hoist nested extras (serving_hot_qps rides inside bench_q1) so
        # every metric is a top-level extra_metrics entry for the driver
        nested = q1_entry.pop("extra_metrics", None) if box else None
        result.setdefault("extra_metrics", []).append(q1_entry)
        if nested:
            result["extra_metrics"].extend(nested)
    print(json.dumps(result))
    sys.stdout.flush()
    if os.environ.get("MO_BENCH_NO_Q1") != "1" and not box:
        os._exit(0)       # q1 thread is wedged on the device: don't hang


if __name__ == "__main__":
    main()

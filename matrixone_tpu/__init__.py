"""matrixone_tpu — a TPU-native data framework with MatrixOne's capabilities.

A brand-new design (NOT a port) re-architecting the reference's compute-dense
core (reference: matrixorigin/matrixone, pkg/container + pkg/vectorize +
pkg/sql/colexec + pkg/vectorindex + cgo/) as an idiomatic JAX/XLA/Pallas
program:

- columnar batches live on device as (data, validity) array pairs
  (`matrixone_tpu.container`), mirroring the reference's
  `container/vector/vector.go:43` data/nulls/area triple;
- scalar/aggregate kernels are jitted jnp/Pallas functions with SQL null
  semantics (`matrixone_tpu.ops`), replacing `pkg/vectorize` + `cgo/xcall.c`;
- group-by / join / top-k are sort- and matmul-based formulations that map
  onto the MXU instead of pointer-chasing hash tables
  (reference: `pkg/sql/colexec`, `pkg/container/hashtable`);
- vector search (IVF-Flat build + search, k-means) runs as batched matmul
  distance kernels (`matrixone_tpu.vectorindex`), replacing
  `pkg/vectorindex` + the `cgo/cuvs` CUDA worker;
- SQL text -> plan -> pipeline compilation is host-side Python
  (`matrixone_tpu.sql`, `matrixone_tpu.vm`), with the device kept fed by a
  host-driven batch loop (reference: `pkg/sql/compile`, `pkg/vm`);
- storage / MVCC / WAL are host-side (`matrixone_tpu.storage`,
  `matrixone_tpu.txn`), preserving the reference's behavior contracts
  (`pkg/vm/engine/tae`, `pkg/txn`);
- multi-device distribution uses `jax.sharding.Mesh` + `shard_map` with XLA
  collectives over ICI (`matrixone_tpu.parallel`), replacing morpc shuffle /
  RemoteRun (`pkg/common/morpc`, `pkg/sql/compile/remoterun.go`).
"""

import jax

# SQL needs exact 64-bit integer arithmetic (BIGINT, DECIMAL as scaled int64,
# 64-bit hashes for group-by/join). TPU emulates int64 with int32 pairs; the
# hot float kernels below explicitly use f32/bf16 so MXU throughput is not
# affected. (Reference keeps the same split: exact Go int64/decimal kernels in
# pkg/vectorize, float SIMD in cgo/.)
jax.config.update("jax_enable_x64", True)

from matrixone_tpu.version import __version__  # noqa: E402,F401

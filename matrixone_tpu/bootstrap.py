"""Bootstrap + rolling catalog upgrades.

Reference analogue: `pkg/bootstrap` (+ `bootstrap/versions/`): first
boot creates the system tables; every later boot runs the ORDERED chain
of version migrations so a data dir written by an older build upgrades
in place — system tables appear/extend without dump/restore, and the
manifest records the catalog version reached.

Design here: migrations are idempotent functions keyed by the version
they establish. `upgrade(engine)` runs every migration above the data
dir's recorded version, in order, then stamps the engine; the next
checkpoint persists the stamp. A brand-new engine starts at
CATALOG_VERSION directly (migrations are for OLD dirs, not new ones) —
but running them anyway is safe by the idempotency contract, which the
tests enforce by running the chain twice.
"""

from __future__ import annotations

from typing import Callable, Dict, List

#: bump when adding a migration below
CATALOG_VERSION = 3


def _v2_account_tables(engine) -> None:
    """r4 added tenants: dirs from before have no mo_account/mo_user/
    mo_role/mo_user_role/mo_priv. AccountManager bootstraps them
    idempotently (sys account + root included)."""
    from matrixone_tpu.frontend.auth import AccountManager
    mgr = getattr(engine, "_auth_mgr", None)
    if mgr is None:
        engine._auth_mgr = AccountManager(engine)


def _v3_statement_info(engine) -> None:
    """r1's observability table, for dirs that predate it (or lost it):
    statement tracing must never fail a user statement because the
    table is missing."""
    from matrixone_tpu.utils.trace import StatementRecorder
    if not hasattr(engine, "stmt_recorder"):
        engine.stmt_recorder = StatementRecorder(engine)


#: ordered: version N's migration brings a (N-1)-dir to N
MIGRATIONS: Dict[int, Callable] = {
    2: _v2_account_tables,
    3: _v3_statement_info,
}


def upgrade(engine) -> List[int]:
    """Run pending migrations; returns the versions applied. Safe to
    call on every open (reference: bootstrap runs on every service
    start and no-ops when current)."""
    have = getattr(engine, "catalog_version", 1)
    applied: List[int] = []
    for ver in sorted(MIGRATIONS):
        if ver > have:
            MIGRATIONS[ver](engine)
            applied.append(ver)
    engine.catalog_version = max(have, CATALOG_VERSION)
    return applied

"""Change data capture (reference: pkg/cdc, 33k LoC — redesigned on the
engine's logtail subscriber hook).

A CdcTask subscribes to one table's commit stream and forwards decoded
changes (insert rows as python dicts, deletes as row-id lists) to a sink,
tracking a watermark (last shipped commit_ts) so restarts resume without
loss — events at or below the watermark are skipped on replay.

Tables SHOULD have a primary key (the reference's CDC requires one): a
PK-less table falls back to all-columns row identity, where a delete of
one of several identical rows removes them all downstream and replayed
inserts can duplicate.

Full DML propagates: inserts as rows, deletes as PK-valued rows
(decoded from the still-readable segments at notify time), updates as the
engine's delete+insert pairs within one commit ts. `backfill()` replays
committed state past the watermark from MVCC segments/tombstones, so a
restarted task resumes at-least-once without a retained event log
(reference: cdc watermark + logtail re-read).

Sinks:
  * CallbackSink  — python callable (tests, embedding)
  * SQLSink       — re-applies changes to a downstream table over any
                    Session-like executor (a second engine, or a remote
                    MOServer via matrixone_tpu.client) — the reference's
                    MySQL sinker (cdc/sinker_v2); deletes are PK-matched
"""

from __future__ import annotations

import threading
import time

from matrixone_tpu.utils import san
from typing import Callable, Dict, List, Optional

import numpy as np


def sql_literal(v) -> str:
    """Render one python value as a SQL literal — the ONE renderer shared
    by every SQL-generating sink/writer (SQLSink, SourceWriter, dynamic
    table refresh), so type coverage cannot drift between them."""
    import datetime
    import math
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1)
        return str(int((v - epoch).total_seconds() * 1e6))
    if isinstance(v, datetime.date):
        return "'" + v.isoformat() + "'"
    if isinstance(v, float):
        # SQL has no nan/inf literals: repr() would emit bare `nan`,
        # corrupting every generated statement downstream (SQLSink,
        # SourceWriter, dynamic-table refresh).  NULL is the only value
        # every SQL dialect can round-trip for "not a representable
        # number" — render it explicitly.
        if math.isnan(v) or math.isinf(v):
            return "null"
        return repr(v)
    return str(v)


def delta_events(engine, table: str, from_ts: int) -> List[tuple]:
    """The decoded per-commit delta stream of one table, replayed from
    MVCC state: every (commit_ts, kind, payload) with commit_ts >=
    from_ts, in commit order with deletes before inserts at equal ts —
    exactly the live `engine.subscribe` ordering (an UPDATE is
    delete+insert at one ts).

    This is the ONE commit-delta source shared by CdcTask.backfill, the
    materialized-view catch-up refresh (matrixone_tpu/mview), and the
    dynamic-table delta refresh (stream.refresh_dynamic_table): payloads
    are the same objects the live stream carries (Segment for inserts,
    gid arrays for deletes), so a consumer written against one surface
    works against the other.

    Replay stays EXACTLY-ONCE across background merges: each snapshot
    fence (engine.MergeFence) contributes the window (prev_merge_ts,
    merge_ts] replayed from ITS pinned segments/tombstones, the live
    list contributes everything after the last fence.  A merge's rewrite
    segment carries commit_ts == merge_ts, which every window's
    EXCLUSIVE lower bound structurally excludes — resumed consumers
    never see the compacted rewrite as a fresh insert.  A resume at or
    below the table's delta_floor (the newest RELEASED fence) has lost
    its history to GC; callers guard that rung and re-seed."""
    t = engine.get_table(table)
    fences = getattr(t, "fences", None) or []
    floor = getattr(t, "delta_floor", 0)
    if fences and from_ts > floor:
        windows = []
        prev = floor
        for f in fences:                  # ascending merge_ts
            windows.append((prev, f.merge_ts, f.segments, f.tombstones))
            prev = f.merge_ts
        windows.append((prev, None, t.segments, t.tombstones))
    else:
        # from-scratch seed (or no fenced history): the live view IS the
        # net state — one squashed replay
        windows = [(None, None, t.segments, t.tombstones)]
    events = []
    for lo, hi, segs, tombs in windows:
        for seg in segs:
            ts = seg.commit_ts
            if ts < from_ts or (lo is not None and ts <= lo) \
                    or (hi is not None and ts > hi):
                continue
            events.append((ts, 1, "insert", seg))
        for ts, gids in tombs:
            if ts < from_ts or (lo is not None and ts <= lo) \
                    or (hi is not None and ts > hi):
                continue
            events.append((ts, 0, "delete", gids))
    return [(ts, kind, payload)
            for ts, _order, kind, payload in sorted(events,
                                                    key=lambda e: e[:2])]


class FileWatermark:
    """Durable CDC watermark: one atomically-replaced file on any
    FileService (the sink side's fs in a mirror deployment).  The
    ordering contract is the whole point — callers persist ONLY AFTER
    the delivery it covers is durable downstream, so a crash between
    the two re-delivers (at-least-once; PK sinks upsert) instead of
    skipping (a gap is silent data loss the mocrash sweep's planted
    `watermark-early` violation demonstrates).  A torn store can never
    surface: FileService.write is atomic-replace, so `load` sees the
    old or the new watermark, never a mix."""

    def __init__(self, fs, path: str = "cdc/watermark"):
        self.fs = fs
        self.path = path

    def load(self) -> int:
        if not self.fs.exists(self.path):
            return 0
        raw = self.fs.read(self.path).decode().strip()
        return int(raw) if raw else 0

    def store(self, ts: int) -> None:
        self.fs.write(self.path, str(int(ts)).encode())


class CallbackSink:
    def __init__(self, fn: Callable):
        self.fn = fn

    def on_insert(self, table: str, rows: List[dict], pk_cols=None):
        self.fn("insert", table, rows)

    def on_delete(self, table: str, pk_rows: List[dict]):
        self.fn("delete", table, pk_rows)


class SQLSink:
    """Re-applies full DML to a downstream executor; deletes match on the
    upstream PK values shipped with the event."""

    def __init__(self, executor, target_table: Optional[str] = None):
        self.executor = executor     # Session or client.Connection
        self.target_table = target_table

    @staticmethod
    def _lit(v) -> str:
        return sql_literal(v)

    def on_insert(self, table: str, rows: List[dict], pk_cols=None):
        target = self.target_table or table
        if not rows:
            return
        if pk_cols:
            # at-least-once delivery: replayed inserts (backfill at the
            # watermark) must not duplicate-key the mirror — remove any
            # prior copy of these PKs first (delete-then-insert upsert)
            self.on_delete(table, [{c: r[c] for c in pk_cols}
                                   for r in rows])
        cols = list(rows[0].keys())
        values = ["(" + ", ".join(self._lit(r[c]) for c in cols) + ")"
                  for r in rows]
        sql = (f"insert into {target} ({', '.join(cols)}) values "
               + ", ".join(values))
        self.executor.execute(sql)

    @classmethod
    def _pred(cls, c: str, v) -> str:
        # SQL three-valued logic: `c = null` never matches
        return f"{c} is null" if v is None else f"{c} = {cls._lit(v)}"

    def on_delete(self, table: str, pk_rows: List[dict]):
        target = self.target_table or table
        if not pk_rows:
            return
        cols = list(pk_rows[0].keys())
        if len(cols) == 1 and all(r[cols[0]] is not None for r in pk_rows):
            c = cols[0]
            vals = ", ".join(self._lit(r[c]) for r in pk_rows)
            self.executor.execute(
                f"delete from {target} where {c} in ({vals})")
            return
        preds = ["(" + " and ".join(self._pred(c, r[c]) for c in cols) + ")"
                 for r in pk_rows]
        self.executor.execute(
            f"delete from {target} where " + " or ".join(preds))


class CdcTask:
    """reference: cdc task driven by taskservice; here a subscriber with a
    watermark, startable/stoppable."""

    def __init__(self, engine, table: str, sink, from_ts: int = 0):
        self.engine = engine
        self.table = table
        self.sink = sink
        self.watermark = from_ts
        # RLock: a sink that writes back into the same engine re-enters
        # _on_commit on this thread and must not self-deadlock
        self._lock = san.rlock("CdcTask._lock")
        self._cv = san.condition(self._lock)
        #: live deliveries currently running OUTSIDE the lock (see
        #: _apply_event); backfill waits for them to drain before its
        #: replay so the sink never sees two concurrent callers
        self._inflight = 0
        # backfill-in-progress queue: live events arriving mid-backfill
        # are deferred, NOT applied (a live DELETE applied before its
        # row's backfill INSERT replays would be resurrected by that
        # INSERT) and NOT blocked on (holding the lock across the whole
        # backfill would ABBA-deadlock against the engine commit lock
        # when the sink writes into the same engine)
        self._buffering = False
        self._buffer: List[tuple] = []
        self._active = False
        self._path = "live"      # mo_cdc_events_total delivery path
        self._wm_key: Optional[str] = None

    def start(self) -> "CdcTask":
        if not self._active:
            self._active = True
            self.engine.subscribe(self._on_commit)
            # pin this sink's replay history: the merge scheduler's
            # fence GC holds any compaction fence of this table until
            # our watermark has caught up past it (delta-aware GC)
            reg = getattr(self.engine, "register_watermark", None)
            if reg is not None:
                self._wm_key = f"cdc:{self.table}:{id(self)}"
                reg(self._wm_key, self.table, lambda: self.watermark)
        return self

    def stop(self):
        self._active = False
        self.engine.unsubscribe(self._on_commit)
        unreg = getattr(self.engine, "unregister_watermark", None)
        if unreg is not None and getattr(self, "_wm_key", None):
            unreg(self._wm_key)
            self._wm_key = None

    def _decode_segment(self, seg) -> List[dict]:
        t = self.engine.get_table(self.table)
        rows = []
        cols = [c for c, _ in t.meta.schema]
        for i in range(seg.n_rows):
            row = {}
            for c, dtype in t.meta.schema:
                if not seg.validity[c][i]:
                    row[c] = None
                elif dtype.is_varlen:
                    row[c] = t.dicts[c][int(seg.arrays[c][i])]
                elif dtype.is_vector:
                    row[c] = ("[" + ",".join(str(float(x))
                                             for x in seg.arrays[c][i]) + "]")
                else:
                    row[c] = seg.arrays[c][i].item()
            rows.append(row)
        return rows

    def _on_commit(self, commit_ts: int, table: str, kind: str, payload):
        if not self._active or table != self.table:
            return
        with self._cv:
            if self._buffering:
                self._buffer.append((commit_ts, kind, payload))
                return     # backfill drains the queue after its replay
            # one commit publishes several events with the SAME commit_ts
            # (deletes then inserts — update pairs); strict < keeps them
            # all and makes restart delivery at-least-once
            if commit_ts < self.watermark:
                return     # already shipped (restart replay)
            self._inflight += 1
        try:
            self._apply_event(commit_ts, kind, payload)
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def _apply_event(self, commit_ts: int, kind: str, payload) -> None:
        """Deliver one event to the sink, WITHOUT holding self._lock: a
        sink that writes into an engine takes that engine's commit lock,
        and holding the task lock across it closes the ABBA mosan's
        dynamic lock-order graph caught (committer: commit lock -> task
        lock in _on_commit; sink: task lock -> commit lock).  Sink calls
        stay SERIAL without the lock: live events arrive under the
        SOURCE engine's commit lock (one at a time), and a backfill
        first arms buffering (queueing new arrivals) then waits out any
        delivery already in flight (_inflight) before replaying."""
        from matrixone_tpu.utils import metrics as M
        if kind == "insert":
            pk = self.engine.get_table(self.table).meta.primary_key
            self.sink.on_insert(self.table, self._decode_segment(payload),
                                pk_cols=pk or None)
            M.cdc_events.inc(path=self._path, kind="insert")
        elif kind == "delete":
            self.sink.on_delete(self.table, self._decode_pk_rows(
                np.asarray(payload, np.int64)))
            M.cdc_events.inc(path=self._path, kind="delete")
        with self._lock:
            self.watermark = max(self.watermark, commit_ts)

    def _decode_pk_rows(self, gids: "np.ndarray") -> List[dict]:
        """PK values for deleted rows (segments still hold the data —
        tombstones never erase it). Tables without a PK ship all columns
        as the row identity."""
        t = self.engine.get_table(self.table)
        cols = t.meta.primary_key or [c for c, _ in t.meta.schema]
        arrays, validity = t.fetch_rows(np.asarray(gids, np.int64), cols)
        sd = dict(t.meta.schema)
        rows = []
        for i in range(len(gids)):
            row = {}
            for c in cols:
                if not validity[c][i]:
                    row[c] = None
                elif sd[c].is_varlen:
                    row[c] = t.dicts[c][int(arrays[c][i])]
                else:
                    row[c] = arrays[c][i].item()
            rows.append(row)
        return rows

    def backfill(self, from_ts: Optional[int] = None) -> None:
        """Ship committed changes past the watermark from MVCC state (the
        restart/resume path: no retained event stream needed). Events
        replay in commit-ts order, deletes before inserts at equal ts —
        the live ordering (an UPDATE is delete+insert at one ts).

        `from_ts` pins the replay start: a caller that subscribed live
        BEFORE backfilling passes the pre-subscribe watermark, so a live
        commit that advanced the watermark in between cannot make
        backfill skip history (duplicates are fine — delivery is
        at-least-once and PK sinks upsert)."""
        was_active = self._active
        self._active = True      # _on_commit delivers only when active
        try:
            self._backfill_events(self.watermark if from_ts is None
                                  else from_ts)
        finally:
            self._active = was_active

    def _backfill_events(self, from_ts: int) -> None:
        """Live commits must observably serialize AFTER the whole
        backfill: a live DELETE applied mid-backfill for a row whose
        backfill INSERT has not replayed yet would be a no-op, and the
        later replayed INSERT would resurrect the deleted row
        permanently.  Achieved by buffering (not blocking): the event
        list is built with buffering armed, the replay runs with the
        lock taken per event, and arrivals queue in _buffer — drained in
        arrival order once the replay finishes.  Duplicates between the
        list and the queue are fine (at-least-once, PK sinks upsert)."""
        with self._cv:
            self._buffering = True
            # wait out any live delivery that passed its buffering check
            # before we armed it — the sink must never see two callers
            # (bounded wait: a wedged sink must not wedge backfill too)
            deadline = time.monotonic() + 30.0
            while self._inflight > 0 and time.monotonic() < deadline:
                self._cv.wait(timeout=1.0)
            from matrixone_tpu.utils import metrics as M
            t = self.engine.get_table(self.table)
            floor = getattr(t, "delta_floor", 0)
            fences = getattr(t, "fences", None) or []
            if 0 < from_ts <= floor:
                # DEGRADE RUNG: the snapshot fence that held this
                # window's history was GC'd (no consumer was registered
                # to pin it).  The deltas between from_ts and the floor
                # are gone — silent divergence is worse than a loud stop,
                # so the sink must be re-seeded (backfill from 0 replays
                # the full live state).  A merge whose fence is still
                # held does NOT land here: delta_events replays it
                # exactly-once through the fence windows.
                M.cdc_backfills.inc(outcome="refused")
                raise ValueError(
                    f"cannot resume CDC on {self.table!r} from "
                    f"{from_ts}: the merge fence below {floor} was "
                    f"GC'd and the deltas compacted away; re-seed the "
                    f"sink (backfill from 0)")
            if from_ts == 0:
                M.cdc_backfills.inc(outcome="seed")
            elif fences and from_ts <= fences[-1].merge_ts:
                M.cdc_backfills.inc(outcome="fenced")
            else:
                M.cdc_backfills.inc(outcome="live")
            events = delta_events(self.engine, self.table, from_ts)
        try:
            for ts, kind, payload in events:
                self._replay_event(ts, kind, payload)
        finally:
            try:
                while True:
                    with self._cv:
                        if not self._buffer:
                            break
                        queued = self._buffer
                        self._buffer = []
                    # apply OUTSIDE the lock (see _apply_event):
                    # arrivals during this batch keep queueing
                    # (_buffering is still True), so the next loop turn
                    # picks them up
                    for ts, kind, payload in queued:
                        self._apply_event(ts, kind, payload)
            finally:
                # ANY exit — including a sink error mid-drain — must
                # unbuffer, or every future live event queues forever;
                # events stranded in _buffer stay recoverable because
                # the watermark never advanced past them (re-backfill
                # replays them, at-least-once)
                with self._cv:
                    self._buffering = False

    def _replay_event(self, commit_ts: int, kind: str, payload) -> None:
        """Deliver one backfill event regardless of the current watermark
        (which a live commit may have advanced past this event)."""
        self._path = "backfill"
        try:
            self._apply_event(commit_ts, kind, payload)
        finally:
            self._path = "live"

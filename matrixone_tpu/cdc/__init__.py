"""Change data capture (reference: pkg/cdc, 33k LoC — redesigned on the
engine's logtail subscriber hook).

A CdcTask subscribes to one table's commit stream and forwards decoded
changes (insert rows as python dicts, deletes as row-id lists) to a sink,
tracking a watermark (last shipped commit_ts) so restarts resume without
loss — events at or below the watermark are skipped on replay.

Sinks:
  * CallbackSink  — python callable (tests, embedding)
  * SQLSink       — re-applies changes to a downstream table over any
                    Session-like executor (a second engine, or a remote
                    MOServer via matrixone_tpu.client) — the reference's
                    MySQL sinker (cdc/sinker_v2)
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np


class CallbackSink:
    def __init__(self, fn: Callable):
        self.fn = fn

    def on_insert(self, table: str, rows: List[dict]):
        self.fn("insert", table, rows)

    def on_delete(self, table: str, gids: List[int]):
        self.fn("delete", table, gids)


class SQLSink:
    """Re-applies inserts to a downstream executor (deletes need a PK
    mapping and land with PK-aware DML in a later round)."""

    def __init__(self, executor, target_table: Optional[str] = None):
        self.executor = executor     # Session or client.Connection
        self.target_table = target_table

    def on_insert(self, table: str, rows: List[dict]):
        target = self.target_table or table
        if not rows:
            return
        cols = list(rows[0].keys())
        values = []
        for r in rows:
            parts = []
            for c in cols:
                v = r[c]
                if v is None:
                    parts.append("null")
                elif isinstance(v, str):
                    parts.append("'" + v.replace("'", "''") + "'")
                else:
                    parts.append(str(v))
            values.append("(" + ", ".join(parts) + ")")
        sql = (f"insert into {target} ({', '.join(cols)}) values "
               + ", ".join(values))
        self.executor.execute(sql)

    def on_delete(self, table: str, gids: List[int]):
        pass   # PK-mapped deletes: future round


class CdcTask:
    """reference: cdc task driven by taskservice; here a subscriber with a
    watermark, startable/stoppable."""

    def __init__(self, engine, table: str, sink, from_ts: int = 0):
        self.engine = engine
        self.table = table
        self.sink = sink
        self.watermark = from_ts
        self._lock = threading.Lock()
        self._active = False

    def start(self) -> "CdcTask":
        if not self._active:
            self._active = True
            self.engine.subscribe(self._on_commit)
        return self

    def stop(self):
        self._active = False
        self.engine.unsubscribe(self._on_commit)

    def _decode_segment(self, seg) -> List[dict]:
        t = self.engine.get_table(self.table)
        rows = []
        cols = [c for c, _ in t.meta.schema]
        for i in range(seg.n_rows):
            row = {}
            for c, dtype in t.meta.schema:
                if not seg.validity[c][i]:
                    row[c] = None
                elif dtype.is_varlen:
                    row[c] = t.dicts[c][int(seg.arrays[c][i])]
                elif dtype.is_vector:
                    row[c] = ("[" + ",".join(str(float(x))
                                             for x in seg.arrays[c][i]) + "]")
                else:
                    row[c] = seg.arrays[c][i].item()
            rows.append(row)
        return rows

    def _on_commit(self, commit_ts: int, table: str, kind: str, payload):
        if not self._active or table != self.table:
            return
        with self._lock:
            # one commit publishes several events with the SAME commit_ts
            # (inserts then deletes); strict < keeps them all and makes
            # restart delivery at-least-once from the watermark
            if commit_ts < self.watermark:
                return     # already shipped (restart replay)
            if kind == "insert":
                self.sink.on_insert(table, self._decode_segment(payload))
            elif kind == "delete":
                self.sink.on_delete(
                    table, np.asarray(payload).tolist())
            self.watermark = commit_ts

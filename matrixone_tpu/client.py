"""Python client SDK speaking the MySQL wire protocol
(reference: clients/python SDK + any stock MySQL connector).

    conn = matrixone_tpu.client.connect(port=6001)
    cols, rows = conn.query("select 1 + 1")
    conn.execute("insert into t values (1)")
    conn.close()
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple


class MySQLError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code


class Connection:
    def __init__(self, host: str = "127.0.0.1", port: int = 6001,
                 user: str = "root", password: str = "",
                 database: str = ""):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.seq = 0
        self._handshake(user, database)

    # ---- framing
    def _send(self, payload: bytes):
        header = struct.pack("<I", len(payload))[:3] + bytes([self.seq & 0xFF])
        self.sock.sendall(header + payload)
        self.seq += 1

    def _recv(self) -> bytes:
        header = self._recv_n(4)
        length = int.from_bytes(header[:3], "little")
        self.seq = header[3] + 1
        return self._recv_n(length)

    def _recv_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("server closed connection")
            buf += part
        return buf

    # ---- lenenc decoding
    @staticmethod
    def _lenenc(data: bytes, pos: int) -> Tuple[Optional[int], int]:
        b0 = data[pos]
        if b0 < 0xFB:
            return b0, pos + 1
        if b0 == 0xFB:
            return None, pos + 1          # NULL
        if b0 == 0xFC:
            return int.from_bytes(data[pos + 1:pos + 3], "little"), pos + 3
        if b0 == 0xFD:
            return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
        return int.from_bytes(data[pos + 1:pos + 9], "little"), pos + 9

    # ---- handshake
    def _handshake(self, user: str, database: str):
        greeting = self._recv()
        assert greeting[0] == 10, "unsupported protocol"
        caps = 0x0200 | 0x8000 | 0x00200000   # 41 + secure conn + plugin auth
        if database:
            caps |= 0x8                        # CLIENT_CONNECT_WITH_DB
        payload = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                   + bytes([0x21]) + b"\x00" * 23
                   + user.encode() + b"\x00"
                   + bytes([0])                      # empty auth response
                   + (database.encode() + b"\x00" if database else b""))
        self._send(payload)
        resp = self._recv()
        if resp[0] == 0xFF:
            raise self._err(resp)

    @staticmethod
    def _err(payload: bytes) -> MySQLError:
        code = int.from_bytes(payload[1:3], "little")
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):
            msg = msg[6:]
        return MySQLError(code, msg)

    # ---- commands
    def query(self, sql: str) -> Tuple[List[str], List[tuple]]:
        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:          # OK packet (no resultset)
            return [], []
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._recv()
            pos = 0
            parts = []
            for _f in range(6):       # catalog schema table org_table name org_name
                ln, pos = self._lenenc(col, pos)
                parts.append(col[pos:pos + (ln or 0)])
                pos += ln or 0
            names.append(parts[4].decode())
        eof = self._recv()            # EOF after columns
        rows = []
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            pos = 0
            row = []
            for _ in range(ncols):
                ln, pos = self._lenenc(pkt, pos)
                if ln is None:
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + ln].decode())
                    pos += ln
            rows.append(tuple(row))
        return names, rows

    def execute(self, sql: str) -> int:
        """Run a statement; returns affected rows (0 for resultsets)."""
        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:
            affected, _ = self._lenenc(first, 1)
            return affected or 0
        # drain the resultset
        ncols, _ = self._lenenc(first, 0)
        for _ in range(ncols):
            self._recv()
        self._recv()
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            if pkt[0] == 0xFE and len(pkt) < 9:
                return 0

    def ping(self) -> bool:
        self.seq = 0
        self._send(b"\x0e")
        return self._recv()[0] == 0x00

    def close(self):
        try:
            self.seq = 0
            self._send(b"\x01")
        except OSError:
            pass
        self.sock.close()


def connect(**kwargs) -> Connection:
    return Connection(**kwargs)

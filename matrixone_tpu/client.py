"""Python client SDK speaking the MySQL wire protocol
(reference: clients/python SDK + any stock MySQL connector).

    conn = matrixone_tpu.client.connect(port=6001)
    cols, rows = conn.query("select 1 + 1")
    conn.execute("insert into t values (1)")
    conn.close()
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple


class MySQLError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"({code}) {message}")
        self.code = code


def _lenenc_bytes(raw: bytes) -> bytes:
    if len(raw) < 251:
        return bytes([len(raw)]) + raw
    if len(raw) < 1 << 16:
        return b"\xfc" + struct.pack("<H", len(raw)) + raw
    if len(raw) < 1 << 24:
        return b"\xfd" + struct.pack("<I", len(raw))[:3] + raw
    return b"\xfe" + struct.pack("<Q", len(raw)) + raw


def native_password_scramble(password: str, nonce: bytes) -> bytes:
    """Client-side mysql_native_password response:
    SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw))). Lives here (stdlib-only)
    so the thin client never imports the server/engine stack."""
    import hashlib
    if not password:
        return b""
    s1 = hashlib.sha1(password.encode()).digest()
    s2 = hashlib.sha1(s1).digest()
    mix = hashlib.sha1(nonce + s2).digest()
    return bytes(a ^ b for a, b in zip(s1, mix))


class Connection:
    def __init__(self, host: str = "127.0.0.1", port: int = 6001,
                 user: str = "root", password: str = "",
                 database: str = "", timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.seq = 0
        self._handshake(user, password, database)

    # ---- framing (payloads >= 16MB span multiple packets)
    def _send(self, payload: bytes):
        while True:
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            header = (struct.pack("<I", len(chunk))[:3]
                      + bytes([self.seq & 0xFF]))
            self.sock.sendall(header + chunk)
            self.seq += 1
            if len(chunk) < 0xFFFFFF:
                return

    def _recv(self) -> bytes:
        payload = b""
        while True:
            header = self._recv_n(4)
            length = int.from_bytes(header[:3], "little")
            self.seq = header[3] + 1
            payload += self._recv_n(length)
            if length < 0xFFFFFF:
                return payload

    def _recv_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                raise ConnectionError("server closed connection")
            buf += part
        return buf

    # ---- lenenc decoding
    @staticmethod
    def _lenenc(data: bytes, pos: int) -> Tuple[Optional[int], int]:
        b0 = data[pos]
        if b0 < 0xFB:
            return b0, pos + 1
        if b0 == 0xFB:
            return None, pos + 1          # NULL
        if b0 == 0xFC:
            return int.from_bytes(data[pos + 1:pos + 3], "little"), pos + 3
        if b0 == 0xFD:
            return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
        return int.from_bytes(data[pos + 1:pos + 9], "little"), pos + 9

    # ---- handshake
    @staticmethod
    def _nonce_from_greeting(greeting: bytes) -> bytes:
        """Extract the 20-byte auth nonce from a HandshakeV10 packet."""
        pos = 1
        pos = greeting.index(b"\x00", pos) + 1       # server version
        pos += 4                                     # connection id
        part1 = greeting[pos:pos + 8]
        pos += 8 + 1                                 # nonce part 1 + filler
        pos += 2 + 1 + 2 + 2 + 1 + 10                # caps/charset/status/len
        part2 = greeting[pos:pos + 12]
        return part1 + part2

    def _handshake(self, user: str, password: str, database: str):
        greeting = self._recv()
        assert greeting[0] == 10, "unsupported protocol"
        nonce = self._nonce_from_greeting(greeting)
        auth = native_password_scramble(password, nonce)
        caps = 0x0200 | 0x8000 | 0x00200000   # 41 + secure conn + plugin auth
        if database:
            caps |= 0x8                        # CLIENT_CONNECT_WITH_DB
        payload = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                   + bytes([0x21]) + b"\x00" * 23
                   + user.encode() + b"\x00"
                   + bytes([len(auth)]) + auth
                   + (database.encode() + b"\x00" if database else b"")
                   + b"mysql_native_password\x00")
        self._send(payload)
        resp = self._recv()
        if resp[0] == 0xFF:
            raise self._err(resp)

    @staticmethod
    def _err(payload: bytes) -> MySQLError:
        code = int.from_bytes(payload[1:3], "little")
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):
            msg = msg[6:]
        return MySQLError(code, msg)

    # ---- commands
    def query(self, sql: str) -> Tuple[List[str], List[tuple]]:
        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:          # OK packet (no resultset)
            return [], []
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._recv()
            pos = 0
            parts = []
            for _f in range(6):       # catalog schema table org_table name org_name
                ln, pos = self._lenenc(col, pos)
                parts.append(col[pos:pos + (ln or 0)])
                pos += ln or 0
            names.append(parts[4].decode())
        eof = self._recv()            # EOF after columns
        rows = []
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            pos = 0
            row = []
            for _ in range(ncols):
                ln, pos = self._lenenc(pkt, pos)
                if ln is None:
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + ln].decode())
                    pos += ln
            rows.append(tuple(row))
        return names, rows

    def execute(self, sql: str) -> int:
        """Run a statement; returns affected rows (0 for resultsets)."""
        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:
            affected, _ = self._lenenc(first, 1)
            return affected or 0
        # drain the resultset
        ncols, _ = self._lenenc(first, 0)
        for _ in range(ncols):
            self._recv()
        self._recv()
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            if pkt[0] == 0xFE and len(pkt) < 9:
                return 0

    # ---- prepared statements (binary protocol)
    def prepare(self, sql: str) -> "PreparedStatement":
        self.seq = 0
        self._send(b"\x16" + sql.encode())
        ok = self._recv()
        if ok[0] == 0xFF:
            raise self._err(ok)
        stmt_id = int.from_bytes(ok[1:5], "little")
        n_cols = int.from_bytes(ok[5:7], "little")
        n_params = int.from_bytes(ok[7:9], "little")
        for _ in range(n_params):
            self._recv()                  # param definitions
        if n_params:
            self._recv()                  # EOF
        for _ in range(n_cols):
            self._recv()
        if n_cols:
            self._recv()
        return PreparedStatement(self, stmt_id, n_params)

    def _execute_prepared(self, stmt_id: int, params: list):
        body = (b"\x17" + struct.pack("<I", stmt_id) + b"\x00"
                + struct.pack("<I", 1))
        n = len(params)
        if n:
            nullmap = bytearray((n + 7) // 8)
            types = b""
            values = b""
            for i, v in enumerate(params):
                if v is None:
                    nullmap[i // 8] |= 1 << (i % 8)
                    types += bytes([6, 0])            # MYSQL_TYPE_NULL
                elif isinstance(v, bool):
                    types += bytes([1, 0])
                    values += bytes([int(v)])
                elif isinstance(v, int):
                    types += bytes([8, 0])            # LONGLONG
                    values += struct.pack("<q", v)
                elif isinstance(v, float):
                    types += bytes([5, 0])            # DOUBLE
                    values += struct.pack("<d", v)
                else:
                    import datetime
                    if isinstance(v, datetime.datetime):
                        types += bytes([12, 0])
                        values += bytes([7]) + struct.pack(
                            "<HBBBBB", v.year, v.month, v.day,
                            v.hour, v.minute, v.second)
                    elif isinstance(v, datetime.date):
                        types += bytes([10, 0])
                        values += bytes([4]) + struct.pack(
                            "<HBB", v.year, v.month, v.day)
                    else:
                        raw = (v if isinstance(v, bytes)
                               else str(v).encode())
                        types += bytes([253, 0])      # VAR_STRING
                        values += _lenenc_bytes(raw)
            body += bytes(nullmap) + b"\x01" + types + values
        self.seq = 0
        self._send(body)
        return self._read_binary_result()

    def _read_binary_result(self):
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:
            affected, _ = self._lenenc(first, 1)
            return [], [], affected or 0
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._recv()
            pos = 0
            parts = []
            for _f in range(6):
                ln, pos = self._lenenc(col, pos)
                parts.append(col[pos:pos + (ln or 0)])
                pos += ln or 0
            names.append(parts[4].decode())
        self._recv()                      # EOF after columns
        rows = []
        nm_len = (ncols + 2 + 7) // 8
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            nullmap = pkt[1:1 + nm_len]
            pos = 1 + nm_len
            row = []
            for i in range(ncols):
                if nullmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                    row.append(None)
                    continue
                ln, pos = self._lenenc(pkt, pos)
                row.append(pkt[pos:pos + (ln or 0)].decode())
                pos += ln or 0
            rows.append(tuple(row))
        return names, rows, 0

    def ping(self) -> bool:
        self.seq = 0
        self._send(b"\x0e")
        return self._recv()[0] == 0x00

    def close(self):
        try:
            self.seq = 0
            self._send(b"\x01")
        except OSError:
            pass
        self.sock.close()


class PreparedStatement:
    """Client handle for a server-side prepared statement (binary
    protocol). execute(*params) -> (names, rows, affected)."""

    def __init__(self, conn: Connection, stmt_id: int, n_params: int):
        self.conn = conn
        self.stmt_id = stmt_id
        self.n_params = n_params

    def execute(self, *params):
        if len(params) != self.n_params:
            raise ValueError(
                f"statement takes {self.n_params} parameters, got "
                f"{len(params)}")
        return self.conn._execute_prepared(self.stmt_id, list(params))

    def close(self):
        try:
            self.conn.seq = 0
            self.conn._send(b"\x19" + struct.pack("<I", self.stmt_id))
        except OSError:
            pass


def connect(**kwargs) -> Connection:
    return Connection(**kwargs)

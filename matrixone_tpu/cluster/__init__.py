"""CN/TN split: the reference's defining cluster shape, TPU-native.

Reference analogue (what to match, not how):
  * TN — one process owns storage, the commit pipeline, WAL, checkpoints
    (`pkg/tnservice`, `pkg/vm/engine/tae`, tae/rpc/handle.go:547
    HandleCommit) and generates the logtail push stream
    (tae/logtail/service/server.go:192);
  * CN — N stateless processes hold logtail-replayed partition state and
    serve snapshot reads merging that state with shared-storage objects,
    never touching the TN on the read path
    (`pkg/vm/engine/disttae`, disttae/logtail_consumer.go:296).

Redesign here: the TN's WAL record stream IS the logtail (one
serialization, two consumers: durability + replication). A CN bootstraps
from the shared checkpoint manifest + objectio objects, subscribes from
its checkpoint ts, and applies records with the same WalApplier the
restart replay uses. Writes from a CN ship the txn workspace to the TN
(commit RPC); read-your-writes holds until the logtail catches up to the
returned commit ts (the waitCanServeTableSnapshot gate,
disttae/logtail_consumer.go:389).
"""

from matrixone_tpu.cluster.cn import (CNService, LogtailConsumer,
                                      RemoteCatalog, ReplicaBrokenError)
from matrixone_tpu.cluster.tn import TNService

__all__ = ["TNService", "CNService", "LogtailConsumer", "RemoteCatalog",
           "ReplicaBrokenError"]

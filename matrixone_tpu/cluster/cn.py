"""CN service: stateless compute node over logtail-replayed state.

Reference analogue: `pkg/vm/engine/disttae` — the CN keeps per-table
partition state replayed from the TN's logtail push stream
(disttae/logtail_consumer.go:296 PushClient.init / apply loop), serves
snapshot reads merging that state with shared-storage objects, ships its
txn workspace to the TN at commit (txn/rpc CN->TN), and gates
read-your-writes on the logtail catching up to the commit ts
(logtail_consumer.go:389 waitCanServeTableSnapshot).

Redesign: the replica is a full `Engine` built by `open_checkpoint`
(manifest + objectio objects from shared storage, no WAL) and advanced
record-by-record by `WalApplier` — the exact code path a TN restart
replay uses, so CN state can never diverge from what a recovery would
rebuild.  `RemoteCatalog` exposes the whole Engine surface to an
unmodified `frontend.Session`: reads hit the replica, mutations become
TN RPCs.
"""

from __future__ import annotations

import socket
import threading

from matrixone_tpu.utils import san
from matrixone_tpu.utils.lifecycle import ServiceThreads
import time
from typing import Dict, List, Optional

import numpy as np

from matrixone_tpu.cluster.rpc import (ERR_TYPES, RpcClient,
                                       backoff_delay, deadline_scope,
                                       new_rid, pack_blobs,
                                       parse_addr as _parse_addr)
from matrixone_tpu.utils.fault import INJECTOR
from matrixone_tpu.logservice.replicated import _recv_msg, _send_msg
from matrixone_tpu.storage import arrowio, wal as walmod
from matrixone_tpu.storage.engine import (Engine, WalApplier,
                                          schema_to_json)
from matrixone_tpu.storage.fileservice import FileService, LocalFS

#: CN->TN request/response channel (shared framing, cluster/rpc.py)
_TNClient = RpcClient


class ReplicaBrokenError(RuntimeError):
    """The logtail circuit breaker tripped: the replica is quarantined
    (its state may be stale) and refuses to serve reads or gate commits
    rather than silently answering from frozen data."""


class LogtailConsumer:
    """Subscribe to the TN's logtail and apply records into the replica.

    Resubscribes from `applied_ts` after a TN restart (the CNs-resubscribe
    half of the reference's logtail client). `wait_ts` is the
    read-your-writes gate.

    Circuit breaker (VERDICT r3 weak #7): an apply error used to spin a
    resubscribe loop forever while reads silently served stale data. Now
    repeated failures without progress first trigger ONE full-resync
    self-heal (drop partial state, rebuild from the manifest); if the
    failure persists the consumer marks the replica `broken`, stops, and
    every read/gate raises ReplicaBrokenError."""

    MAX_STRIKES = 3

    def __init__(self, replica: Engine, addr):
        self.replica = replica
        self.addr = _parse_addr(addr)
        self.applied_ts = replica._ckpt_ts
        self.last_error: Optional[str] = None
        self.strikes = 0
        self.broken = False
        self._healed_once = False
        self._cv = san.condition("LogtailConsumer._cv")
        self._caught_up = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, timeout: float = 60.0) -> "LogtailConsumer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._caught_up.wait(timeout):
            raise TimeoutError("logtail subscription never caught up")
        return self

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------ loop
    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            try:
                self._consume_once()
                attempt = 0
            except (OSError, ConnectionError):
                # TN down or restarting: resubscribe from what we have.
                # Jittered backoff, not a flat tick — every CN loses the
                # stream at the same instant a TN restarts, and a fixed
                # retry interval re-synchronizes the whole fleet's dials
                attempt += 1
                time.sleep(backoff_delay(attempt))
            except Exception as e:            # noqa: BLE001
                import sys
                self.last_error = repr(e)
                self.strikes += 1
                print(f"[cn-logtail] apply error (strike "
                      f"{self.strikes}/{self.MAX_STRIKES}): {e!r}",
                      file=sys.stderr, flush=True)
                if self.strikes >= self.MAX_STRIKES:
                    if not self._healed_once:
                        # self-heal: a poisoned partial state (half-applied
                        # group, stale table layout) is discarded and the
                        # replica rebuilt from the durable manifest
                        self._healed_once = True
                        self.strikes = 0
                        try:
                            self._resync_full()
                            with self._cv:
                                self.applied_ts = max(self.applied_ts,
                                                      self.replica._ckpt_ts)
                        except Exception as e2:   # noqa: BLE001
                            self.last_error = repr(e2)
                            self.broken = True
                            print("[cn-logtail] BREAKER OPEN (resync "
                                  f"failed): {e2!r}", file=sys.stderr,
                                  flush=True)
                            break
                    else:
                        # deterministic poison: quarantine instead of
                        # spinning while reads serve frozen data
                        self.broken = True
                        print(f"[cn-logtail] BREAKER OPEN: {e!r}",
                              file=sys.stderr, flush=True)
                        break
                attempt += 1
                time.sleep(backoff_delay(attempt))
        if self.broken:
            with self._cv:         # wake any wait_ts blockers to fail
                self._cv.notify_all()

    def _consume_once(self) -> None:
        if INJECTOR.trigger("logtail.subscribe") == "drop":
            raise ConnectionError(
                "fault injected: logtail subscription dropped")
        sock = socket.create_connection(self.addr, timeout=30.0)
        # molint: disable=deadline-propagation -- poll TICK, not a
        # deadline: the recv loop below continues on socket.timeout so
        # the 1s value only bounds how often _stop is re-checked
        sock.settimeout(1.0)
        try:
            _send_msg(sock, {"op": "subscribe", "from_ts": self.applied_ts})
            applier = WalApplier(self.replica, skip_ts=self.applied_ts)
            while not self._stop.is_set():
                try:
                    h, b = _recv_msg(sock)
                except socket.timeout:
                    continue
                self._apply(applier, h, b)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _apply(self, applier: WalApplier, h: dict, b: bytes) -> None:
        op = h.get("op")
        if op == "__caught_up__":
            # the marker's ts is the TN frontier at subscribe time:
            # every commit <= it was in the backlog just applied, so the
            # frontier itself is applied (wait_ts targets become
            # reachable on an idle cluster)
            self._advance(h.get("ts", 0), commit=True)
            self._caught_up.set()
            return
        if op == "__frontier__":
            self._advance(h.get("ts", 0), commit=True)
            return
        rep = self.replica
        if op == "__resync__":
            # our applied_ts predates the TN's last checkpoint: the
            # records in the gap were truncated — rebuild the whole
            # replica from the manifest, then stream from ckpt ts
            self._resync_full()
            self._advance(h.get("ts", 0), commit=True)
            return
        if op == "merge_table":
            self._resync_table(h["name"])
            self._advance(h.get("ts", 0), commit=True)
            return
        with rep._commit_lock:
            ts = applier.apply(h, b)
        if ts is not None:
            self._advance(ts, commit=True)
        elif op not in ("insert", "delete") and h.get("ts"):
            self._advance(h["ts"], commit=False)

    def _advance(self, ts: int, commit: bool) -> None:
        rep = self.replica
        self.strikes = 0            # progress: the stream is healthy
        self._healed_once = False
        with self._cv:
            if commit and ts > rep.committed_ts:
                rep.committed_ts = ts
            rep.hlc.update(ts)
            self.applied_ts = max(self.applied_ts, ts)
            self._cv.notify_all()
        from matrixone_tpu.utils.sync import notify_waiters
        notify_waiters()

    def _resync_table(self, name: str) -> None:
        """A TN merge rewrote the table's gids: rebuild from the fresh
        manifest (written before the merge record was appended)."""
        import json
        rep = self.replica
        manifest = json.loads(rep.fs.read("meta/manifest.json").decode())
        with rep._commit_lock:
            tm = manifest["tables"].get(name)
            if tm is not None:
                rep._load_manifest_table(name, tm, replace=True)
            else:
                rep.tables.pop(name, None)
            # the table's gids (or the table itself) just changed out
            # from under every cached plan/result pinned to them
            rep.ddl_gen += 1
            for ix in rep.indexes_on(name):
                ix.dirty = True     # gids changed under any local index

    def _resync_full(self) -> None:
        """Rebuild the whole replica from the latest manifest (the
        subscribe gap was truncated away)."""
        rep = self.replica
        with rep._commit_lock:
            rep.tables = {}
            rep.snapshots = {}
            rep.stages = {}
            rep.publications = {}
            rep.sources = set()
            rep.dynamic_tables = {}
            rep._load_checkpoint()
            # the whole catalog was swapped: every cached plan/result
            # keyed to the pre-resync shape is invalid
            rep.ddl_gen += 1
            for ix in rep.indexes.values():
                ix.dirty = True
            rep.committed_ts = max(rep.committed_ts, rep._ckpt_ts)

    # ------------------------------------------------------------ gate
    def wait_ts(self, ts: int, timeout: float = 30.0) -> None:
        with self._cv:
            if not self._cv.wait_for(
                    lambda: self.broken or self.applied_ts >= ts, timeout):
                raise TimeoutError(
                    f"logtail did not reach ts {ts} within {timeout}s "
                    f"(applied {self.applied_ts})")
            if self.broken and self.applied_ts < ts:
                raise ReplicaBrokenError(
                    f"logtail breaker open (last error: "
                    f"{self.last_error})")


class _TableProxy:
    """Replica table + write-path interception: auto-increment allocation
    is a TN RPC (pkg/incrservice — a per-CN counter would collide), and
    autocommit inserts ship to the TN commit pipeline."""

    def __init__(self, rc: "RemoteCatalog", t):
        object.__setattr__(self, "_rc", rc)
        object.__setattr__(self, "_t", t)

    def __getattr__(self, k):
        return getattr(object.__getattribute__(self, "_t"), k)

    def __setattr__(self, k, v):
        setattr(object.__getattribute__(self, "_t"), k, v)

    def allocate_auto(self, n: int) -> np.ndarray:
        resp = self._rc._call({"op": "alloc_auto",
                               "table": self._t.meta.name, "n": int(n)})
        return np.asarray(resp["vals"], np.int64)

    def observe_auto(self, values) -> None:
        vals = np.asarray(values).tolist()
        if vals:
            self._rc._call({"op": "observe_auto",
                            "table": self._t.meta.name, "vals": vals})

    def insert_batch(self, batch) -> int:
        arrays, validity = self._t.batch_to_arrays(batch)
        return self._rc.commit_write(self._t.meta.name, arrays, validity)

    def insert_numpy(self, arrays, validity=None, strings=None) -> int:
        t = self._t
        strings = strings or {}
        full, val = {}, {}
        n = None
        for col, dtype in t.meta.schema:
            if dtype.is_varlen:
                codes, cats = strings[col]
                arr = t.remap_codes(col, codes, cats)
            else:
                arr = np.asarray(arrays[col], dtype=dtype.np_dtype)
            if n is None:
                n = len(arr)
            full[col] = arr
            v = None if validity is None else validity.get(col)
            val[col] = v.copy() if v is not None else np.ones(n, np.bool_)
        return self._rc.commit_write(t.meta.name, full, val)


class RemoteCatalog:
    """The Engine surface for a CN session: reads -> replica, mutations ->
    TN RPC + logtail wait. An unmodified `frontend.Session` runs on it."""

    TXN_LEASE = 30.0

    def __init__(self, tn_addr, fs: Optional[FileService] = None,
                 data_dir: Optional[str] = None,
                 txn_lease: float = TXN_LEASE):
        if fs is None:
            fs = LocalFS(data_dir)
        self._replica = Engine.open_checkpoint(fs)
        self._client = _TNClient(tn_addr)
        self.consumer = LogtailConsumer(self._replica, tn_addr).start()
        # CN-local open-txn counter (fast path for merge forwarding);
        # the authoritative cluster-wide registry lives on the TN, fed
        # by txn_opened/txn_closed leases below.
        self.active_txns = 0
        self._txn_lease = txn_lease
        self._txn_tokens: Dict[int, str] = {}     # txn_id -> TN token
        self._txn_mu = san.lock("RemoteCatalog._txn_mu")
        self._closed = threading.Event()
        self._renewer = threading.Thread(target=self._renew_loop,
                                         daemon=True)
        self._renewer.start()

    def close(self) -> None:
        self._closed.set()
        # flush the replica-hosted statement recorder's buffered tail
        # (sessions hang it off the replica engine; see utils/trace.py)
        rep_close = getattr(self._replica, "close", None)
        if rep_close is not None:
            rep_close()
        self.consumer.stop()
        pool = getattr(self, "_frag_pool", None)
        if pool is not None:
            pool.close()
        self._client.close()

    # ----------------------------------------------------- txn registry
    def txn_opened(self, txn_id: int) -> None:
        """Engine hook (txn/client.TxnHandle): lease a token on the TN so
        merges defer cluster-wide while this txn is open."""
        resp = self._call({"op": "txn_begin", "lease": self._txn_lease})
        with self._txn_mu:
            self._txn_tokens[txn_id] = resp["token"]
            self.active_txns += 1

    def txn_closed(self, txn_id: int) -> None:
        with self._txn_mu:
            tok = self._txn_tokens.pop(txn_id, None)
            self.active_txns -= 1
        if tok is not None:
            try:
                self._call({"op": "txn_end", "token": tok})
            except (OSError, ConnectionError, ValueError):
                pass      # TN down: the lease expires on its own

    def _renew_loop(self) -> None:
        period = max(1.0, self._txn_lease / 3.0)
        while not self._closed.wait(period):
            with self._txn_mu:
                toks = list(self._txn_tokens.values())
            if toks:
                try:
                    self._call({"op": "txn_renew", "tokens": toks,
                                "lease": self._txn_lease})
                except (OSError, ConnectionError, ValueError):
                    pass  # transient: next tick retries within the lease

    # --------------------------------------------------------- plumbing
    def __getattr__(self, k):
        # reads and shared state (tables, committed_ts, hlc, locks, fs,
        # index_cache, _commit_lock, ...) come from the replica
        return getattr(self._replica, k)

    def _call(self, header: dict, blob: bytes = b"") -> dict:
        # every TN call carries an idempotency rid, minted ONCE per
        # logical call: a transport retry re-sends the SAME rid and the
        # TN's dedup cache replays the recorded response instead of
        # re-executing (write-safe retries — a mid-call disconnect on
        # commit can no longer double-apply)
        header = dict(header, rid=new_rid())
        resp, _ = self._client.call(header, blob)
        if not resp.get("ok"):
            err = resp.get("err", "TN error")
            raise ERR_TYPES.get(resp.get("etype"), ValueError)(err)
        return resp

    def _ddl(self, record: dict) -> dict:
        resp = self._call({"op": "ddl", "record": record})
        self.consumer.wait_ts(resp["applied_ts"])
        return resp

    def _check_breaker(self) -> None:
        if self.consumer.broken:
            raise ReplicaBrokenError(
                f"CN replica quarantined — logtail apply kept failing "
                f"(last error: {self.consumer.last_error})")

    def sync_frontier(self, timeout: float = 30.0) -> None:
        """Catch the replica up to the TN's CURRENT commit frontier
        (reference: disttae waitCanServeTableSnapshot,
        logtail_consumer.go:389 — reads gate on the logtail reaching
        the snapshot). Used on catalog misses: a table created through
        ANOTHER connection must be visible once the TN has it."""
        try:
            resp = self._call({"op": "ping"})
            self.consumer.wait_ts(resp["committed_ts"], timeout=timeout)
        except (OSError, ConnectionError, ValueError):
            pass                       # TN down: serve the local frontier

    def get_table(self, name: str):
        self._check_breaker()
        try:
            t = self._replica.get_table(name)
        except ValueError:
            # not here YET? close the replication gap once and retry —
            # "no such table" must mean the CLUSTER doesn't have it,
            # not that this replica is lagging
            self.sync_frontier()
            t = self._replica.get_table(name)
        return _TableProxy(self, t)

    def get_table_meta(self, name: str):
        self._check_breaker()
        try:
            return self._replica.get_table_meta(name)
        except ValueError:
            self.sync_frontier()
            return self._replica.get_table_meta(name)

    # ------------------------------------------------------------ writes
    def commit_write(self, table: str, arrays, validity) -> int:
        return self.commit_txn(None, {table: [(arrays, validity)]}, {})

    def commit_txn(self, snapshot_ts, inserts: Dict[str, list],
                   deletes: Dict[str, np.ndarray]) -> int:
        """Ship the workspace to the TN (txn/rpc sender -> tae/rpc
        HandleCommit). Varchar columns travel as Arrow dictionary arrays
        (batch-local codes + categories, built vectorized from the CN's
        dict) — CN and TN dictionaries evolve independently, so codes are
        remapped at the TN, never trusted across the wire."""
        tables, blobs = [], []
        for tname, segs in inserts.items():
            t = self._replica.get_table(tname)
            varlen = {c for c, d in t.meta.schema if d.is_varlen}
            for arrays, validity in segs:
                enc = {}
                for c, a in arrays.items():
                    if c in varlen:
                        enc[c] = arrowio.to_dict_encoded(
                            t.dicts[c], np.asarray(a),
                            np.asarray(validity[c]))
                    else:
                        enc[c] = np.asarray(a)
                blobs.append(walmod.arrays_to_arrow(enc, validity))
                tables.append(tname)
        header = {
            "op": "commit", "snapshot_ts": snapshot_ts, "tables": tables,
            "deletes": {t: np.asarray(g, np.int64).tolist()
                        for t, g in deletes.items()},
        }
        resp = self._call(header, pack_blobs(blobs))
        # read-your-writes: block until our own commit is applied locally
        self.consumer.wait_ts(resp["ts"])
        return resp["affected"]

    # --------------------------------------------------------------- ddl
    def create_table(self, meta, if_not_exists=False, log=True) -> None:
        self._ddl({
            "op": "create_table", "name": meta.name,
            "schema": schema_to_json(meta.schema),
            "pk": meta.primary_key, "auto": meta.auto_increment,
            "not_null": meta.not_null,
            "partition": (meta.partition.to_json()
                          if meta.partition is not None else None),
            "if_not_exists": if_not_exists})

    def drop_table(self, name: str, if_exists=False, log=True) -> None:
        if name not in self._replica.tables and if_exists:
            return
        self._ddl({"op": "drop_table", "name": name,
                   "if_exists": if_exists})

    def create_external(self, meta, location: str, fmt: str, log=True,
                        if_not_exists: bool = False,
                        snapshot=None) -> None:
        self._ddl({"op": "create_external", "name": meta.name,
                   "schema": schema_to_json(meta.schema),
                   "location": location, "fmt": fmt,
                   "snapshot": snapshot,
                   "if_not_exists": if_not_exists})

    def create_publication(self, name, tables, log=True) -> None:
        self._ddl({"op": "create_publication", "name": name,
                   "tables": list(tables)})

    def drop_publication(self, name, log=True) -> None:
        self._ddl({"op": "drop_publication", "name": name})

    def mark_source(self, name, log=True) -> None:
        self._ddl({"op": "mark_source", "name": name})

    def register_dynamic(self, name, sql, log=True) -> None:
        self._ddl({"op": "create_dynamic", "name": name, "sql": sql})

    def create_stage(self, name, url, log=True) -> None:
        self._ddl({"op": "create_stage", "name": name, "url": url})

    def drop_stage(self, name, log=True) -> None:
        self._ddl({"op": "drop_stage", "name": name})

    def alter_partition_drop(self, table, part, log=True) -> None:
        self._ddl({"op": "alter_partition_drop", "table": table,
                   "part": part})

    def drop_snapshot(self, name) -> None:
        self._ddl({"op": "drop_snapshot", "name": name})

    def create_snapshot(self, name) -> int:
        resp = self._call({"op": "create_snapshot", "name": name})
        self.consumer.wait_ts(resp["applied_ts"])
        return resp["ts"]

    def restore_table(self, table: str, ts: int) -> int:
        resp = self._call({"op": "restore_table", "table": table,
                           "ts": int(ts)})
        self.consumer.wait_ts(resp["applied_ts"])
        return resp["affected"]

    def merge_table(self, name: str, min_segments: int = 2,
                    checkpoint: bool = True) -> int:
        """Forwarded to the TN; the logtail merge record triggers a local
        resync.  Deferred (-2, same contract as Engine.merge_table) while
        ANY CN in the cluster has an open transaction: every open txn
        holds a leased token in the TN's registry (txn_opened above), and
        the TN's merge handler defers while live tokens exist — the
        cluster-wide guard the reference gets from TAE's central active-
        txn table.  The local check below is just a fast path."""
        if self.active_txns > 0:
            return -2
        resp = self._call({"op": "merge_table", "name": name,
                           "min_segments": min_segments})
        self.consumer.wait_ts(resp["applied_ts"])
        return resp["kept"]

    def checkpoint(self) -> None:
        self._call({"op": "checkpoint"})


class FragmentServer:
    """CN<->CN pipeline endpoint: executes shipped plan fragments against
    this CN's replica (reference: cnservice's pipeline RPC server +
    compile/remoterunServer.go decoding scopes from peer CNs)."""

    def __init__(self, catalog, port: int = 0):
        self.catalog = catalog
        self.frags_run = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(32)
        self._stopping = threading.Event()
        self._svc = ServiceThreads("mo-frag")

    def start(self) -> "FragmentServer":
        self._svc.spawn_accept(self._serve)
        return self

    def stop(self) -> None:
        self._stopping.set()
        # interrupt blocked accept/recv and JOIN everything with a
        # deadline (mosan leak checker gates abandoned threads)
        self._svc.shutdown(self._sock)

    def _serve(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._svc.spawn_handler(self._handle, conn)

    def _handle(self, conn: socket.socket) -> None:
        from matrixone_tpu.parallel.fragments import (execute_fragment,
                                                      run_shuffle_join,
                                                      run_shuffle_scan,
                                                      shuffle_store_for)
        try:
            while True:
                header, blob = _recv_msg(conn)
                op = header.get("op")
                if op == "ping":
                    _send_msg(conn, {"ok": True})
                    continue
                if op == "stats":
                    _send_msg(conn, {"ok": True,
                                     "frags_run": self.frags_run})
                    continue
                if op == "shuffle_put":
                    # a peer pushing its bucket of a repartitioned side
                    # (colexec/dispatch analogue)
                    shuffle_store_for(self.catalog).put(
                        str(header["shuffle_id"]), header["side"],
                        int(header["from"]), int(header["to"]), blob)
                    _send_msg(conn, {"ok": True})
                    continue
                if op == "shuffle_drop":
                    # coordinator-ordered cleanup of a failed shuffle
                    shuffle_store_for(self.catalog).drop_sid(
                        str(header["shuffle_id"]))
                    _send_msg(conn, {"ok": True})
                    continue
                if op != "run_fragment":
                    _send_msg(conn, {"ok": False, "err": f"bad op {op}"})
                    continue
                try:
                    kind = header.get("kind")
                    # propagate the caller's remaining budget into the
                    # fragment's own nested RPCs (shuffle pushes to
                    # peer CNs inherit the coordinator's deadline)
                    with deadline_scope(
                            ms=header.get("deadline_ms") or 180_000):
                        if kind == "shuffle_scan":
                            resp, rblob = run_shuffle_scan(self.catalog,
                                                           header)
                        elif kind == "shuffle_join":
                            resp, rblob = run_shuffle_join(self.catalog,
                                                           header)
                        else:
                            resp, rblob = execute_fragment(self.catalog,
                                                           header)
                    self.frags_run += 1
                except Exception as e:           # noqa: BLE001
                    resp, rblob = {"ok": False,
                                   "err": f"{type(e).__name__}: {e}"}, b""
                _send_msg(conn, resp, rblob)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class CNService:
    """One CN process: replica + logtail consumer + MySQL wire server +
    fragment endpoint for distributed scopes."""

    def __init__(self, tn_addr, fs: Optional[FileService] = None,
                 data_dir: Optional[str] = None, port: int = 0,
                 users: Optional[dict] = None, insecure: bool = True,
                 frag_port: int = 0, peers: Optional[list] = None):
        from matrixone_tpu.frontend.server import MOServer
        self.catalog = RemoteCatalog(tn_addr, fs=fs, data_dir=data_dir)
        self.fragments = FragmentServer(self.catalog, port=frag_port)
        if peers:
            self.catalog.dist_peers = list(peers)
        self.server = MOServer(engine=self.catalog, port=port,
                               users=users, insecure=insecure)

    def start(self) -> "CNService":
        self.fragments.start()
        self.server.start()
        return self

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def frag_port(self) -> int:
        return self.fragments.port

    def stop(self) -> None:
        self.server.stop()
        self.fragments.stop()
        self.catalog.close()


def main() -> None:
    import argparse
    import sys
    ap = argparse.ArgumentParser()
    ap.add_argument("--tn", required=True, help="host:port of the TN")
    ap.add_argument("--dir", required=True, help="shared storage dir")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--frag-port", type=int, default=0)
    ap.add_argument("--peers", default="",
                    help="comma-separated fragment endpoints of ALL "
                         "CNs (including this one) for distributed scopes")
    ap.add_argument("--keeper", default="",
                    help="comma-separated keeper endpoints to register "
                         "with and heartbeat (HAKeeper)")
    ap.add_argument("--insecure", type=int, default=1,
                    help="1 = accept any login (test default); 0 = "
                         "account/password auth via mo_user")
    args = ap.parse_args()
    peers = [p for p in args.peers.split(",") if p]
    cn = CNService(args.tn, data_dir=args.dir, port=args.port,
                   frag_port=args.frag_port, peers=peers,
                   insecure=bool(args.insecure)).start()
    if args.keeper:
        from matrixone_tpu.cluster.rpc import parse_addr
        from matrixone_tpu.hakeeper import HAClient
        HAClient([parse_addr(a) for a in args.keeper.split(",") if a],
                 "cn", f"cn-{cn.port}",
                 service_addr=f"127.0.0.1:{cn.port}").start()
    print(f"PORT {cn.port}", flush=True)
    print(f"FRAGPORT {cn.frag_port}", flush=True)
    sys.stdout.flush()
    threading.Event().wait()


if __name__ == "__main__":
    main()

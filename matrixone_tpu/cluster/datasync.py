"""Datasync: standby-cluster WAL shipping.

Reference analogue: `pkg/datasync` — a standby cluster consumes the
primary's log shard and re-applies it, so the standby can take over
after the primary site is lost. Redesign on this engine's shape: the
TN's logtail stream IS its WAL, so a StandbyAgent subscribes exactly
like a CN replica but with durability: every received record is
appended VERBATIM to the standby's own local WAL before it is applied,
and periodic checkpoints compact the standby's state into its own
manifest/objects. Promotion is then just opening the standby's data dir
as a TN (`TNService(data_dir=standby_dir)`) — the normal restart replay
(checkpoint + WAL tail) reconstructs everything the primary had acked
to the stream.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from matrixone_tpu.cluster.rpc import backoff_delay, parse_addr
from matrixone_tpu.logservice.replicated import _recv_msg, _send_msg
from matrixone_tpu.storage import wal as walmod
from matrixone_tpu.storage.engine import Engine, WalApplier
from matrixone_tpu.storage.fileservice import FileService, LocalFS


class StandbyAgent:
    """Consume a primary TN's logtail into a durable local standby.

    Unlike a CN replica (in-memory state over a shared checkpoint), the
    standby owns its own storage: records are journaled to ITS WAL
    before applying, so a standby crash replays locally and a primary
    loss promotes the standby dir into a full TN."""

    def __init__(self, tn_addr, fs: Optional[FileService] = None,
                 data_dir: Optional[str] = None,
                 checkpoint_every: int = 256):
        if fs is None:
            fs = LocalFS(data_dir)
        self.fs = fs
        self.addr = parse_addr(tn_addr)
        # restart path: resume from our own checkpoint + WAL tail
        self.engine = Engine.open(fs)
        self.checkpoint_every = checkpoint_every
        self.applied_ts = self._durable_position()
        self.records_since_ckpt = 0
        self.last_error: Optional[str] = None
        self._group: list = []
        self._caught_up = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle
    def start(self, timeout: float = 60.0) -> "StandbyAgent":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._caught_up.wait(timeout):
            # no half-dead agent: the consumer must stop before a caller
            # retries, or two engines would append to the same WAL
            self.stop()
            raise TimeoutError("standby never caught up with the primary")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -------------------------------------------------------- positioning
    def _durable_position(self) -> int:
        """Highest PRIMARY ts covered by durable standby state: the
        position file written at each checkpoint (our local ckpt_ts is a
        WALL-CLOCK stamp — trusting it would skip primary records under
        clock skew) plus the WAL tail's record ts (all primary ts)."""
        import json
        last = 0
        if self.fs.exists("meta/datasync_pos.json"):
            try:
                last = int(json.loads(
                    self.fs.read("meta/datasync_pos.json")))
            except (ValueError, TypeError):
                last = 0
        for h, _b in self.engine.wal.replay():
            last = max(last, h.get("ts", 0))
        return last

    def _persist_pos(self) -> None:
        """Write the durable position file. Must precede ANY operation
        that truncates the standby WAL (checkpoint, merge-triggered
        checkpoint): a crash between truncation and the next pos write
        would otherwise regress _durable_position() to a stale file with
        no WAL tail to make up the difference, resubscribe from an old
        ts, and re-apply records already baked into the checkpoint."""
        import json
        self.fs.write("meta/datasync_pos.json",
                      json.dumps(self.applied_ts).encode())

    def _checkpoint(self) -> None:
        """Checkpoint + persist the primary position it covers (written
        BEFORE the truncation so a crash between the two replays the
        tail instead of skipping it)."""
        self._persist_pos()
        self.engine.checkpoint()
        self.records_since_ckpt = 0

    # --------------------------------------------------------------- sync
    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            try:
                self._consume_once()
                attempt = 0
            except (OSError, ConnectionError):
                # primary down: hold position; promotion is the
                # operator's call (we ARE the recovery path).  Jittered
                # backoff so standbys don't re-dial in lockstep
                attempt += 1
                time.sleep(backoff_delay(attempt))
            except Exception as e:            # noqa: BLE001
                import sys
                self.last_error = repr(e)
                print(f"[datasync] apply error, recovering from local "
                      f"WAL and resubscribing: {e!r}", file=sys.stderr,
                      flush=True)
                # an error between journaling a group and applying it
                # leaves memory behind the WAL; rebuild in-memory state
                # from our durable truth so the journaled group is never
                # re-received (duplicate frames) nor lost
                try:
                    self.engine = Engine.open(self.fs)
                    self.applied_ts = self._durable_position()
                except Exception as e2:       # noqa: BLE001
                    self.last_error = repr(e2)
                attempt += 1
                time.sleep(backoff_delay(attempt))

    def _consume_once(self) -> None:
        sock = socket.create_connection(self.addr, timeout=30.0)
        # molint: disable=deadline-propagation -- poll TICK, not a
        # deadline: the recv loop continues on socket.timeout; the 1s
        # only bounds how often _stop is re-checked
        sock.settimeout(1.0)
        try:
            _send_msg(sock, {"op": "subscribe",
                             "from_ts": self.applied_ts})
            applier = WalApplier(self.engine, skip_ts=self.applied_ts)
            # journal at COMMIT boundaries only: a resubscribe mid-group
            # makes the primary resend the group's frames, and frames
            # already journaled individually would duplicate in our WAL
            # (duplicate rows after promotion) — so the group buffers
            # here and lands atomically with its commit record
            self._group = []
            while not self._stop.is_set():
                try:
                    h, b = _recv_msg(sock)
                except socket.timeout:
                    continue
                self._apply(applier, h, b)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _apply(self, applier: WalApplier, h: dict, b: bytes) -> None:
        op = h.get("op")
        if op == "__caught_up__":
            self._caught_up.set()
            return
        if op == "__frontier__":
            # heartbeat marker: advances the in-memory position only —
            # never journaled (there is nothing durable to replay)
            self._advance(h.get("ts", 0))
            return
        if op == "__resync__":
            # our position predates the primary's checkpoint: rebuild
            # from the primary's manifest is impossible here (separate
            # storage) — but the primary's stream starts at its ckpt, so
            # a standby that was down across a primary checkpoint must
            # re-seed. Re-seeding = full state copy; v1 surfaces it.
            raise RuntimeError(
                "standby lagged across a primary checkpoint; re-seed "
                "the standby from a fresh backup")
        if op == "merge_table":
            # the primary rewrote gids; mirror the compaction locally
            # from our OWN state (bit-equal row set, locally owned gids).
            # checkpoint=True truncates our WAL inside merge_table, so
            # the pos file must land first (see _persist_pos).  No outer
            # commit-lock wrap: merge_table takes merge-lock -> commit-
            # lock itself, and wrapping it inverts that order against
            # every scheduler/foreground merge (mosan-caught cycle)
            self._persist_pos()
            self.engine.merge_table(h["name"], min_segments=1,
                                    checkpoint=True)
            self.records_since_ckpt = 0
            self._advance(h.get("ts", 0))
            return
        hts = h.get("ts", 0)
        already = hts and hts <= self.applied_ts
        if op in ("insert", "delete"):
            if not already:
                self._group.append((h, b))   # journal with its commit
        elif op == "commit":
            if not already:
                # WAL the whole group + commit BEFORE applying (the
                # primary's WAL-first rule); applied_ts then advances
                # past this ts, so a redelivery is skipped entirely
                for gh, gb in self._group:
                    self.engine.wal.append(gh, gb)
                self.engine.wal.append(h, b)
                self.records_since_ckpt += len(self._group) + 1
            self._group = []
        elif not already:
            # catalog records apply (and advance) immediately
            self.engine.wal.append(h, b)
            self.records_since_ckpt += 1
        with self.engine._commit_lock:
            ts = applier.apply(h, b)
        if ts is not None:
            self._advance(ts)
        elif op not in ("insert", "delete") and hts:
            self._advance(hts)
        if self.records_since_ckpt >= self.checkpoint_every:
            self._checkpoint()

    def _advance(self, ts: int) -> None:
        if ts > self.engine.committed_ts:
            self.engine.committed_ts = ts
        self.engine.hlc.update(ts)
        self.applied_ts = max(self.applied_ts, ts)

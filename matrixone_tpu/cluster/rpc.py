"""Shared wire helpers for the CN<->TN RPC: blob framing and error-type
mapping. One definition — the framing is a cross-process protocol and
hand-maintained copies would drift."""

from __future__ import annotations

import struct
from typing import List

from matrixone_tpu.storage.engine import (ConflictError, ConstraintError,
                                          DuplicateKeyError)

ERR_TYPES = {"conflict": ConflictError, "duplicate": DuplicateKeyError,
             "constraint": ConstraintError}


def err_name(e: Exception) -> str:
    if isinstance(e, ConflictError):
        return "conflict"
    if isinstance(e, DuplicateKeyError):
        return "duplicate"
    if isinstance(e, ConstraintError):
        return "constraint"
    return "error"


def pack_blobs(blobs: List[bytes]) -> bytes:
    return b"".join(struct.pack("<I", len(b)) + b for b in blobs)


def unpack_blobs(blob: bytes) -> List[bytes]:
    out, off = [], 0
    while off + 4 <= len(blob):
        (n,) = struct.unpack_from("<I", blob, off)
        out.append(blob[off + 4:off + 4 + n])
        off += 4 + n
    return out

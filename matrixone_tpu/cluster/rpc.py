"""Resilient RPC fabric shared by every lane (CN->TN commits/DDL, CN->CN
fragment shipping, proxy relay, worker offload): blob framing, an error
taxonomy, pooled per-peer connections, per-call deadlines that propagate
into nested calls, exponential backoff with jitter, and per-peer circuit
breakers with half-open probing.

Reference analogue: `pkg/common/morpc` — pooled backends, futures,
circuit breaking, deadline-carrying contexts. One definition — the
framing is a cross-process protocol and hand-maintained copies would
drift.

Error taxonomy (callers classify by isinstance, never by string):

  * TransportError (ConnectionError) — the peer was unreachable or the
    connection died; RETRYABLE, but only for calls that are idempotent:
    reads, or mutations carrying an idempotency request-id ("rid") that
    the server dedups (a blind re-send of a mutation after a partial
    send could double-apply it).
  * DeadlineExceeded (TimeoutError) — the call's time budget ran out;
    not retried (the budget is gone).
  * BreakerOpen (ConnectionError) — the peer's circuit is open; raised
    WITHOUT touching the network so callers degrade (reroute, local
    fallback) instead of hanging on a known-bad peer.
  * engine errors (ConflictError, ...) — the server executed the call
    and said no; NEVER retried.

`MO_RPC_RESILIENCE=off` disables retries/breakers/deadline enforcement
(single attempt, errors surface raw) — the chaos drills use it to prove
the layer is what keeps queries alive under injected faults.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import socket
import struct
import threading

from matrixone_tpu.utils import san
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from matrixone_tpu.storage.engine import (ConflictError, ConstraintError,
                                          DuplicateKeyError)
from matrixone_tpu.utils import metrics as M, motrace
from matrixone_tpu.utils.fault import INJECTOR


def parse_addr(addr) -> tuple:
    if isinstance(addr, (tuple, list)):
        return addr[0], int(addr[1])
    host, port = addr.rsplit(":", 1)
    return host, int(port)


# --------------------------------------------------------------- config
def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def resilience_enabled() -> bool:
    return os.environ.get("MO_RPC_RESILIENCE", "on").lower() \
        not in ("off", "0", "false")


#: retry/backoff/breaker knobs (see README "Resilience knobs")
RETRIES = _env_int("MO_RPC_RETRIES", 4)              # attempts per call
BACKOFF_BASE = _env_float("MO_RPC_BACKOFF_BASE", 0.02)
BACKOFF_MAX = _env_float("MO_RPC_BACKOFF_MAX", 1.0)
POOL_SIZE = _env_int("MO_RPC_POOL", 2)               # idle socks per peer
BREAKER_THRESHOLD = _env_int("MO_RPC_BREAKER_THRESHOLD", 5)
BREAKER_COOLDOWN = _env_float("MO_RPC_BREAKER_COOLDOWN", 2.0)


def backoff_delay(attempt: int) -> float:
    """Exponential backoff with full jitter: attempt 1 -> ~BASE,
    doubling, capped at BACKOFF_MAX."""
    d = min(BACKOFF_MAX, BACKOFF_BASE * (2 ** max(0, attempt - 1)))
    return d * (0.5 + random.random())


# ------------------------------------------------------- error taxonomy
class RpcError(Exception):
    """Marker base for fabric-level failures."""


class TransportError(RpcError, ConnectionError):
    """Peer unreachable / connection died. Retryable for idempotent
    calls. Subclasses ConnectionError so pre-fabric handlers that catch
    (OSError, ConnectionError) keep working."""


class DeadlineExceeded(RpcError, TimeoutError):
    """The call's time budget ran out (possibly inherited from an
    enclosing deadline_scope)."""


class BreakerOpen(RpcError, ConnectionError):
    """The peer's circuit is open: failing fast instead of dialing."""


ERR_TYPES = {"conflict": ConflictError, "duplicate": DuplicateKeyError,
             "constraint": ConstraintError}


def err_name(e: Exception) -> str:
    if isinstance(e, ConflictError):
        return "conflict"
    if isinstance(e, DuplicateKeyError):
        return "duplicate"
    if isinstance(e, ConstraintError):
        return "constraint"
    return "error"


# ------------------------------------------------- deadline propagation
class Deadline:
    """Absolute expiry on the monotonic clock."""

    __slots__ = ("expires_at",)

    def __init__(self, seconds: float):
        self.expires_at = time.monotonic() + seconds

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0


_tls = threading.local()


def current_deadline() -> Optional[Deadline]:
    return getattr(_tls, "deadline", None)


@contextmanager
def deadline_scope(seconds: Optional[float] = None, *,
                   ms: Optional[float] = None):
    """Bound every RPC issued in this thread's dynamic extent. Nested
    scopes can only SHRINK the budget (a callee never outlives its
    caller's deadline); servers re-enter the scope from the request's
    `deadline_ms` header, so the budget follows the call chain across
    processes."""
    budget = (ms / 1000.0) if ms is not None else \
        (seconds if seconds is not None else 30.0)
    new = Deadline(budget)
    prev = current_deadline()
    if prev is not None:
        new.expires_at = min(new.expires_at, prev.expires_at)
    _tls.deadline = new
    try:
        yield new
    finally:
        _tls.deadline = prev


# ------------------------------------------------------ circuit breaker
class CircuitBreaker:
    """closed -> (threshold consecutive failures) -> open -> (cooldown)
    -> half-open: ONE probe call allowed; success closes, failure
    re-opens. State changes are exported via mo_rpc_breaker_state and
    wake utils.sync waiters."""

    def __init__(self, addr: tuple, threshold: Optional[int] = None,
                 cooldown: Optional[float] = None):
        self.addr = addr
        self.peer = f"{addr[0]}:{addr[1]}"
        self.threshold = threshold or BREAKER_THRESHOLD
        self.cooldown = cooldown if cooldown is not None else \
            BREAKER_COOLDOWN
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self._probe_in_flight = False
        self._lock = san.lock("CircuitBreaker._lock")

    def allow(self) -> bool:
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if (time.monotonic() - self.opened_at) >= self.cooldown:
                    self._set("half-open")
                    self._probe_in_flight = True
                    return True
                return False
            # half-open: admit a single probe at a time
            if not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._probe_in_flight = False
            if self.state != "closed":
                self._set("closed")

    def release_probe(self) -> None:
        """An admitted call exited without a verdict (e.g. its deadline
        expired before the attempt ran): free the half-open probe slot
        so the breaker cannot wedge with a probe nobody owns."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            self.failures += 1
            if self.state == "half-open" or \
                    (self.state == "closed"
                     and self.failures >= self.threshold):
                self.opened_at = time.monotonic()
                self._set("open")
            elif self.state == "open":
                self.opened_at = time.monotonic()   # stay open, re-arm

    _STATE_CODE = {"closed": 0, "half-open": 1, "open": 2}

    def _set(self, state: str) -> None:
        # called with the lock held
        self.state = state
        M.rpc_breaker_state.set(self._STATE_CODE[state], peer=self.peer)
        M.rpc_breaker_transitions.inc(peer=self.peer, state=state)
        from matrixone_tpu.utils.sync import notify_waiters
        notify_waiters()

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "failures": self.failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown}


_breakers: Dict[tuple, CircuitBreaker] = {}
_breakers_lock = san.lock("matrixone_tpu.cluster.rpc._breakers_lock")


def breaker_for(addr) -> CircuitBreaker:
    key = parse_addr(addr)
    with _breakers_lock:
        b = _breakers.get(key)
        if b is None:
            b = _breakers[key] = CircuitBreaker(key)
        return b


def breaker_states() -> Dict[str, dict]:
    """Per-peer breaker view (mo_ctl('rpc','status'))."""
    with _breakers_lock:
        bs = list(_breakers.values())
    return {b.peer: b.snapshot() for b in bs}


def reset_breakers() -> None:
    """Test hook: forget every peer's breaker state."""
    with _breakers_lock:
        _breakers.clear()


# ------------------------------------------------------ request dedup
class _Pending:
    __slots__ = ("event",)

    def __init__(self):
        self.event = threading.Event()


class RequestDedup:
    """Server-side idempotency: rid -> (resp, blob), LRU-bounded. A
    retried mutation (same rid, possibly on a NEW connection after a
    mid-call disconnect) replays the recorded response instead of
    re-executing — the exactly-once half of write-safe retries.

    In-flight coverage: the retry can arrive (new connection, new
    handler thread) while the FIRST attempt is still executing — the
    backoff is milliseconds, a cold commit can be seconds. claim() makes
    the duplicate WAIT for the original's result instead of racing a
    second execution."""

    def __init__(self, cap: int = 4096):
        self.cap = cap
        self._d: "OrderedDict[str, object]" = OrderedDict()
        self._lock = san.lock("RequestDedup._lock")

    def claim(self, rid: str, timeout: float = 30.0):
        """-> ("mine", None): caller must execute then complete(rid).
        -> ("done", (resp, blob)): replay this recorded response."""
        with self._lock:
            ent = self._d.get(rid)
            if ent is None:
                self._d[rid] = _Pending()
                return "mine", None
            if isinstance(ent, tuple):
                self._d.move_to_end(rid)
                return "done", ent
            event = ent.event
        event.wait(timeout)
        with self._lock:
            ent = self._d.get(rid)
            if isinstance(ent, tuple):
                return "done", ent
        return "done", ({"ok": False,
                         "err": f"duplicate request {rid} still "
                                f"in flight after {timeout}s"}, b"")

    def complete(self, rid: str, resp: dict, blob: bytes = b"") -> None:
        with self._lock:
            ent = self._d.get(rid)
            self._d[rid] = (resp, blob)
            self._d.move_to_end(rid)
            while len(self._d) > self.cap:
                k = next(iter(self._d))
                if isinstance(self._d[k], _Pending):
                    break            # never evict an in-flight entry
                self._d.popitem(last=False)
        if isinstance(ent, _Pending):
            ent.event.set()          # wake waiting duplicates


_rid_counter = itertools.count(1)
_rid_prefix = f"{os.getpid():x}-{random.getrandbits(32):08x}"


def new_rid() -> str:
    """Process-unique idempotency id for one LOGICAL call (generate once,
    reuse across every retry of that call)."""
    return f"{_rid_prefix}-{next(_rid_counter)}"


# ------------------------------------------------------------ transport
class RpcClient:
    """Pooled request/response channel to one peer (morpc backend
    analogue). Thread-safe: concurrent calls each check a socket out of
    the per-peer pool (up to `pool_size` kept warm; bursts open
    ephemeral sockets that are closed on return).

    Retry policy: transport failures are retried with jittered
    exponential backoff, but ONLY when the call is marked idempotent —
    `retryable=True` (reads) or a header carrying "rid" (mutations the
    server dedups). Everything is bounded by the call deadline and the
    peer's circuit breaker."""

    def __init__(self, addr, timeout: float = 30.0,
                 pool_size: Optional[int] = None,
                 retries: Optional[int] = None):
        self.addr = parse_addr(addr)
        self.timeout = timeout
        self.pool_size = pool_size if pool_size is not None else POOL_SIZE
        self.retries = retries if retries is not None else RETRIES
        self._idle: List[socket.socket] = []
        self._lock = san.lock("RpcClient._lock")
        self._closed = False
        self.breaker = breaker_for(self.addr)

    # ---- socket pool
    def _checkout(self, budget: float) -> socket.socket:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        s = socket.create_connection(
            self.addr, timeout=max(0.001, min(self.timeout, budget)))
        return s

    def _checkin(self, s: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.pool_size:
                self._idle.append(s)
                return
        try:
            s.close()
        except OSError:
            pass

    # ---- call
    def call(self, header: dict, blob: bytes = b"",
             retryable: Optional[bool] = None) -> Tuple[dict, bytes]:
        # mosan choke point: an RPC (with retries + backoff sleeps)
        # under the commit lock or a cache lock stalls every writer
        san.check_blocking("rpc.call")
        on = resilience_enabled()
        op = str(header.get("op", ""))
        if retryable is None:
            retryable = "rid" in header
        dl = current_deadline() or Deadline(self.timeout)
        attempts = max(1, self.retries) if (on and retryable) else 1
        if on and not self.breaker.allow():
            M.rpc_errors.inc(kind="breaker", op=op)
            raise BreakerOpen(
                f"circuit open for peer {self.addr} "
                f"({self.breaker.failures} consecutive failures)")
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                M.rpc_retries.inc(op=op)
                delay = min(backoff_delay(attempt),
                            max(0.0, dl.remaining()))
                if delay > 0:
                    time.sleep(delay)
                if on and not self.breaker.allow():
                    M.rpc_errors.inc(kind="breaker", op=op)
                    raise BreakerOpen(
                        f"circuit open for peer {self.addr}")
            if on and dl.expired():
                M.rpc_errors.inc(kind="deadline", op=op)
                self.breaker.release_probe()
                raise DeadlineExceeded(
                    f"rpc {op!r} to {self.addr}: deadline exceeded "
                    f"after {attempt} attempt(s)") from last
            M.rpc_attempts.inc(op=op)
            t0 = time.perf_counter()
            try:
                with motrace.span("rpc.call", op=op,
                                  peer=self.breaker.peer,
                                  attempt=attempt):
                    out = self._attempt(header, blob, dl)
                if on:
                    self.breaker.record_success()
                M.rpc_seconds.observe(time.perf_counter() - t0)
                # spans the server shipped back on the response header
                # fold into the caller's trace (utils/motrace.py)
                motrace.merge_remote(out[0])
                return out
            except DeadlineExceeded:
                M.rpc_errors.inc(kind="deadline", op=op)
                if on:
                    self.breaker.release_probe()
                raise         # subclasses TimeoutError/OSError: not a
                              # transport failure, never retried
            except (OSError, ConnectionError) as e:
                if on:
                    self.breaker.record_failure()
                last = e
            except Exception:  # noqa: BLE001 — breaker-counted, re-raised
                # a garbage/mis-protocol response (struct/json decode
                # error) is a misbehaving peer: count it so the breaker
                # can open (and a half-open probe is not leaked), but
                # propagate the real error — re-sending cannot help
                if on:
                    self.breaker.record_failure()
                raise
        if on and dl.expired():
            M.rpc_errors.inc(kind="deadline", op=op)
            raise DeadlineExceeded(
                f"rpc {op!r} to {self.addr}: deadline exceeded "
                f"({last!r})") from last
        M.rpc_errors.inc(kind="transport", op=op)
        raise TransportError(
            f"rpc {op!r} to {self.addr} failed after {attempts} "
            f"attempt(s): {last!r}") from last

    def _attempt(self, header: dict, blob: bytes,
                 dl: Deadline) -> Tuple[dict, bytes]:
        from matrixone_tpu.logservice.replicated import (_recv_msg,
                                                         _send_msg)
        rem = dl.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"rpc to {self.addr}: no budget left before send")
        s = self._checkout(rem)
        ok = False
        try:
            s.settimeout(max(0.001, min(self.timeout, dl.remaining())))
            wire = dict(header)
            wire["deadline_ms"] = int(max(1.0, dl.remaining() * 1000))
            # trace context rides the SAME wire header as the deadline
            # (one attribute read when motrace is disarmed)
            motrace.inject(wire)
            fault = INJECTOR.trigger("rpc.send")
            if fault == "drop":
                raise ConnectionError(
                    "fault injected: connection dropped at rpc.send")
            if fault == "partial":
                # torn half-frame: the server sees a truncated message
                # and drops the connection; the request was NOT applied
                hj = json.dumps(wire).encode()
                frame = (struct.pack("<I", len(hj)) + hj
                         + struct.pack("<I", len(blob)) + blob)
                s.sendall(frame[:max(1, len(frame) // 2)])
                raise ConnectionError(
                    "fault injected: partial send at rpc.send")
            _send_msg(s, wire, blob)
            if INJECTOR.trigger("rpc.recv") == "drop":
                # mid-call disconnect AFTER the request reached the
                # peer: the hazard idempotency rids exist for
                raise ConnectionError(
                    "fault injected: connection dropped at rpc.recv")
            out = _recv_msg(s)
            ok = True
            return out
        finally:
            if ok:
                self._checkin(s)
            else:
                try:
                    s.close()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for s in idle:
            try:
                s.close()
            except OSError:
                pass


def pack_blobs(blobs: List[bytes]) -> bytes:
    return b"".join(struct.pack("<I", len(b)) + b for b in blobs)


def unpack_blobs(blob: bytes) -> List[bytes]:
    out, off = [], 0
    while off + 4 <= len(blob):
        (n,) = struct.unpack_from("<I", blob, off)
        out.append(blob[off + 4:off + 4 + n])
        off += 4 + n
    return out

"""Shared wire helpers for the CN<->TN and CN<->CN RPC: blob framing,
error-type mapping, and the request/response client. One definition —
the framing is a cross-process protocol and hand-maintained copies would
drift."""

from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional

from matrixone_tpu.storage.engine import (ConflictError, ConstraintError,
                                          DuplicateKeyError)


def parse_addr(addr) -> tuple:
    if isinstance(addr, (tuple, list)):
        return addr[0], int(addr[1])
    host, port = addr.rsplit(":", 1)
    return host, int(port)


class RpcClient:
    """One serialized request/response socket (morpc backend analogue,
    minimum form). Reconnects once per call on failure. Used for CN->TN
    commits/DDL and CN->CN fragment shipping."""

    def __init__(self, addr, timeout: float = 30.0):
        self.addr = parse_addr(addr)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.settimeout(self.timeout)
        return s

    def call(self, header: dict, blob: bytes = b""):
        from matrixone_tpu.logservice.replicated import (_recv_msg,
                                                         _send_msg)
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    _send_msg(self._sock, header, blob)
                    return _recv_msg(self._sock)
                except (OSError, ConnectionError):
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    if attempt:
                        raise

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

ERR_TYPES = {"conflict": ConflictError, "duplicate": DuplicateKeyError,
             "constraint": ConstraintError}


def err_name(e: Exception) -> str:
    if isinstance(e, ConflictError):
        return "conflict"
    if isinstance(e, DuplicateKeyError):
        return "duplicate"
    if isinstance(e, ConstraintError):
        return "constraint"
    return "error"


def pack_blobs(blobs: List[bytes]) -> bytes:
    return b"".join(struct.pack("<I", len(b)) + b for b in blobs)


def unpack_blobs(blob: bytes) -> List[bytes]:
    out, off = [], 0
    while off + 4 <= len(blob):
        (n,) = struct.unpack_from("<I", blob, off)
        out.append(blob[off + 4:off + 4 + n])
        off += 4 + n
    return out

"""TN service: the storage/commit owner + logtail push server.

Reference analogue: `pkg/tnservice` + `tae/rpc/handle.go:537,547`
(HandlePreCommitWrite/HandleCommit — CN commits arrive over RPC) and
`tae/logtail/service/server.go:192` (logtail push server fanning commit
deltas to subscribed CNs). The transport is the same length-prefixed
JSON+blob framing the log replicas use — one fabric, every role.
"""

from __future__ import annotations

import itertools
import queue
import socket
import threading

from matrixone_tpu.utils import san
from matrixone_tpu.utils.lifecycle import ServiceThreads
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from matrixone_tpu.logservice.replicated import _recv_msg, _send_msg
from matrixone_tpu.storage import wal as walmod
from matrixone_tpu.storage.engine import (ConflictError, ConstraintError,
                                          DuplicateKeyError, Engine)
from matrixone_tpu.storage.fileservice import FileService, LocalFS


class LogtailHub:
    """Tee over the engine's WAL: every append is durable (inner wal) AND
    reaches subscriber queues — the logtail stream is the WAL stream
    (tae/logtail derives its stream from the commit pipeline).

    Incremental design (VERDICT r3 weak #5): the hub keeps an in-memory
    backlog of records since the last truncation, seeded ONCE from the
    durable log at startup — subscribe never re-reads the WAL from disk.
    Fan-out runs on a dedicated dispatcher thread, so append holds the
    hub lock only for the durable write + an enqueue; a slow subscriber
    or an in-flight subscribe can no longer stall commits.

    Correctness of the subscribe handoff: every record gets an LSN; the
    dispatcher publishes `_processed_lsn` and snapshots the subscriber
    list under the hub lock BEFORE fanning a record out. subscribe()
    atomically reads `_processed_lsn`, slices the backlog up to it, and
    registers its queue — so a record is delivered exactly once: from
    the backlog slice if the dispatcher already passed it, from the live
    queue otherwise."""

    def __init__(self, wal):
        self.wal = wal
        self.last_ts = 0
        self._subs: List[queue.Queue] = []
        self._lock = san.lock("LogtailHub._lock")
        self._backlog: List[tuple] = []      # (lsn, header, blob)
        self._next_lsn = 1
        for h, b in wal.replay():            # seed: one disk read, ever
            self._backlog.append((self._next_lsn, h, b))
            self.last_ts = max(self.last_ts, h.get("ts", 0))
            self._next_lsn += 1
        self._processed_lsn = self._next_lsn - 1
        self._dispatchq: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        daemon=True)
        self._thread.start()

    # ---- WalWriter interface (engine-facing)
    def append(self, header: dict, arrow_blob: bytes = b"") -> None:
        with self._lock:
            self.wal.append(header, arrow_blob)
            self.last_ts = max(self.last_ts, header.get("ts", 0))
            lsn = self._next_lsn
            self._next_lsn += 1
            self._backlog.append((lsn, header, arrow_blob))
            # enqueue under the lock: dispatch order must equal WAL order
            # (the applier's pending-group buffering assumes it)
            self._dispatchq.put((lsn, header, arrow_blob))

    def truncate(self) -> None:
        with self._lock:
            self.wal.truncate()
            # live subscribers still get any queued records (they were
            # appended pre-truncation); only FUTURE subscribers start
            # from the checkpoint, which _serve_logtail routes to resync
            self._backlog = []

    def replay(self, stats=None):
        try:
            return self.wal.replay(stats=stats)
        except TypeError:      # wrapped wal predates the stats hook
            return self.wal.replay()

    def stop(self) -> None:
        self._stop.set()
        # join with a deadline: the dispatch loop wakes within its 0.5s
        # queue-poll tick (mosan's leak checker gates abandoned threads)
        self._thread.join(timeout=5)

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                lsn, h, b = self._dispatchq.get(timeout=0.5)
            except queue.Empty:
                continue
            with self._lock:
                subs = list(self._subs)
                self._processed_lsn = lsn
            for q in subs:
                q.put((h, b))

    # ---- logtail side
    def subscribe(self, from_ts: int) -> Tuple[list, queue.Queue]:
        """Records after from_ts, in WAL order. A subscribe landing
        mid-commit-group may end the backlog with dangling insert/delete
        records — the consumer's WalApplier buffers those until the
        commit record arrives on the live queue (same contract as a
        restart replay hitting a torn tail)."""
        with self._lock:
            p = self._processed_lsn
            backlog = [(h, b) for lsn, h, b in self._backlog
                       if lsn <= p
                       and not (h.get("ts", 0) and h["ts"] <= from_ts)]
            q = queue.Queue()
            self._subs.append(q)
            return backlog, q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            self._subs = [s for s in self._subs if s is not q]

    def safe_frontier(self, q: queue.Queue, committed_ts: int):
        """`committed_ts` only if every appended record has been
        dispatched AND this subscriber's queue is drained — otherwise
        None. Read committed_ts BEFORE calling: commit order is
        hub.append -> committed_ts advance, so a committed ts implies
        its record already holds an lsn, and the two checks then prove
        it was delivered. Advertising a frontier ahead of delivery
        would let subscribers advance applied_ts past records still in
        flight (permanent loss on a resubscribe)."""
        with self._lock:
            if self._processed_lsn == self._next_lsn - 1 and q.empty():
                return committed_ts
            return None


from matrixone_tpu.cluster.rpc import (RequestDedup, deadline_scope,
                                       err_name as _err_name, unpack_blobs)
from matrixone_tpu.utils import motrace


class TNService:
    """One TN process: Engine + commit RPC + logtail push + DDL apply."""

    def __init__(self, fs: Optional[FileService] = None,
                 data_dir: Optional[str] = None, port: int = 0, wal=None):
        if fs is None:
            fs = LocalFS(data_dir)
        self.engine = Engine.open(fs, wal=wal)
        self.hub = LogtailHub(self.engine.wal)
        self.engine.wal = self.hub
        # cluster-wide active-txn registry (reference: TAE tracks active
        # txns centrally because commit runs there): CNs lease a token per
        # open txn; merge defers while any live token exists.  Leases
        # expire so a kill -9'd CN cannot block merges forever.
        self._remote_txns: Dict[str, float] = {}     # token -> deadline
        self._txn_lock = san.lock("TNService._txn_lock")
        self._txn_ids = itertools.count(1)
        # idempotency: retried CN calls (same rid, any connection) replay
        # the recorded response instead of re-executing the mutation
        self._rids = RequestDedup()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._stopping = threading.Event()
        self._svc = ServiceThreads("mo-tn")

    # ------------------------------------------------------------- serve
    def start(self) -> "TNService":
        self._svc.spawn_accept(self.serve_forever)
        return self

    def serve_forever(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._svc.spawn_handler(self._handle, conn)

    def stop(self) -> None:
        self._stopping.set()
        self.hub.stop()
        # interrupt blocked accept/recv (incl. live logtail pushes) and
        # join every thread this service started, with a deadline
        self._svc.shutdown(self._sock)

    # ------------------------------------------------- remote txn leases
    def live_remote_txns(self) -> int:
        now = time.monotonic()
        with self._txn_lock:
            for tok in [t for t, dl in self._remote_txns.items()
                        if dl < now]:
                del self._remote_txns[tok]
            return len(self._remote_txns)

    # ----------------------------------------------------------- handlers
    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                header, blob = _recv_msg(conn)
                op = header.get("op")
                if op == "subscribe":
                    self._serve_logtail(conn, header.get("from_ts", 0))
                    return
                rid = header.get("rid")
                if rid:
                    # idempotency: a retry of a call we already executed
                    # (or are STILL executing on another thread) replays
                    # the recorded response instead of re-running it
                    dl_ms = header.get("deadline_ms") or 30_000
                    kind, ent = self._rids.claim(
                        rid, timeout=max(0.05, dl_ms / 1000.0))
                    if kind == "done":
                        resp, rblob = dict(ent[0], dedup=True), ent[1]
                        _send_msg(conn, resp, rblob)
                        continue
                # re-enter the caller's trace context from the same
                # wire header that carries deadline_ms; the TN's spans
                # ship back to the CN on the response (rs.attach)
                rs = motrace.remote_session(header, proc="tn",
                                            name=f"tn.{op}")
                try:
                    # re-enter the caller's remaining time budget so
                    # nested calls (quorum WAL appends) inherit it
                    with deadline_scope(
                            ms=header.get("deadline_ms") or 30_000):
                        with rs:
                            resp, rblob = self._dispatch(op, header,
                                                         blob)
                except Exception as e:        # noqa: BLE001
                    resp, rblob = {"ok": False, "err": str(e),
                                   "etype": _err_name(e)}, b""
                rs.attach(resp)
                if rid:
                    # record (and wake waiting duplicates) BEFORE the
                    # send: a disconnect between our apply and the
                    # client's read is exactly the window a retry closes
                    self._rids.complete(rid, resp, rblob)
                _send_msg(conn, resp, rblob)
                if op == "stop":
                    import os
                    os._exit(0)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op: str, header: dict, blob: bytes):
        eng = self.engine
        if op == "ping":
            return {"ok": True, "committed_ts": eng.committed_ts,
                    "ckpt_ts": eng._ckpt_ts}, b""
        if op == "commit":
            return self._handle_commit(header, blob), b""
        if op == "ddl":
            return self._handle_ddl(header["record"]), b""
        if op == "alloc_auto":
            t = eng.get_table(header["table"])
            vals = t.allocate_auto(int(header["n"]))
            return {"ok": True,
                    "vals": np.asarray(vals).tolist()}, b""
        if op == "observe_auto":
            t = eng.get_table(header["table"])
            t.observe_auto(np.asarray(header["vals"], np.int64))
            return {"ok": True}, b""
        if op == "create_snapshot":
            ts = eng.create_snapshot(header["name"])
            return {"ok": True, "ts": ts,
                    "applied_ts": self.hub.last_ts}, b""
        if op == "restore_table":
            n = eng.restore_table(header["table"], int(header["ts"]))
            return {"ok": True, "affected": n,
                    "applied_ts": self.hub.last_ts}, b""
        if op == "txn_begin":
            lease = float(header.get("lease", 30.0))
            tok = f"rtxn-{next(self._txn_ids)}"
            with self._txn_lock:
                self._remote_txns[tok] = time.monotonic() + lease
            return {"ok": True, "token": tok}, b""
        if op == "txn_end":
            with self._txn_lock:
                self._remote_txns.pop(header["token"], None)
            return {"ok": True}, b""
        if op == "txn_renew":
            # upsert, not update: a restarted TN loses the in-memory
            # registry, and the still-open txns on CNs must win back
            # their merge protection on the next renew tick
            lease = float(header.get("lease", 30.0))
            now = time.monotonic()
            with self._txn_lock:
                for tok in header.get("tokens", []):
                    self._remote_txns[tok] = now + lease
            return {"ok": True}, b""
        if op == "merge_table":
            # cluster-wide guard: an open snapshot txn on ANY CN would
            # see pre-merge gids the merge destroys — defer (-2, the same
            # contract as Engine.merge_table's local guard)
            if self.live_remote_txns() > 0:
                return {"ok": True, "kept": -2,
                        "applied_ts": self.hub.last_ts}, b""
            kept = eng.merge_table(header["name"],
                                   min_segments=header.get("min_segments",
                                                           2))
            return {"ok": True, "kept": kept,
                    "applied_ts": self.hub.last_ts}, b""
        if op == "checkpoint":
            eng.checkpoint()
            return {"ok": True}, b""
        if op == "stop":
            return {"ok": True}, b""
        return {"ok": False, "err": f"bad op {op}"}, b""

    def _handle_commit(self, header: dict, blob: bytes) -> dict:
        """tae/rpc/handle.go:547 HandleCommit: rebuild the shipped
        workspace, re-encode strings into TN dictionaries, run the
        authoritative commit pipeline.  The rebuild runs under the
        commit lock (reentrant) so two CN connection threads cannot
        interleave dictionary encoding with each other's commit; the
        commit itself runs OUTSIDE the handler's hold — the encoded
        codes are table-global and append-only, so they stay valid
        across the release, and commit_txn's post-commit hook
        (materialized-view maintenance) must run with the lock free
        or its state lock inverts against the commit lock (mosan
        caught the cycle)."""
        eng = self.engine
        with eng._commit_lock:
            blobs = unpack_blobs(blob)
            inserts: Dict[str, list] = {}
            for tname, b in zip(header.get("tables", []), blobs):
                t = eng.get_table(tname)
                arrays, validity = walmod.arrow_to_arrays(b)
                for c, a in list(arrays.items()):
                    if isinstance(a, list):   # legacy: per-row strings
                        arrays[c] = t.encode_strings_list(c, a)
                # DictEncoded varchar passes through: commit_txn remaps
                # batch-local codes -> table codes vectorized, under its
                # own lock (no per-row Python on the commit path)
                inserts.setdefault(tname, []).append((arrays, validity))
            deletes = {t: np.asarray(g, np.int64)
                       for t, g in header.get("deletes", {}).items()}
        try:
            affected = eng.commit_txn(header.get("snapshot_ts"),
                                      inserts, deletes)
        except (ConflictError, DuplicateKeyError,
                ConstraintError) as e:
            return {"ok": False, "err": str(e), "etype": _err_name(e)}
        return {"ok": True, "affected": affected,
                "ts": eng.committed_ts}

    def _handle_ddl(self, rec: dict) -> dict:
        """Catalog mutation forwarded from a CN. Applied through the
        real engine methods with log=True, so the WAL record streams to
        every subscriber (including the requesting CN, which applies it
        exactly as restart replay would)."""
        from matrixone_tpu.sql.binder import BindError  # noqa: F401
        from matrixone_tpu.storage.engine import (TableMeta,
                                                  schema_from_json)
        from matrixone_tpu.storage.partition import PartitionSpec
        eng = self.engine
        op = rec["op"]
        if op == "create_table":
            eng.create_table(
                TableMeta(rec["name"], schema_from_json(rec["schema"]),
                          rec.get("pk") or [],
                          auto_increment=rec.get("auto"),
                          not_null=rec.get("not_null", []),
                          partition=PartitionSpec.from_json(
                              rec.get("partition"))),
                if_not_exists=rec.get("if_not_exists", False))
        elif op == "drop_table":
            eng.drop_table(rec["name"], if_exists=rec.get("if_exists",
                                                          False))
        elif op == "create_external":
            eng.create_external(
                TableMeta(rec["name"], schema_from_json(rec["schema"]),
                          []),
                rec["location"], rec["fmt"],
                if_not_exists=rec.get("if_not_exists", False),
                snapshot=rec.get("snapshot"))
        elif op == "create_stage":
            eng.create_stage(rec["name"], rec["url"])
        elif op == "drop_stage":
            eng.drop_stage(rec["name"])
        elif op == "create_publication":
            eng.create_publication(rec["name"], list(rec["tables"]))
        elif op == "drop_publication":
            eng.drop_publication(rec["name"])
        elif op == "mark_source":
            eng.mark_source(rec["name"])
        elif op == "create_dynamic":
            eng.register_dynamic(rec["name"], rec["sql"])
        elif op == "drop_snapshot":
            eng.drop_snapshot(rec["name"])
        elif op == "alter_partition_drop":
            eng.alter_partition_drop(rec["table"], rec["part"])
        else:
            return {"ok": False, "err": f"bad ddl {op}"}
        return {"ok": True, "applied_ts": self.hub.last_ts}

    def _serve_logtail(self, conn: socket.socket, from_ts: int) -> None:
        """Backlog then live push; the connection becomes one-way.

        If the subscriber's from_ts predates the last checkpoint, the
        records it needs were truncated — it must rebuild from the
        manifest first (__resync__), then stream from the checkpoint ts.
        The retry loop closes the race against a checkpoint truncating
        the WAL between reading _ckpt_ts and registering the queue."""
        while True:
            ck = self.engine._ckpt_ts
            eff_ts = max(from_ts, ck)
            backlog, q = self.hub.subscribe(eff_ts)
            if self.engine._ckpt_ts == ck:
                break
            self.hub.unsubscribe(q)
        try:
            if ck > from_ts:
                _send_msg(conn, {"op": "__resync__", "ts": ck})
            for h, b in backlog:
                _send_msg(conn, h, b)
            cu_ts = self.engine.committed_ts
            cu_safe = self.hub.safe_frontier(q, cu_ts)
            _send_msg(conn, {"op": "__caught_up__",
                             "ts": cu_safe or 0})
            while not self._stopping.is_set():
                try:
                    # 250ms cadence: new sessions sync to the frontier
                    # at connect, so the idle heartbeat bounds their
                    # connect-time stall
                    h, b = q.get(timeout=0.25)
                except queue.Empty:
                    # frontier heartbeat (reference: logtail periodic
                    # update-ts events): an idle CN's applied_ts keeps
                    # tracking the TN frontier, so read gates
                    # (sync_frontier / fragment snapshots) stay
                    # reachable without fresh commits. ONLY a
                    # delivery-safe frontier is advertised (see
                    # safe_frontier) — never a ts ahead of records
                    # still in the dispatch pipeline.
                    ts = self.engine.committed_ts
                    safe = self.hub.safe_frontier(q, ts)
                    if safe:
                        _send_msg(conn, {"op": "__frontier__",
                                         "ts": safe})
                    continue
                _send_msg(conn, h, b)
        except (ConnectionError, OSError):
            pass
        finally:
            self.hub.unsubscribe(q)
            try:
                conn.close()
            except OSError:
                pass


def main() -> None:
    import argparse
    import sys
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--log-replicas", default="",
                    help="comma-separated host:port log replica "
                         "endpoints; the TN then journals through the "
                         "quorum WAL instead of a local file")
    ap.add_argument("--keeper", default="",
                    help="comma-separated keeper endpoints to register "
                         "with and heartbeat (HAKeeper)")
    ap.add_argument("--campaign", action="store_true",
                    help="acquire the quorum WAL via leader election "
                         "(waits for any live writer's lease to lapse) "
                         "instead of unconditional epoch fencing")
    args = ap.parse_args()
    wal = None
    if args.log_replicas:
        from matrixone_tpu.cluster.rpc import parse_addr
        from matrixone_tpu.logservice.replicated import ReplicatedLog
        addrs = [parse_addr(a) for a
                 in args.log_replicas.split(",") if a]
        if args.campaign:
            wal = ReplicatedLog.campaign_until_elected(addrs,
                                                       timeout=120.0)
        else:
            wal = ReplicatedLog(addrs)
    motrace.TRACER.proc = "tn"
    tn = TNService(data_dir=args.dir, port=args.port, wal=wal)
    if args.keeper:
        from matrixone_tpu.cluster.rpc import parse_addr
        from matrixone_tpu.hakeeper import HAClient
        HAClient([parse_addr(a) for a in args.keeper.split(",") if a],
                 "tn", f"tn-{tn.port}",
                 service_addr=f"127.0.0.1:{tn.port}").start()
    print(f"PORT {tn.port}", flush=True)
    sys.stdout.flush()
    tn.serve_forever()


if __name__ == "__main__":
    main()

from matrixone_tpu.container import dtypes
from matrixone_tpu.container.batch import Batch, from_device
from matrixone_tpu.container.device import (DeviceBatch, DeviceColumn,
                                            bucket_length, from_numpy)
from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.container.vector import Vector

__all__ = ["dtypes", "Batch", "from_device", "DeviceBatch", "DeviceColumn",
           "bucket_length", "from_numpy", "DType", "TypeOid", "Vector"]

"""Host-side Batch: named Vectors + row count, and the host<->device bridge.

Redesign of `pkg/container/batch/types.go:45`. `Batch.to_device()` is the
seam the reference implements with cgo pointer-marshalling
(`pkg/sql/plan/function/cxcall.go:65` ships 6 raw ptr/len words per vector);
here it is numpy -> padded jnp arrays, with varlena columns
dictionary-encoded (codes on device, dictionary kept host-side in the
returned `HostDicts`).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from matrixone_tpu.container import device as dev
from matrixone_tpu.container.dtypes import DType, varchar
from matrixone_tpu.container.vector import Vector, arrow_type_to_dtype
from matrixone_tpu.utils import qa

#: host-side dictionaries for device dictionary-encoded varlena columns
HostDicts = Dict[str, List[str]]


@dataclasses.dataclass
class Batch:
    columns: Dict[str, Vector]

    def __len__(self) -> int:
        for v in self.columns.values():
            return len(v)
        return 0

    @property
    def schema(self) -> Dict[str, DType]:
        return {n: v.dtype for n, v in self.columns.items()}

    @classmethod
    def from_pydict(cls, data: Dict[str, list], schema: Dict[str, DType]) -> "Batch":
        return cls({n: Vector.from_values(data[n], schema[n]) for n in schema})

    def to_device(self, pad_to: Optional[int] = None):
        """-> (DeviceBatch, HostDicts). Varlena columns become int32 codes."""
        n = len(self)
        arrays, dtypes, validity, dicts = {}, {}, {}, {}
        for name, vec in self.columns.items():
            if vec.dtype.is_varlen:
                codes, dictionary = vec.encode_dictionary()
                arrays[name] = codes
                from matrixone_tpu.container import dtypes as dt
                dtypes[name] = dt.INT32
                dicts[name] = dictionary
            else:
                arrays[name] = vec.data
                dtypes[name] = vec.dtype
            validity[name] = vec.valid_mask()
        dbatch = dev.from_numpy(arrays, dtypes, validity, n_rows=n, pad_to=pad_to)
        # remember the SQL-level type on the device column for varlena cols
        for name, vec in self.columns.items():
            if vec.dtype.is_varlen:
                col = dbatch.columns[name]
                dbatch.columns[name] = dev.DeviceColumn(
                    data=col.data, validity=col.validity, dtype=vec.dtype)
        return dbatch, dicts

    # ---- Arrow interop ----

    def to_arrow(self) -> pa.RecordBatch:
        names = list(self.columns)
        return pa.RecordBatch.from_arrays(
            [self.columns[n].to_arrow() for n in names], names=names)

    @classmethod
    def from_arrow(cls, rb, schema: Optional[Dict[str, DType]] = None) -> "Batch":
        cols = {}
        for i, name in enumerate(rb.schema.names):
            arr = rb.column(i)
            dtype = schema[name] if schema else arrow_type_to_dtype(arr.type)
            cols[name] = Vector.from_arrow(arr, dtype)
        return cls(cols)


def from_device(dbatch: dev.DeviceBatch, dicts: Optional[HostDicts] = None,
                schema: Optional[Dict[str, DType]] = None) -> Batch:
    """Pull a DeviceBatch back to host, trimming padding and decoding dicts."""
    import jax
    dicts = dicts or {}
    n = int(jax.device_get(dbatch.n_rows))
    cols: Dict[str, Vector] = {}
    for name, col in dbatch.columns.items():
        data = np.asarray(jax.device_get(col.data))
        val = np.asarray(jax.device_get(col.validity))
        if col.is_const and n > 1:
            data = np.broadcast_to(data, (n,) + data.shape[1:]).copy()
            val = np.broadcast_to(val, (n,)).copy()
        data, val = data[:n], val[:n]
        dtype = (schema or {}).get(name, col.dtype)
        if name in dicts or dtype.is_varlen:
            if name not in dicts and n > 0 and val.any():
                raise ValueError(
                    f"varchar column {name!r} reached the host without a "
                    f"dictionary — an operator dropped dict propagation")
            lut = np.asarray(dicts.get(name, []), dtype=object)
            if qa.armed() and len(data) and val.any():
                # canary audit for dict codes: a valid visible cell whose
                # code is outside the dictionary can only be a leaked
                # poisoned pad row (codes are produced by encode or by
                # expressions over in-range codes)
                oob = val & ((data < 0) | (data >= len(lut)))
                n_oob = int(np.count_nonzero(oob))
                if n_oob:
                    qa.record_finding(
                        "canary-in-result", f"column {name!r}",
                        f"{n_oob} valid cell(s) carry a dictionary code "
                        f"outside the LUT — a poisoned pad row leaked")
                    data = np.where(oob, 0, data)
                    val = val & ~oob
            strings = pa.array(
                [lut[c] if v else None for c, v in zip(data, val)],
                type=pa.string())
            cols[name] = Vector(dtype=dtype if dtype.is_varlen else varchar(),
                                strings=strings,
                                validity=None if val.all() else val)
        else:
            if qa.armed():
                qa.audit_host_column(name, data, val)
            cols[name] = Vector(dtype=dtype, data=data,
                                validity=None if val.all() else val)
    return Batch(cols)

"""Device-resident columnar containers (JAX pytrees).

The TPU-native redesign of the reference's `container.Vector` / `container.Batch`
(`pkg/container/vector/vector.go:43`, `pkg/container/batch/types.go:45`):

  reference (Go, CPU)                      this module (JAX, TPU)
  ------------------------------           -----------------------------------
  data []byte (fixed-width values)    ->   DeviceColumn.data  jnp array
  nulls *nulls.Nulls (bitmap)         ->   DeviceColumn.validity bool array
  area []byte (varlena heap)          ->   dictionary codes in .data (int32),
                                           dictionary strings stay host-side
  batch.Batch{Vecs, rowCount}         ->   DeviceBatch{columns, n_rows}

Key deviations, all deliberate for XLA:
  * arrays are padded to bucketed lengths so jitted kernels hit the compile
    cache instead of recompiling per batch size (see `bucket_length`); padding
    rows are masked out by `DeviceBatch.row_mask()`;
  * validity is a bool array, not a bitmap — XLA fuses mask math into
    neighbouring elementwise ops for free; host<->device serialization packs
    to bits (container/host Vector does that);
  * a "const" (scalar) column is a length-1 array broadcast by kernels,
    mirroring the reference's const-vector class.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container.dtypes import DType
from matrixone_tpu.utils import qa

#: batch length buckets — powers of two from 1Ki to 1Mi. A batch of 13_000
#: rows is padded to 16_384 so every operator's jit cache has at most
#: len(_BUCKETS) entries per dtype signature (the reference has no analogue:
#: Go code doesn't recompile; XLA does, so shapes must be quantized).
_BUCKETS = [1 << k for k in range(10, 21)]


def bucket_length(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    # beyond 1Mi rows, round up to the next multiple of 1Mi
    m = _BUCKETS[-1]
    return ((n + m - 1) // m) * m


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One typed column on device: values + validity mask.

    ``data``: shape [n] (scalar types) or [n, dim] (VECF32 embeddings).
    ``validity``: bool [n]; True = value present (Arrow convention).
    ``dtype``: the SQL type (static / aux data, not traced).
    """

    data: jnp.ndarray
    validity: jnp.ndarray
    dtype: DType

    def tree_flatten(self):
        return (self.data, self.validity), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity = children
        return cls(data=data, validity=validity, dtype=aux)

    @property
    def padded_len(self) -> int:
        return self.data.shape[0]

    @classmethod
    def const(cls, value, dtype: DType) -> "DeviceColumn":
        """Length-1 'const' column (reference: const-class vectors)."""
        data = jnp.asarray([value], dtype=dtype.jnp_dtype)
        return cls(data=data, validity=jnp.ones((1,), jnp.bool_), dtype=dtype)

    @classmethod
    def const_null(cls, dtype: DType) -> "DeviceColumn":
        data = jnp.zeros((1,), dtype=dtype.jnp_dtype)
        return cls(data=data, validity=jnp.zeros((1,), jnp.bool_), dtype=dtype)

    @property
    def is_const(self) -> bool:
        return self.data.shape[0] == 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceBatch:
    """A batch of named columns + dynamic row count.

    ``n_rows`` is a traced int32 scalar: batches padded to the same bucket
    share one compiled executable regardless of their true length.
    """

    columns: Dict[str, DeviceColumn]
    n_rows: jnp.ndarray  # int32 scalar

    def tree_flatten(self):
        names = tuple(self.columns.keys())
        return (tuple(self.columns.values()), self.n_rows), names

    @classmethod
    def tree_unflatten(cls, names, children):
        cols, n_rows = children
        return cls(columns=dict(zip(names, cols)), n_rows=n_rows)

    @property
    def padded_len(self) -> int:
        for c in self.columns.values():
            if not c.is_const:
                return c.padded_len
        return 1

    def row_mask(self) -> jnp.ndarray:
        """bool [padded_len]: True for real (non-padding) rows."""
        return jnp.arange(self.padded_len, dtype=jnp.int32) < self.n_rows

    def column(self, name: str) -> DeviceColumn:
        return self.columns[name]

    def with_column(self, name: str, col: DeviceColumn) -> "DeviceBatch":
        cols = dict(self.columns)
        cols[name] = col
        return DeviceBatch(columns=cols, n_rows=self.n_rows)

    def select(self, names) -> "DeviceBatch":
        return DeviceBatch(columns={n: self.columns[n] for n in names},
                           n_rows=self.n_rows)


def _dtype_ok(have, want: np.dtype) -> bool:
    """Accept the declared dtype OR a narrower signed int (narrow dict
    codes from ops/encodings: int8/int16 codes under a declared int32
    column must survive staging, not silently widen back)."""
    have = np.dtype(have)
    if have == want:
        return True
    return (have.kind == "i" and want.kind == "i"
            and have.itemsize < want.itemsize)


def from_numpy(arrays: Dict[str, np.ndarray],
               dtypes: Dict[str, DType],
               validity: Optional[Dict[str, np.ndarray]] = None,
               n_rows: Optional[int] = None,
               pad_to: Optional[int] = None) -> DeviceBatch:
    """Build a padded DeviceBatch from host numpy arrays (zero rows allowed)."""
    if n_rows is None:
        n_rows = len(next(iter(arrays.values()))) if arrays else 0
    padded = pad_to if pad_to is not None else bucket_length(max(n_rows, 1))
    cols = {}
    for name, arr in arrays.items():
        dt = dtypes[name]
        val = None if validity is None else validity.get(name)
        if (padded == n_rows and isinstance(arr, jax.Array)
                and _dtype_ok(arr.dtype, np.dtype(dt.np_dtype))):
            # already device-resident at the right dtype and length (the
            # blockcache hands out ready-to-batch device arrays): skip
            # the host round-trip entirely — this is the warm-scan path
            jval = (val if isinstance(val, jax.Array)
                    else jnp.ones(n_rows, jnp.bool_) if val is None
                    else jnp.asarray(np.asarray(val, np.bool_)))
            cols[name] = DeviceColumn(data=arr, validity=jval, dtype=dt)
            continue
        arr = np.asarray(arr)
        if not _dtype_ok(arr.dtype, np.dtype(dt.np_dtype)):
            arr = np.asarray(arr, dtype=dt.np_dtype)
        if val is None:
            val = np.ones(n_rows, dtype=np.bool_)
        else:
            val = np.asarray(val, np.bool_)
        pad_n = padded - n_rows
        if pad_n:
            pad_shape = (pad_n,) + arr.shape[1:]
            # padded-tail fill: zeros, or canary-poisoned under the moqa
            # audit (utils/qa.py) — the tail is dead by contract, so the
            # fill value must never be observable
            arr = np.concatenate([arr, qa.pad_fill(arr.dtype, pad_shape)])
            val = np.concatenate([val, np.zeros(pad_n, dtype=np.bool_)])
        cols[name] = DeviceColumn(data=jnp.asarray(arr),
                                  validity=jnp.asarray(val),
                                  dtype=dt)
    return DeviceBatch(columns=cols, n_rows=jnp.asarray(n_rows, jnp.int32))

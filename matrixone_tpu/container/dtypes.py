"""SQL type system for device-resident columnar data.

Re-designs the reference's type oids (`pkg/container/types/types.go`) for a
TPU target: every type is either
  * fixed-width and device-native (maps to a jnp dtype), or
  * variable-length (VARCHAR/CHAR/TEXT/BLOB), kept host-side as Arrow arrays
    and shipped to device only as dictionary codes (int32) — TPUs cannot
    pointer-chase a varlena `area` (reference: container/vector/vector.go:43),
    so dictionary/offset encoding is the device representation.

DECIMAL is a scaled int64 (DECIMAL64) or scaled int128-as-two-int64
(not yet implemented). Reference: pkg/container/types/decimal.go. Exact
integer arithmetic keeps TPC-H money sums bit-identical to the CPU oracle —
float reduction order issues do not arise.
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp
import numpy as np


class TypeOid(enum.IntEnum):
    BOOL = 10
    INT8 = 20
    INT16 = 21
    INT32 = 22
    INT64 = 23
    UINT8 = 24
    UINT16 = 25
    UINT32 = 26
    UINT64 = 27
    FLOAT32 = 30
    FLOAT64 = 31
    DECIMAL64 = 32
    DATE = 40        # days since unix epoch, int32
    DATETIME = 41    # microseconds since unix epoch, int64
    TIMESTAMP = 42   # microseconds since unix epoch (UTC), int64
    VARCHAR = 50
    CHAR = 51
    TEXT = 52
    BLOB = 53
    JSON = 54
    VECF32 = 60      # fixed-dim float32 embedding (reference: types.T_array_float32)
    VECF64 = 61


_FIXED_NP = {
    TypeOid.BOOL: np.bool_,
    TypeOid.INT8: np.int8,
    TypeOid.INT16: np.int16,
    TypeOid.INT32: np.int32,
    TypeOid.INT64: np.int64,
    TypeOid.UINT8: np.uint8,
    TypeOid.UINT16: np.uint16,
    TypeOid.UINT32: np.uint32,
    TypeOid.UINT64: np.uint64,
    TypeOid.FLOAT32: np.float32,
    TypeOid.FLOAT64: np.float64,
    TypeOid.DECIMAL64: np.int64,
    TypeOid.DATE: np.int32,
    TypeOid.DATETIME: np.int64,
    TypeOid.TIMESTAMP: np.int64,
    TypeOid.VECF32: np.float32,
    TypeOid.VECF64: np.float64,
}

_VARLEN = {TypeOid.VARCHAR, TypeOid.CHAR, TypeOid.TEXT, TypeOid.BLOB, TypeOid.JSON}
_INTS = {TypeOid.INT8, TypeOid.INT16, TypeOid.INT32, TypeOid.INT64,
         TypeOid.UINT8, TypeOid.UINT16, TypeOid.UINT32, TypeOid.UINT64}
_FLOATS = {TypeOid.FLOAT32, TypeOid.FLOAT64}


@dataclasses.dataclass(frozen=True)
class DType:
    """A SQL column type: oid + (width | scale | dim) modifiers."""

    oid: TypeOid
    width: int = 0      # display width / max length for VARCHAR(n)
    scale: int = 0      # decimal scale: stored value = real * 10**scale
    dim: int = 0        # embedding dimension for VECF32/VECF64

    @property
    def is_varlen(self) -> bool:
        return self.oid in _VARLEN

    @property
    def is_numeric(self) -> bool:
        return self.oid in _INTS or self.oid in _FLOATS or self.oid == TypeOid.DECIMAL64

    @property
    def is_integer(self) -> bool:
        return self.oid in _INTS

    @property
    def is_float(self) -> bool:
        return self.oid in _FLOATS

    @property
    def is_vector(self) -> bool:
        return self.oid in (TypeOid.VECF32, TypeOid.VECF64)

    @property
    def np_dtype(self) -> np.dtype:
        if self.is_varlen:
            raise TypeError(f"{self} has no fixed-width numpy dtype")
        return np.dtype(_FIXED_NP[self.oid])

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.np_dtype)

    def __str__(self) -> str:
        sql_names = {TypeOid.INT8: "tinyint", TypeOid.INT16: "smallint",
                     TypeOid.INT32: "int", TypeOid.INT64: "bigint",
                     TypeOid.FLOAT32: "float", TypeOid.FLOAT64: "double"}
        n = sql_names.get(self.oid, self.oid.name.lower())
        if self.oid == TypeOid.DECIMAL64:
            return f"decimal({self.width or 18},{self.scale})"
        if self.oid == TypeOid.VARCHAR and self.width:
            return f"varchar({self.width})"
        if self.is_vector and self.dim:
            return f"{n}({self.dim})"
        return n


_NP_TO_OID = {
    np.dtype(np.bool_): TypeOid.BOOL, np.dtype(np.int8): TypeOid.INT8,
    np.dtype(np.int16): TypeOid.INT16, np.dtype(np.int32): TypeOid.INT32,
    np.dtype(np.int64): TypeOid.INT64, np.dtype(np.uint8): TypeOid.UINT8,
    np.dtype(np.uint16): TypeOid.UINT16, np.dtype(np.uint32): TypeOid.UINT32,
    np.dtype(np.uint64): TypeOid.UINT64,
    np.dtype(np.float32): TypeOid.FLOAT32,
    np.dtype(np.float64): TypeOid.FLOAT64,
}


def from_jnp(dtype) -> DType:
    """Physical array dtype -> a DType with the same agg/compare semantics
    (used to revive spilled columns; logical modifiers are not recovered)."""
    return DType(_NP_TO_OID[np.dtype(dtype)])


# Shorthand constructors (match reference's types.New(...) helpers).
BOOL = DType(TypeOid.BOOL)
INT8 = DType(TypeOid.INT8)
INT16 = DType(TypeOid.INT16)
INT32 = DType(TypeOid.INT32)
INT64 = DType(TypeOid.INT64)
UINT8 = DType(TypeOid.UINT8)
UINT16 = DType(TypeOid.UINT16)
UINT32 = DType(TypeOid.UINT32)
UINT64 = DType(TypeOid.UINT64)
FLOAT32 = DType(TypeOid.FLOAT32)
FLOAT64 = DType(TypeOid.FLOAT64)
DATE = DType(TypeOid.DATE)
DATETIME = DType(TypeOid.DATETIME)
TIMESTAMP = DType(TypeOid.TIMESTAMP)
VARCHAR = DType(TypeOid.VARCHAR, width=65535)
CHAR = DType(TypeOid.CHAR, width=255)
TEXT = DType(TypeOid.TEXT)


def decimal64(precision: int = 18, scale: int = 2) -> DType:
    return DType(TypeOid.DECIMAL64, width=precision, scale=scale)


def varchar(n: int = 65535) -> DType:
    return DType(TypeOid.VARCHAR, width=n)


def vecf32(dim: int) -> DType:
    return DType(TypeOid.VECF32, dim=dim)


def vecf64(dim: int) -> DType:
    return DType(TypeOid.VECF64, dim=dim)


#: numeric promotion lattice for binary ops (reference:
#: pkg/sql/plan/function overload resolution — simplified).
_RANK = [TypeOid.BOOL, TypeOid.INT8, TypeOid.UINT8, TypeOid.INT16, TypeOid.UINT16,
         TypeOid.INT32, TypeOid.UINT32, TypeOid.INT64, TypeOid.UINT64,
         TypeOid.DECIMAL64, TypeOid.FLOAT32, TypeOid.FLOAT64]


def promote(a: DType, b: DType) -> DType:
    """Result type of a numeric binary op."""
    if a.oid == b.oid:
        if a.oid == TypeOid.DECIMAL64:
            return a if a.scale >= b.scale else b
        return a
    ra, rb = _RANK.index(a.oid), _RANK.index(b.oid)
    hi = a if ra >= rb else b
    if TypeOid.DECIMAL64 in (a.oid, b.oid) and hi.oid != TypeOid.DECIMAL64:
        return FLOAT64  # decimal + float -> float64
    return hi


# ---------------------------------------------------------------- epochs
# ONE conversion for date/datetime <-> epoch integers (binder literal
# coercion, INSERT coercion, and clock functions all share it; exact
# integer arithmetic — float total_seconds() truncates ~1% of
# microsecond values by 1us)
import datetime as _dtm

_EPOCH_D = _dtm.date(1970, 1, 1)
_EPOCH_DT = _dtm.datetime(1970, 1, 1)
_US = _dtm.timedelta(microseconds=1)


def epoch_days(d: "_dtm.date") -> int:
    return (d - _EPOCH_D).days


def epoch_micros(dtv: "_dtm.datetime") -> int:
    return (dtv - _EPOCH_DT) // _US


def epoch_days_from_iso(s: str) -> int:
    return epoch_days(_dtm.date.fromisoformat(s.strip()))


def epoch_micros_from_iso(s: str) -> int:
    return epoch_micros(_dtm.datetime.fromisoformat(s.strip()))

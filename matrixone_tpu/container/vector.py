"""Host-side columnar Vector — the CPU half of the container layer.

Redesign of `pkg/container/vector/vector.go:43` for a host that feeds a TPU:
fixed-width data is a numpy array + bool validity; varlena (VARCHAR/TEXT)
is a pyarrow string array. `encode_dictionary()` produces the device
representation of strings: int32 codes + a host dictionary — the TPU never
sees the varlena heap (the reference's `area`), only dense codes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
import pyarrow as pa

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.container.dtypes import DType, TypeOid


@dataclasses.dataclass
class Vector:
    """Host column: fixed-width numpy data or pyarrow varlena, + validity."""

    dtype: DType
    data: Optional[np.ndarray] = None       # fixed-width types
    strings: Optional[pa.Array] = None      # varlena types
    validity: Optional[np.ndarray] = None   # bool; None => all valid

    def __len__(self) -> int:
        if self.dtype.is_varlen:
            return len(self.strings)
        return len(self.data)

    @classmethod
    def from_values(cls, values, dtype: DType) -> "Vector":
        if dtype.is_varlen:
            arr = pa.array(values, type=pa.string())
            val = None
            if arr.null_count:
                val = ~np.asarray(arr.is_null())
            return cls(dtype=dtype, strings=arr, validity=val)
        values = list(values)
        val = np.array([v is not None for v in values], dtype=np.bool_)
        filled = [v if v is not None else 0 for v in values]
        if dtype.oid == TypeOid.DECIMAL64:
            scaled = [int(round(float(v) * 10 ** dtype.scale)) for v in filled]
            data = np.array(scaled, dtype=np.int64)
        elif dtype.oid == TypeOid.DATE:
            import datetime
            epoch = datetime.date(1970, 1, 1)
            days = [(v - epoch).days if isinstance(v, datetime.date) else int(v)
                    for v in filled]
            data = np.asarray(days, dtype=np.int32)
        elif dtype.oid in (TypeOid.DATETIME, TypeOid.TIMESTAMP):
            import datetime
            epoch = datetime.datetime(1970, 1, 1)
            us = [int((v - epoch).total_seconds() * 1e6)
                  if isinstance(v, datetime.datetime) else int(v)
                  for v in filled]
            data = np.asarray(us, dtype=np.int64)
        else:
            data = np.asarray(filled, dtype=dtype.np_dtype)
        return cls(dtype=dtype, data=data,
                   validity=None if val.all() else val)

    def valid_mask(self) -> np.ndarray:
        if self.validity is not None:
            return self.validity
        return np.ones(len(self), dtype=np.bool_)

    def encode_dictionary(self):
        """-> (codes int32 [n], dictionary list[str]); device ships the codes.

        Null rows get code 0 (masked by validity). The reference's group-by
        hashes raw bytes (container/hashtable/string_hash_map.go); we instead
        dictionary-encode once on host and group by dense codes on device.
        """
        assert self.dtype.is_varlen
        enc = self.strings.dictionary_encode()
        codes = np.asarray(enc.indices.fill_null(0), dtype=np.int32)
        dictionary = enc.dictionary.to_pylist()
        if not dictionary:
            dictionary = [""]
        return codes, dictionary

    def to_pylist(self):
        if self.dtype.is_varlen:
            return self.strings.to_pylist()
        mask = self.valid_mask()
        if self.dtype.oid == TypeOid.DECIMAL64:
            scale = 10 ** self.dtype.scale
            return [int(v) / scale if m else None
                    for v, m in zip(self.data, mask)]
        if self.dtype.oid == TypeOid.DATE:
            import datetime
            epoch = datetime.date(1970, 1, 1)
            return [epoch + datetime.timedelta(days=int(v)) if m else None
                    for v, m in zip(self.data, mask)]
        if self.dtype.oid in (TypeOid.DATETIME, TypeOid.TIMESTAMP):
            import datetime
            epoch = datetime.datetime(1970, 1, 1)
            return [epoch + datetime.timedelta(microseconds=int(v)) if m
                    else None for v, m in zip(self.data, mask)]
        if self.dtype.is_vector:
            return [[float(x) for x in self.data[i]] if mask[i] else None
                    for i in range(len(self))]
        return [self.data[i].item() if mask[i] else None
                for i in range(len(self))]

    # ---- Arrow interop (objectio serialization + client results) ----

    def to_arrow(self) -> pa.Array:
        if self.dtype.is_varlen:
            return self.strings
        mask = None
        if self.validity is not None:
            mask = ~self.validity
        if self.dtype.is_vector:
            n, d = self.data.shape
            flat = pa.array(self.data.reshape(-1))
            return pa.FixedSizeListArray.from_arrays(flat, d)
        return pa.array(self.data, mask=mask)

    @classmethod
    def from_arrow(cls, arr: pa.Array, dtype: DType) -> "Vector":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if dtype.is_varlen:
            if pa.types.is_dictionary(arr.type):
                arr = arr.dictionary_decode()
            val = None
            if arr.null_count:
                val = ~np.asarray(arr.is_null())
            return cls(dtype=dtype, strings=arr.cast(pa.string()), validity=val)
        if dtype.is_vector:
            if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type):
                # '[1,2,3]' literals (CSV / client wire format); empty or
                # NULL cells stay NULL (zero-filled + invalid), never a
                # spurious all-zeros embedding
                rows, valid = [], []
                for s in arr.to_pylist():
                    txt = (s or "").strip()
                    parts = [x for x in txt[1:-1].split(",") if x.strip()] \
                        if txt.startswith("[") else []
                    if parts:
                        rows.append([float(x) for x in parts])
                        valid.append(True)
                    else:
                        rows.append([0.0] * dtype.dim)
                        valid.append(False)
                data = np.asarray(rows, dtype=dtype.np_dtype)
                v = np.asarray(valid, np.bool_)
                return cls(dtype=dtype, data=data,
                           validity=None if v.all() else v)
            d = arr.type.list_size
            data = np.asarray(arr.flatten(), dtype=dtype.np_dtype).reshape(-1, d)
            return cls(dtype=dtype, data=data)
        val = None
        if arr.null_count:
            val = ~np.asarray(arr.is_null())
            arr = arr.fill_null(0)
        data = np.asarray(arr, dtype=dtype.np_dtype)
        return cls(dtype=dtype, data=data, validity=val)


def arrow_type_to_dtype(t: pa.DataType) -> DType:
    m = {pa.bool_(): dt.BOOL, pa.int8(): dt.INT8, pa.int16(): dt.INT16,
         pa.int32(): dt.INT32, pa.int64(): dt.INT64, pa.uint8(): dt.UINT8,
         pa.uint16(): dt.UINT16, pa.uint32(): dt.UINT32, pa.uint64(): dt.UINT64,
         pa.float32(): dt.FLOAT32, pa.float64(): dt.FLOAT64,
         pa.date32(): dt.DATE}
    if t in m:
        return m[t]
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return dt.VARCHAR
    if pa.types.is_fixed_size_list(t):
        return dt.vecf32(t.list_size)
    if pa.types.is_timestamp(t):
        return dt.TIMESTAMP
    raise TypeError(f"unsupported arrow type {t}")

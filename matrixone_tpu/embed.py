"""In-process cluster for tests and embedding (reference: pkg/embed
cluster.go:73 NewCluster — log + TN + N CNs in one process).

Here the "cluster" is: one Engine (storage+txn, the TN/Log role), a wire
server (the CN frontend), a TaskService (background checkpoint runner),
and optionally a TPU compute worker — all with one lifecycle:

    with Cluster(n_sessions=2) as c:
        c.sessions[0].execute("create table t (a bigint)")
        conn = c.connect()        # MySQL-wire client into the same engine
"""

from __future__ import annotations

import shutil
import tempfile
from typing import List, Optional

from matrixone_tpu.frontend.server import MOServer
from matrixone_tpu.frontend.session import Session
from matrixone_tpu.storage.engine import Engine
from matrixone_tpu.storage.fileservice import (LocalFS, MemoryFS,
                                               maybe_record)
from matrixone_tpu.taskservice import TaskService


class Cluster:
    def __init__(self, n_sessions: int = 1, data_dir: Optional[str] = None,
                 wire: bool = True, checkpoint_interval_s: float = 0.0,
                 with_worker: bool = False, with_hakeeper: bool = False,
                 hk_down_after_s: float = 2.0):
        self._tmp = None
        if data_dir == ":tmp:":
            self._tmp = tempfile.mkdtemp(prefix="mo_tpu_")
            fs = LocalFS(self._tmp)
        elif data_dir is not None:
            fs = LocalFS(data_dir)
        else:
            fs = MemoryFS()
        # MO_CRASH_RECORD: journal every storage mutation into the
        # process-global crash journal (utils/crash) so an operator can
        # sweep a captured history offline (tools/mocrash)
        fs = maybe_record(fs, tag="embed")
        self.engine = (Engine.open(fs) if fs.exists("meta/manifest.json")
                       or fs.exists("wal/wal.log") else Engine(fs))
        self.sessions: List[Session] = [Session(catalog=self.engine)
                                        for _ in range(n_sessions)]
        self.tasks = TaskService(self.engine).start()
        # MO_MERGE_SCHED=1: background compaction/checkpoint/GC loop
        # (storage/merge_sched) rides the embedded engine's lifecycle
        from matrixone_tpu.storage import merge_sched
        self.merge_scheduler = merge_sched.maybe_start(self.engine)
        if checkpoint_interval_s > 0:
            resumed = any(t["name"] == "auto-checkpoint"
                          for t in self.tasks._tasks.values())
            if not resumed:
                self.tasks.submit("auto-checkpoint", "checkpoint",
                                  interval_s=checkpoint_interval_s)
        self.server = MOServer(engine=self.engine, port=0).start() \
            if wire else None
        self.worker = None
        self.worker_client = None
        if with_worker:
            from matrixone_tpu.worker import TpuWorkerServer, WorkerClient
            self.worker = TpuWorkerServer(port=0).start()
            self.worker_client = WorkerClient(f"127.0.0.1:{self.worker.port}")
        self.hakeeper = None
        self._ha_agents = []
        if with_hakeeper:
            from matrixone_tpu.hakeeper import HAClient, HAKeeper
            import json as _json
            self.hakeeper = HAKeeper(
                down_after_s=hk_down_after_s,
                persist=lambda snap: fs.write(
                    "meta/hakeeper.json", _json.dumps(snap).encode()),
                restore=lambda: (_json.loads(
                    fs.read("meta/hakeeper.json").decode())
                    if fs.exists("meta/hakeeper.json") else None)
            ).start()
            hk_addr = ("127.0.0.1", self.hakeeper.port)
            eng = self.engine
            self._ha_agents.append(HAClient(
                hk_addr, "tn", "tn-0",
                stats_fn=lambda: {"committed_ts": eng.committed_ts,
                                  "tables": len(eng.tables)}).start())
            for i, _s in enumerate(self.sessions):
                self._ha_agents.append(
                    HAClient(hk_addr, "cn", f"cn-{i}").start())
            if self.server is not None:
                self._ha_agents.append(HAClient(
                    hk_addr, "server", "server-0",
                    service_addr=f"127.0.0.1:{self.server.port}").start())

    # ------------------------------------------------------------- access
    def session(self, i: int = 0) -> Session:
        return self.sessions[i]

    def connect(self):
        """New wire-protocol connection (matrixone_tpu.client)."""
        from matrixone_tpu import client
        assert self.server is not None, "cluster started with wire=False"
        return client.connect(port=self.server.port)

    def checkpoint(self):
        self.engine.checkpoint()

    # ---------------------------------------------------------- lifecycle
    def close(self, cleanup: bool = False):
        for s in self.sessions:
            s.close()
        # flush the statement recorder's buffered tail before teardown
        # (utils/trace.py buffers flush_every records)
        self.engine.close()
        for a in self._ha_agents:
            a.stop()
        if self.hakeeper is not None:
            self.hakeeper.stop()
        self.tasks.stop()
        if self.merge_scheduler is not None:
            self.merge_scheduler.stop()
        if self.server is not None:
            self.server.stop()
        if self.worker_client is not None:
            self.worker_client.close()
        if self.worker is not None:
            self.worker.stop()
        if self._tmp is not None and cleanup:
            shutil.rmtree(self._tmp, ignore_errors=True)

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc):
        self.close(cleanup=True)

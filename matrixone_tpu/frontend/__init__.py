from matrixone_tpu.frontend.session import Result, Session

__all__ = ["Result", "Session"]

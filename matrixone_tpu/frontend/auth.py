"""Accounts, users, roles, privileges, tenant scoping.

Reference analogue: `pkg/frontend/authenticate.go` + the mo_account /
mo_user / mo_role / mo_role_privs system tables — MatrixOne logs in as
`account:user`, resolves privileges through roles, and scopes every
catalog object to the account (tenant).

Redesign here:
  * auth state lives in ordinary engine tables (mo_account, mo_user,
    mo_role, mo_user_role, mo_priv) — so it WAL-logs, checkpoints, and
    replicates to every CN through the logtail like any other data (the
    reference stores them in mo_catalog for the same reason);
  * an in-memory mirror rebuilds lazily and is invalidated by the
    engine's logtail subscriber hook, so per-statement privilege checks
    never rescan tables;
  * tenant scoping is a catalog wrapper (`ScopedCatalog`) that maps
    `name` -> `account$name` at the engine boundary — one shared
    catalog, per-account namespaces, exactly the reference's account_id
    scoping expressed as a prefix.
"""

from __future__ import annotations

import dataclasses
import threading

from matrixone_tpu.utils import san
from typing import Dict, List, Optional, Set

import numpy as np

from matrixone_tpu.container.dtypes import VARCHAR
from matrixone_tpu.storage.engine import TableMeta

SYS_ACCOUNT = "sys"
ADMIN_ROLE = "accountadmin"
PRIVS = frozenset(["select", "insert", "update", "delete", "create",
                   "drop", "all"])

_AUTH_TABLES = {
    "mo_account": [("name", VARCHAR), ("admin_user", VARCHAR)],
    "mo_user": [("account", VARCHAR), ("name", VARCHAR),
                ("stage2", VARCHAR)],
    "mo_role": [("account", VARCHAR), ("name", VARCHAR)],
    "mo_user_role": [("account", VARCHAR), ("user", VARCHAR),
                     ("role", VARCHAR)],
    "mo_priv": [("account", VARCHAR), ("role", VARCHAR),
                ("obj", VARCHAR), ("priv", VARCHAR)],
}


class AuthError(RuntimeError):
    pass


@dataclasses.dataclass
class AuthContext:
    account: str
    user: str
    is_admin: bool           # account admin (or sys root): full account


def _stage2_hex(password: str) -> str:
    from matrixone_tpu.frontend.server import password_stage2
    return password_stage2(password).hex() if password else ""


class AccountManager:
    """Durable account/user/role/privilege state + cached mirror."""

    def __init__(self, engine,
                 seed_users: Optional[Dict[str, bytes]] = None):
        """`seed_users` maps sys-account usernames to stage2 hashes (the
        MOServer `users` config); 'root' defaults to an empty password."""
        self.engine = engine
        self._lock = san.lock("AccountManager._lock")
        self._mirror = None
        self._gen = 0          # bumped on every auth-table change
        self._bootstrap(dict(seed_users or {}))
        engine.subscribe(self._on_change)

    # ------------------------------------------------------- bootstrap
    def _bootstrap(self, seed: Dict[str, bytes]):
        eng = self.engine
        for name, schema in _AUTH_TABLES.items():
            if name not in eng.tables:
                eng.create_table(TableMeta(name, list(schema), []),
                                 if_not_exists=True)
        if not self._rows("mo_account"):
            # the sys account's admin is the first seeded user; a config
            # that omits 'root' gets NO root login (no silent
            # passwordless backdoor)
            admin = "root" if "root" in seed or not seed \
                else next(iter(seed))
            self._insert("mo_account", {"name": SYS_ACCOUNT,
                                        "admin_user": admin})
            self._insert("mo_role", {"account": SYS_ACCOUNT,
                                     "name": ADMIN_ROLE})
            self._insert("mo_priv", {"account": SYS_ACCOUNT,
                                     "role": ADMIN_ROLE, "obj": "*",
                                     "priv": "all"})
            if not seed:
                seed = {"root": b""}     # default config: root, empty pw
        for user, stage2 in seed.items():
            row = self._user_row(SYS_ACCOUNT, user)
            if row is None:
                self._insert("mo_user", {"account": SYS_ACCOUNT,
                                         "name": user,
                                         "stage2": stage2.hex()})
                self._insert("mo_user_role", {"account": SYS_ACCOUNT,
                                              "user": user,
                                              "role": ADMIN_ROLE})
            elif row["stage2"] != stage2.hex():
                # restart with a changed configured password: the config
                # wins (replace the stored hash)
                self._delete("mo_user", {"account": SYS_ACCOUNT,
                                         "name": user})
                self._insert("mo_user", {"account": SYS_ACCOUNT,
                                         "name": user,
                                         "stage2": stage2.hex()})

    # ------------------------------------------------------- table io
    def _rows(self, table: str) -> List[dict]:
        t = self.engine.get_table(table)
        cols = [c for c, _ in t.meta.schema]
        out: List[dict] = []
        for arrays, validity, dicts, n in t.iter_chunks(
                cols + ["__rowid"], 1 << 20):
            decoded = {}
            for c in cols:
                d = dicts.get(c, [])
                decoded[c] = [d[int(v)] if ok and 0 <= int(v) < len(d)
                              else None
                              for v, ok in zip(np.asarray(arrays[c]),
                                               validity[c])]
            gids = np.asarray(arrays["__rowid"])
            for i in range(n):
                row = {c: decoded[c][i] for c in cols}
                row["__gid"] = int(gids[i])
                out.append(row)
        return out

    def _insert(self, table: str, row: Dict[str, str]) -> None:
        t = self.engine.get_table(table)
        strings = {c: (np.zeros(1, np.int32), [v if v is not None else ""])
                   for c, v in row.items()}
        t.insert_numpy({}, strings=strings)
        # own writes invalidate directly: the engine subscriber hook only
        # registers after bootstrap, and a cached pre-write mirror must
        # never survive the write that outdated it
        self._mirror = None
        self._gen += 1

    def _delete(self, table: str, match: Dict[str, str]) -> int:
        gids = [r["__gid"] for r in self._rows(table)
                if all(r.get(k) == v for k, v in match.items())]
        if gids:
            self.engine.commit_txn(None, {}, {
                table: np.asarray(gids, np.int64)})
            self._mirror = None
            self._gen += 1
        return len(gids)

    # --------------------------------------------------------- mirror
    def _on_change(self, ts, table, kind, payload) -> None:
        if table in _AUTH_TABLES:
            self._mirror = None
            self._gen += 1

    def _m(self) -> dict:
        m = self._mirror
        if m is not None:
            return m
        with self._lock:
            if self._mirror is not None:
                return self._mirror
            while True:
                m = self._build_mirror()
                # a write that landed mid-rebuild already invalidated the
                # cache; installing the stale snapshot would honor
                # revoked privileges until the NEXT change — rebuild
                if self._gen == m["_gen"]:
                    self._mirror = m
                    return m

    def _build_mirror(self) -> dict:
        gen = self._gen
        m = {
            "accounts": {r["name"]: r for r in self._rows("mo_account")},
            "users": {(r["account"], r["name"]): r
                      for r in self._rows("mo_user")},
            "roles": {(r["account"], r["name"]) for r
                      in self._rows("mo_role")},
            "user_roles": {},
            "privs": {},
        }
        for r in self._rows("mo_user_role"):
            m["user_roles"].setdefault(
                (r["account"], r["user"]), set()).add(r["role"])
        for r in self._rows("mo_priv"):
            m["privs"].setdefault(
                (r["account"], r["role"]), []).append(
                    (r["obj"], r["priv"]))
        m["_gen"] = gen
        return m

    # ----------------------------------------------------------- login
    def resolve_login(self, username: str):
        """'account:user' (or plain 'user' = sys) -> (account, user,
        stage2 bytes) or None."""
        if ":" in username:
            account, user = username.split(":", 1)
        else:
            account, user = SYS_ACCOUNT, username
        row = self._m()["users"].get((account, user))
        if row is None:
            return None
        stage2 = bytes.fromhex(row["stage2"]) if row["stage2"] else b""
        return account, user, stage2

    def context_for(self, account: str, user: str) -> AuthContext:
        m = self._m()
        acct = m["accounts"].get(account)
        is_admin = bool(acct and acct["admin_user"] == user) or \
            ADMIN_ROLE in m["user_roles"].get((account, user), set())
        return AuthContext(account=account, user=user, is_admin=is_admin)

    def _user_row(self, account: str, user: str):
        return self._m()["users"].get((account, user))

    # ------------------------------------------------------ management
    def create_account(self, name: str, admin_user: str,
                       admin_password: str,
                       if_not_exists: bool = False) -> None:
        if name in self._m()["accounts"]:
            if if_not_exists:
                return
            raise AuthError(f"account {name!r} already exists")
        if "$" in name or ":" in name:
            raise AuthError("account names may not contain '$' or ':'")
        self._insert("mo_account", {"name": name,
                                    "admin_user": admin_user})
        self._insert("mo_user", {"account": name, "name": admin_user,
                                 "stage2": _stage2_hex(admin_password)})
        self._insert("mo_role", {"account": name, "name": ADMIN_ROLE})
        self._insert("mo_user_role", {"account": name, "user": admin_user,
                                      "role": ADMIN_ROLE})
        self._insert("mo_priv", {"account": name, "role": ADMIN_ROLE,
                                 "obj": "*", "priv": "all"})

    def drop_account(self, name: str) -> None:
        if name == SYS_ACCOUNT:
            raise AuthError("cannot drop the sys account")
        if name not in self._m()["accounts"]:
            raise AuthError(f"no such account {name!r}")
        for table in ("mo_priv", "mo_user_role", "mo_role", "mo_user",
                      "mo_account"):
            self._delete(table, {"account": name} if table != "mo_account"
                         else {"name": name})
        # the tenant's tables go with it
        prefix = f"{name}$"
        for tname in [t for t in self.engine.tables if
                      t.startswith(prefix)]:
            self.engine.drop_table(tname, if_exists=True)

    def create_user(self, account: str, name: str, password: str,
                    if_not_exists: bool = False) -> None:
        if self._user_row(account, name):
            if if_not_exists:
                return
            raise AuthError(f"user {name!r} already exists")
        self._insert("mo_user", {"account": account, "name": name,
                                 "stage2": _stage2_hex(password)})

    def drop_user(self, account: str, name: str) -> None:
        acct = self._m()["accounts"].get(account)
        if acct and acct["admin_user"] == name:
            raise AuthError("cannot drop the account admin")
        if not self._delete("mo_user", {"account": account, "name": name}):
            raise AuthError(f"no such user {name!r}")
        self._delete("mo_user_role", {"account": account, "user": name})

    def create_role(self, account: str, name: str) -> None:
        if (account, name) in self._m()["roles"]:
            raise AuthError(f"role {name!r} already exists")
        self._insert("mo_role", {"account": account, "name": name})

    def drop_role(self, account: str, name: str) -> None:
        if name == ADMIN_ROLE:
            raise AuthError("cannot drop the admin role")
        if not self._delete("mo_role", {"account": account, "name": name}):
            raise AuthError(f"no such role {name!r}")
        self._delete("mo_user_role", {"account": account, "role": name})
        self._delete("mo_priv", {"account": account, "role": name})

    def grant_priv(self, account: str, privs: List[str], obj: str,
                   role: str) -> None:
        if (account, role) not in self._m()["roles"]:
            raise AuthError(f"no such role {role!r}")
        for p in privs:
            if p not in PRIVS:
                raise AuthError(f"unknown privilege {p!r}")
            self._insert("mo_priv", {"account": account, "role": role,
                                     "obj": obj, "priv": p})

    def revoke_priv(self, account: str, privs: List[str], obj: str,
                    role: str) -> None:
        for p in privs:
            self._delete("mo_priv", {"account": account, "role": role,
                                     "obj": obj, "priv": p})

    def grant_role(self, account: str, role: str, user: str) -> None:
        if (account, role) not in self._m()["roles"]:
            raise AuthError(f"no such role {role!r}")
        if not self._user_row(account, user):
            raise AuthError(f"no such user {user!r}")
        self._insert("mo_user_role", {"account": account, "user": user,
                                      "role": role})

    def revoke_role(self, account: str, role: str, user: str) -> None:
        self._delete("mo_user_role", {"account": account, "user": user,
                                      "role": role})

    def grants_for(self, account: str, user: str) -> List[tuple]:
        m = self._m()
        out = []
        for role in sorted(m["user_roles"].get((account, user), set())):
            for obj, priv in m["privs"].get((account, role), []):
                out.append((role, obj, priv))
        return out

    # ----------------------------------------------------------- check
    def check(self, ctx: AuthContext, priv: str, obj: str = "*") -> None:
        """Raise AuthError unless ctx may exercise `priv` on `obj`
        (a table name, or '*' for account-level rights)."""
        if ctx.is_admin:
            return
        m = self._m()
        for role in m["user_roles"].get((ctx.account, ctx.user), set()):
            for gobj, gpriv in m["privs"].get((ctx.account, role), []):
                if gobj not in ("*", obj):
                    continue
                if gpriv == "all" or gpriv == priv:
                    return
        raise AuthError(
            f"access denied: user {ctx.user!r} of account "
            f"{ctx.account!r} lacks {priv.upper()} on {obj!r}")


class ScopedCatalog:
    """The engine surface a tenant session sees: every object name maps
    to `account$name` at this boundary, so one shared catalog carries
    per-account namespaces (the reference's account_id scoping)."""

    def __init__(self, inner, account: str):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_acct", account)
        object.__setattr__(self, "_prefix", f"{account}$")
        from matrixone_tpu.queryservice import registry_for
        registry_for(inner)          # share one processlist with root

    def _scope(self, name: str) -> str:
        return name if name.startswith(self._prefix) \
            else self._prefix + name

    def _unscope(self, name: str) -> str:
        return name[len(self._prefix):] \
            if name.startswith(self._prefix) else name

    def __getattr__(self, k):
        return getattr(object.__getattribute__(self, "_inner"), k)

    def __setattr__(self, k, v):
        setattr(object.__getattribute__(self, "_inner"), k, v)

    # ----------------------------------------------------- table reads
    @property
    def tables(self):
        return {self._unscope(k): v
                for k, v in self._inner.tables.items()
                if k.startswith(self._prefix)}

    def get_table(self, name: str):
        return self._inner.get_table(self._scope(name))

    def get_table_meta(self, name: str):
        return self._inner.get_table_meta(self._scope(name))

    # ----------------------------------------------------- table writes
    def _scoped_meta(self, meta: TableMeta) -> TableMeta:
        return dataclasses.replace(meta, name=self._scope(meta.name))

    def create_table(self, meta, **kw):
        return self._inner.create_table(self._scoped_meta(meta), **kw)

    def drop_table(self, name, *a, **kw):
        return self._inner.drop_table(self._scope(name), *a, **kw)

    def create_external(self, meta, *a, **kw):
        return self._inner.create_external(self._scoped_meta(meta),
                                           *a, **kw)

    def commit_write(self, table, arrays, validity):
        return self._inner.commit_write(self._scope(table), arrays,
                                        validity)

    def commit_txn(self, snapshot_ts, inserts, deletes):
        return self._inner.commit_txn(
            snapshot_ts,
            {self._scope(t): v for t, v in inserts.items()},
            {self._scope(t): v for t, v in deletes.items()})

    def merge_table(self, name, *a, **kw):
        return self._inner.merge_table(self._scope(name), *a, **kw)

    def restore_table(self, table, ts):
        return self._inner.restore_table(self._scope(table), ts)

    def register_dynamic(self, name, sql, **kw):
        return self._inner.register_dynamic(self._scope(name), sql, **kw)

    def mark_source(self, name, **kw):
        return self._inner.mark_source(self._scope(name), **kw)

    # -------------------------------------------------------- indexes
    # index metas keep their SCOPED names internally (plans carry them
    # through to the runtime lookups on the raw dict)
    def register_index(self, meta) -> None:
        meta.name = self._scope(meta.name)
        meta.table = self._scope(meta.table)
        self._inner.register_index(meta)

    def indexes_on(self, table: str):
        return self._inner.indexes_on(self._scope(table))

"""MySQL-aware proxy: connection routing across backend servers.

Reference analogue: `pkg/proxy` (24k LoC — tenant/label routing,
connection migration, scale-driven rebalance), collapsed to the core:
accept MySQL clients, pick a backend by least-connections (with optional
draining for scale-in), and relay bytes both ways. Because the protocol
is stateful per connection, "migration" is implemented as drain-and-
reconnect: a draining backend stops receiving new connections and the
proxy reports when it has fully quiesced.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple


class Backend:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.active = 0
        self.draining = False
        self.down_until = 0.0      # health cooldown after connect failure

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)


class MOProxy:
    def __init__(self, backends: List[Tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0):
        self.backends = [Backend(h, p) for h, p in backends]
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._stopping = threading.Event()

    # ----------------------------------------------------------- routing
    def _pick(self, exclude=()) -> Optional[Backend]:
        now = time.monotonic()
        with self._lock:
            live = [b for b in self.backends
                    if not b.draining and b.down_until <= now
                    and b not in exclude]
            if not live:
                return None
            b = min(live, key=lambda x: x.active)
            b.active += 1
            return b

    def add_backend(self, host: str, port: int) -> None:
        with self._lock:
            self.backends.append(Backend(host, port))

    def drain(self, host: str, port: int) -> None:
        """Scale-in: stop routing new connections to this backend."""
        with self._lock:
            for b in self.backends:
                if b.address == (host, port):
                    b.draining = True
                    return
        raise KeyError(f"no such backend {host}:{port}")

    def drained(self, host: str, port: int) -> bool:
        with self._lock:
            for b in self.backends:
                if b.address == (host, port):
                    return b.draining and b.active == 0
        raise KeyError(f"no such backend {host}:{port}")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {f"{b.host}:{b.port}": b.active for b in self.backends}

    # ------------------------------------------------------------ server
    def start(self) -> "MOProxy":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self

    def stop(self):
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                client, _ = self._sock.accept()
            except OSError:
                if self._stopping.is_set():
                    return
                continue   # transient (e.g. ECONNABORTED): keep serving
            threading.Thread(target=self._serve_conn, args=(client,),
                             daemon=True).start()

    def _serve_conn(self, client: socket.socket):
        """Pick a backend, retrying others when one refuses (dead backends
        go on a health cooldown so they stop winning least-connections)."""
        tried = []
        while True:
            backend = self._pick(exclude=tried)
            if backend is None:
                client.close()
                return
            try:
                upstream = socket.create_connection(backend.address,
                                                    timeout=5)
                upstream.settimeout(None)   # the 5s budget was for CONNECT
                break                        # only; sessions may idle
            except OSError:
                with self._lock:
                    backend.active -= 1
                    backend.down_until = time.monotonic() + 5.0
                tried.append(backend)
        self._relay(client, backend, upstream)

    def _relay(self, client: socket.socket, backend: Backend,
               upstream: socket.socket):
        def pump(src, dst):
            """One direction; on EOF half-close the peer's write side only
            so in-flight data in the other direction still drains."""
            try:
                while True:
                    data = src.recv(1 << 16)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump, args=(upstream, client),
                             daemon=True)
        t.start()
        pump(client, upstream)      # client->upstream runs in this thread
        t.join()
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass
        with self._lock:
            backend.active -= 1

"""MySQL-aware proxy: connection routing across backend servers.

Reference analogue: `pkg/proxy` (24k LoC — tenant/label routing,
connection migration, scale-driven rebalance). Two proxies live here:

  * `MOProxy` — the byte relay: least-connections routing + draining
    (drain-and-reconnect semantics, no migration);
  * `SessionProxy` — LIVE CONNECTION MIGRATION (VERDICT r4 Next #8;
    reference: pkg/proxy migrate.go): the proxy speaks the protocol
    per connection, tracking session state it can replay — SET
    statements, open-transaction markers, prepared statements. When a
    backend drains, each of its sessions moves to another CN at its
    next idle point (no in-flight command, no open txn): the proxy
    logs in to the new backend, replays the SETs, re-prepares every
    statement (keeping the CLIENT-visible statement ids stable via an
    id-translation layer), and swaps the upstream — the client sees
    nothing.
"""

from __future__ import annotations

import socket
import struct
import threading

from matrixone_tpu.utils import san
from matrixone_tpu.utils.lifecycle import ServiceThreads
import time
from typing import Dict, List, Optional, Tuple


class Backend:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.active = 0
        self.draining = False
        self.down_until = 0.0      # health cooldown after connect failure

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)


class MOProxy:
    def __init__(self, backends: List[Tuple[str, int]],
                 host: str = "127.0.0.1", port: int = 0,
                 max_conns: int = 0):
        import os
        self.backends = [Backend(h, p) for h, p in backends]
        self.host = host
        self.port = port
        #: connection-level admission (serving layer, reference: proxy
        #: tier connection caps): per-backend concurrent session cap —
        #: when every backend is full a NEW client is refused instead of
        #: piling more sessions onto overloaded CNs. 0 = unlimited.
        self.max_conns = max_conns or int(
            os.environ.get("MO_PROXY_MAX_CONNS", "0") or 0)
        self._sock: Optional[socket.socket] = None
        self._lock = san.lock("MOProxy._lock")
        self._stopping = threading.Event()
        #: track + interrupt + deadline-join every thread this proxy
        #: starts (shared service discipline; mosan leak checker gates)
        self._svc = ServiceThreads("moproxy")

    # ----------------------------------------------------------- routing
    def _pick(self, exclude=()) -> Optional[Backend]:
        now = time.monotonic()
        with self._lock:
            live = [b for b in self.backends
                    if not b.draining and b.down_until <= now
                    and b not in exclude
                    and (self.max_conns <= 0
                         or b.active < self.max_conns)]
            if not live:
                return None
            b = min(live, key=lambda x: x.active)
            b.active += 1
            return b

    def add_backend(self, host: str, port: int) -> None:
        with self._lock:
            self.backends.append(Backend(host, port))

    def drain(self, host: str, port: int) -> None:
        """Scale-in: stop routing new connections to this backend."""
        with self._lock:
            for b in self.backends:
                if b.address == (host, port):
                    b.draining = True
                    return
        raise KeyError(f"no such backend {host}:{port}")

    def drained(self, host: str, port: int) -> bool:
        with self._lock:
            for b in self.backends:
                if b.address == (host, port):
                    return b.draining and b.active == 0
        raise KeyError(f"no such backend {host}:{port}")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {f"{b.host}:{b.port}": b.active for b in self.backends}

    # ------------------------------------------------------------ server
    def start(self) -> "MOProxy":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._svc.spawn_accept(self._accept_loop)
        return self

    def stop(self, grace: float = 5.0):
        """Stop serving and JOIN every thread this proxy started, with a
        deadline: the accept loop (shutdown() — close() alone does not
        wake a blocked accept) and the per-connection relays (their
        sockets are shut down so blocked recv()s return)."""
        self._stopping.set()
        self._svc.shutdown(self._sock, grace=grace)

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                client, _ = self._sock.accept()
            except OSError:
                if self._stopping.is_set():
                    return
                continue   # transient (e.g. ECONNABORTED): keep serving
            self._svc.spawn_handler(self._serve_conn, client)

    def _connect(self, exclude=()):
        """Pick a backend and open an upstream socket, retrying others
        when one refuses (dead backends go on a health cooldown so they
        stop winning least-connections). -> (backend, sock) or None."""
        tried = list(exclude)
        while True:
            backend = self._pick(exclude=tried)
            if backend is None:
                return None
            try:
                upstream = socket.create_connection(backend.address,
                                                    timeout=5)
                upstream.settimeout(None)   # the 5s budget was for CONNECT
                return backend, upstream     # only; sessions may idle
            except OSError:
                with self._lock:
                    backend.active -= 1
                    backend.down_until = time.monotonic() + 5.0
                tried.append(backend)

    def _serve_conn(self, client: socket.socket):
        got = self._connect()
        if got is None:
            from matrixone_tpu.utils import metrics as _M
            _M.proxy_conn_refused.inc()
            client.close()
            return
        backend, upstream = got
        self._relay(client, backend, upstream)

    def _relay(self, client: socket.socket, backend: Backend,
               upstream: socket.socket):
        def pump(src, dst):
            """One direction; on EOF half-close the peer's write side only
            so in-flight data in the other direction still drains."""
            try:
                while True:
                    data = src.recv(1 << 16)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump, args=(upstream, client),
                             daemon=True)
        t.start()
        pump(client, upstream)      # client->upstream runs in this thread
        t.join()
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass
        with self._lock:
            backend.active -= 1


# =====================================================================
# SessionProxy: protocol-aware routing with live connection migration
# =====================================================================

_COM_QUIT = 0x01
_COM_QUERY = 0x03
_COM_STMT_PREPARE = 0x16
_COM_STMT_EXECUTE = 0x17
_COM_STMT_SEND_LONG_DATA = 0x18
_COM_STMT_CLOSE = 0x19
_COM_STMT_RESET = 0x1A

#: commands the server answers with NOTHING (MySQL protocol): waiting
#: for a response here would wedge the relay and hang the client
_NO_RESPONSE_CMDS = frozenset({_COM_STMT_CLOSE, _COM_STMT_SEND_LONG_DATA})


def _read_pkt(sock: socket.socket) -> Optional[bytes]:
    """One MySQL packet INCLUDING its 4-byte header (None on EOF)."""
    hdr = b""
    while len(hdr) < 4:
        part = sock.recv(4 - len(hdr))
        if not part:
            return None
        hdr += part
    ln = int.from_bytes(hdr[:3], "little")
    body = b""
    while len(body) < ln:
        part = sock.recv(ln - len(body))
        if not part:
            return None
        body += part
    return hdr + body


def _is_eof(pkt: bytes) -> bool:
    return len(pkt) - 4 < 9 and pkt[4] == 0xFE


class _Session:
    """Replayable state of one proxied connection."""

    def __init__(self):
        self.user = "root"
        #: var name -> full SET statement (last write wins: replay must
        #: not grow with session age)
        self.sets: Dict[str, str] = {}
        self.stmts: Dict[int, str] = {}           # client id -> sql
        self.id_map: Dict[int, int] = {}          # client id -> backend id
        self.txn_open = False
        self.migrations = 0


class SessionProxy(MOProxy):
    """MOProxy + per-connection protocol awareness + migration."""

    def _serve_conn(self, client: socket.socket):
        got = self._connect()
        if got is None:
            from matrixone_tpu.utils import metrics as _M
            _M.proxy_conn_refused.inc()
            client.close()
            return
        # migration rebinds the session to a new backend/upstream: the
        # cleanup in the finallys must see the CURRENT pair, not the
        # original, or the old backend gets double-decremented and the
        # new one leaks (drained() would flip back to False forever)
        cur = {"backend": got[0], "upstream": got[1]}
        try:
            self._speak(client, cur)
        finally:
            with self._lock:
                cur["backend"].active -= 1

    # ------------------------------------------------------- handshake
    def _speak(self, client, cur):
        upstream = cur["upstream"]
        sess = _Session()
        try:
            greet = _read_pkt(upstream)            # server greeting
            if greet is None:
                client.close()
                return
            client.sendall(greet)
            auth = _read_pkt(client)               # HandshakeResponse41
            if auth is None:
                upstream.close()
                return
            sess.user = self._parse_user(auth)
            upstream.sendall(auth)
            result = _read_pkt(upstream)           # OK / ERR
            if result is None:
                client.close()
                return
            client.sendall(result)
            if result[4] == 0xFF:
                return                             # auth failed
            self._command_loop(sess, client, cur)
        except (OSError, ConnectionError):
            pass
        finally:
            for s in (client, cur["upstream"]):
                try:
                    s.close()
                except OSError:
                    pass

    @staticmethod
    def _parse_user(auth_pkt: bytes) -> str:
        try:
            pkt = auth_pkt[4:]
            pos = 4 + 4 + 1 + 23
            end = pkt.index(b"\x00", pos)
            return pkt[pos:end].decode("utf-8", "replace")
        except (ValueError, IndexError):
            return "root"

    # ---------------------------------------------------- command loop
    def _command_loop(self, sess, client, cur):
        from matrixone_tpu.utils.fault import INJECTOR
        while True:
            backend, upstream = cur["backend"], cur["upstream"]
            if backend.draining and not sess.txn_open:
                moved = self._migrate(sess, backend, upstream)
                if moved is not None:
                    self._swap_upstream(cur, moved)
                    backend, upstream = moved
            pkt = _read_pkt(client)
            if pkt is None or pkt[4] == _COM_QUIT:
                if pkt is not None:
                    try:
                        cur["upstream"].sendall(pkt)
                    except OSError:
                        pass
                return
            cmd = pkt[4]
            orig = pkt          # pre-rewrite: a failover re-rewrites
                                # stmt ids against the NEW backend's map
            # capture BEFORE tracking mutates it: a COMMIT flips
            # txn_open to False during _track_and_rewrite, but its
            # transaction (still open on the dying backend) is exactly
            # what a failover would silently lose — the guard must see
            # the state the command STARTED in
            txn_was_open = sess.txn_open
            for attempt in (0, 1):
                backend, upstream = cur["backend"], cur["upstream"]
                wire = self._track_and_rewrite(sess, cmd, orig)
                sent_to_client: list = []
                try:
                    if INJECTOR.trigger("proxy.relay") == "drop":
                        # chaos drill: the backing CN's socket dies
                        # mid-session, right under this command
                        try:
                            upstream.close()
                        except OSError:
                            pass
                    upstream.sendall(wire)
                    if cmd not in _NO_RESPONSE_CMDS:
                        self._relay_response(sess, cmd, wire, client,
                                             upstream, sent_to_client)
                    break
                except (ConnectionError, OSError):
                    # Backend lost mid-command. Fail over ONCE, and only
                    # when a replay is invisible AND safe: no response
                    # bytes relayed yet, no open transaction (whose
                    # workspace died with the backend), and a command
                    # whose re-send cannot double-apply — the backend
                    # may have executed it before dying, and unlike the
                    # CN->TN lane the wire protocol carries no
                    # idempotency rid, so mutations surface the error
                    # to the client instead of risking a double-apply.
                    if attempt or sent_to_client or txn_was_open \
                            or sess.txn_open \
                            or not self._replay_safe(sess, cmd, orig):
                        raise
                    with self._lock:
                        backend.down_until = time.monotonic() + 5.0
                    moved = self._migrate(sess, backend, upstream)
                    if moved is None:
                        raise
                    from matrixone_tpu.utils import metrics as _M
                    from matrixone_tpu.utils import motrace as _mt
                    _M.proxy_failovers.inc()
                    # MySQL wire carries no trace ctx, so the failover
                    # records as its own head-sampled marker trace in
                    # the proxy lane (utils/motrace.py)
                    _new_be = f"{moved[0].host}:{moved[0].port}"
                    _mt.instant("proxy.failover", proc="proxy",
                                backend=_new_be)
                    self._swap_upstream(cur, moved)

    #: statement prefixes whose re-execution is side-effect free
    _SAFE_SQL = ("select", "show", "desc", "describe", "explain", "set",
                 "use", "begin", "start transaction")

    def _replay_safe(self, sess, cmd: int, pkt: bytes) -> bool:
        """May this command be re-sent to a NEW backend when the old one
        died mid-relay? Only when executing it twice is harmless — the
        old backend may have applied it before the connection died."""
        if cmd == _COM_STMT_PREPARE:
            return True                  # re-prepare is idempotent
        if cmd == _COM_QUERY:
            sql = pkt[5:].decode("utf-8", "replace").lstrip().lower()
            return sql.startswith(self._SAFE_SQL)
        if cmd == _COM_STMT_EXECUTE:
            cid = int.from_bytes(pkt[5:9], "little")
            sql = (sess.stmts.get(cid) or "").lstrip().lower()
            return sql.startswith(self._SAFE_SQL)
        return False   # SEND_LONG_DATA, CLOSE, RESET, unknown: no replay

    def _swap_upstream(self, cur, moved) -> None:
        try:
            cur["upstream"].close()
        except OSError:
            pass
        with self._lock:
            cur["backend"].active -= 1
        cur["backend"], cur["upstream"] = moved
        from matrixone_tpu.utils.sync import notify_waiters
        notify_waiters()

    def _track_and_rewrite(self, sess, cmd: int, pkt: bytes) -> bytes:
        if cmd == _COM_QUERY:
            raw = pkt[5:].decode("utf-8", "replace").strip()
            sql = raw.lower()
            if sql.startswith("begin") or sql.startswith(
                    "start transaction"):
                sess.txn_open = True
            elif sql.startswith(("commit", "rollback")):
                sess.txn_open = False
            elif sql.startswith("set "):
                # replayable session state (reference: migrate.go
                # restores session variables on the new CN); keyed by
                # variable so repeated SETs replace, not accumulate
                var = sql[4:].split("=", 1)[0].strip()
                sess.sets[var] = raw
            return pkt
        if cmd in (_COM_STMT_EXECUTE, _COM_STMT_CLOSE, _COM_STMT_RESET,
                   _COM_STMT_SEND_LONG_DATA):   # all carry stmt-id@5:9
            cid = int.from_bytes(pkt[5:9], "little")
            bid = sess.id_map.get(cid, cid)
            if cmd == _COM_STMT_CLOSE:
                sess.stmts.pop(cid, None)
                sess.id_map.pop(cid, None)
            if bid != cid:
                pkt = pkt[:5] + struct.pack("<I", bid) + pkt[9:]
            return pkt
        return pkt

    def _relay_response(self, sess, cmd: int, req: bytes, client,
                        upstream, sent=None):
        """Forward one COMPLETE response, streaming packets through and
        rewriting the stmt id in PREPARE_OK to the client-visible one.
        Appends a marker to `sent` after the first byte reaches the
        client — past that point a backend loss cannot fail over (the
        client already saw a partial response)."""
        if sent is None:
            sent = []
        first = _read_pkt(upstream)
        if first is None:
            raise ConnectionError("backend closed")
        hdr = first[4]
        sent.append(True)
        if cmd == _COM_STMT_PREPARE and hdr == 0x00:
            bid = int.from_bytes(first[5:9], "little")
            sql = req[5:].decode("utf-8", "replace")
            cid = bid if bid not in sess.id_map.values() else bid + 1000
            # keep ids stable for the CLIENT: first prepare adopts the
            # backend id; after a migration new prepares may collide —
            # allocate a fresh client id then
            while cid in sess.stmts:
                cid += 1
            sess.stmts[cid] = sql
            sess.id_map[cid] = bid
            n_cols = int.from_bytes(first[9:11], "little")
            n_params = int.from_bytes(first[11:13], "little")
            client.sendall(first[:5] + struct.pack("<I", cid)
                           + first[9:])
            for _ in range(n_params):
                client.sendall(_read_pkt(upstream))
            if n_params:
                client.sendall(_read_pkt(upstream))     # EOF
            for _ in range(n_cols):
                client.sendall(_read_pkt(upstream))
            if n_cols:
                client.sendall(_read_pkt(upstream))     # EOF
            return
        client.sendall(first)
        if hdr in (0x00, 0xFF) or _is_eof(first):
            return                                      # OK / ERR / EOF
        # resultset: defs ... EOF ... rows ... EOF|ERR
        eofs = 0
        while eofs < 2:
            pkt = _read_pkt(upstream)
            if pkt is None:
                raise ConnectionError("backend closed mid-resultset")
            client.sendall(pkt)
            if _is_eof(pkt):
                eofs += 1
            elif pkt[4] == 0xFF:
                return

    # -------------------------------------------------------- migration
    def _migrate(self, sess, old_backend, old_upstream):
        """Move this idle session to a non-draining backend: login as the
        same user, replay SETs, re-prepare statements. Returns (backend,
        upstream) or None (stay put — e.g. no healthy target)."""
        target = self._pick(exclude=[old_backend])
        if target is None:
            return None
        try:
            up = socket.create_connection(target.address, timeout=5)
            up.settimeout(None)
            greet = _read_pkt(up)
            if greet is None:
                raise OSError("no greeting")
            # HandshakeResponse41 with the recorded user, empty auth —
            # backends behind THIS proxy trust it (test default
            # insecure=True; production pairs it with a proxy secret,
            # the reference's proxy-internal authentication)
            caps = 0x0200 | 0x8000 | 0x00080000   # proto41|secure|plugin
            resp = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                    + bytes([0x21]) + b"\x00" * 23
                    + sess.user.encode() + b"\x00"
                    + bytes([0])                  # empty auth
                    + b"mysql_native_password\x00")
            up.sendall(b"".join([len(resp).to_bytes(3, "little"),
                                 bytes([1]), resp]))
            ok = _read_pkt(up)
            if ok is None or ok[4] == 0xFF:
                raise OSError("target rejected proxy login")
            # replay session state
            for sql in sess.sets.values():
                self._roundtrip_query(up, sql)
            new_map: Dict[int, int] = {}
            for cid, sql in sess.stmts.items():
                new_map[cid] = self._roundtrip_prepare(up, sql)
            sess.id_map = new_map
            sess.migrations += 1
            return target, up
        except OSError:
            with self._lock:
                target.active -= 1
            return None

    @staticmethod
    def _roundtrip_query(up, sql: str) -> None:
        body = bytes([_COM_QUERY]) + sql.encode()
        up.sendall(len(body).to_bytes(3, "little") + b"\x00" + body)
        first = _read_pkt(up)
        if first is None:
            raise OSError("backend closed during replay")
        if first[4] in (0x00, 0xFF) or _is_eof(first):
            return
        eofs = 0
        while eofs < 2:
            pkt = _read_pkt(up)
            if pkt is None:
                raise OSError("backend closed during replay")
            if _is_eof(pkt):
                eofs += 1
            elif pkt[4] == 0xFF:
                return

    @staticmethod
    def _roundtrip_prepare(up, sql: str) -> int:
        body = bytes([_COM_STMT_PREPARE]) + sql.encode()
        up.sendall(len(body).to_bytes(3, "little") + b"\x00" + body)
        first = _read_pkt(up)
        if first is None or first[4] != 0x00:
            raise OSError(f"re-prepare failed: {sql!r}")
        bid = int.from_bytes(first[5:9], "little")
        n_cols = int.from_bytes(first[9:11], "little")
        n_params = int.from_bytes(first[11:13], "little")
        for _ in range(n_params + (1 if n_params else 0)
                       + n_cols + (1 if n_cols else 0)):
            _read_pkt(up)
        return bid

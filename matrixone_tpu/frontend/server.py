"""MySQL wire-protocol server (reference: pkg/frontend MOServer,
server.go:611/:99/:329 + codec — redesigned to the minimum viable protocol
surface: handshake v10, mysql_native_password accept-all auth,
COM_QUERY/COM_PING/COM_INIT_DB/COM_QUIT, text resultsets, OK/ERR packets).

Real MySQL clients (pymysql, mysql CLI) can connect on the configured port;
matrixone_tpu.client is the in-repo SDK speaking the same protocol.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.frontend.session import Result, Session

# MySQL protocol constants
_CAP_PROTOCOL_41 = 0x0200
_CAP_PLUGIN_AUTH = 0x80000
_CAP_SECURE_CONN = 0x8000
_CAPS = 0xF7FF | _CAP_PLUGIN_AUTH | _CAP_SECURE_CONN

_COM_QUIT = 0x01
_COM_INIT_DB = 0x02
_COM_QUERY = 0x03
_COM_PING = 0x0E

_MYSQL_TYPE = {
    TypeOid.BOOL: 1, TypeOid.INT8: 1, TypeOid.INT16: 2, TypeOid.INT32: 3,
    TypeOid.INT64: 8, TypeOid.UINT8: 1, TypeOid.UINT16: 2,
    TypeOid.UINT32: 3, TypeOid.UINT64: 8, TypeOid.FLOAT32: 4,
    TypeOid.FLOAT64: 5, TypeOid.DECIMAL64: 246, TypeOid.DATE: 10,
    TypeOid.DATETIME: 12, TypeOid.TIMESTAMP: 7,
}


def _lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def _lenenc_str(s: bytes) -> bytes:
    return _lenenc_int(len(s)) + s


class _Conn:
    def __init__(self, sock: socket.socket, session: Session):
        self.sock = sock
        self.session = session
        self.seq = 0

    # ---- packet framing
    def _send(self, payload: bytes):
        while True:
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq & 0xFF])
            self.sock.sendall(header + chunk)
            self.seq += 1
            if len(chunk) < 0xFFFFFF:
                return

    def _recv(self) -> Optional[bytes]:
        header = self._recv_n(4)
        if header is None:
            return None
        length = int.from_bytes(header[:3], "little")
        self.seq = header[3] + 1
        return self._recv_n(length)

    def _recv_n(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                return None
            buf += part
        return buf

    # ---- packets
    def send_handshake(self):
        self.seq = 0
        payload = (bytes([10])
                   + b"8.0.0-matrixone-tpu\x00"
                   + struct.pack("<I", threading.get_ident() & 0xFFFFFFFF)
                   + b"12345678\x00"                       # auth plugin data 1
                   + struct.pack("<H", _CAPS & 0xFFFF)
                   + bytes([0x21])                          # charset utf8
                   + struct.pack("<H", 0x0002)              # status
                   + struct.pack("<H", (_CAPS >> 16) & 0xFFFF)
                   + bytes([21])                            # auth data len
                   + b"\x00" * 10
                   + b"901234567890\x00"                    # auth plugin data 2
                   + b"mysql_native_password\x00")
        self._send(payload)

    def send_ok(self, affected: int = 0, info: str = ""):
        payload = (b"\x00" + _lenenc_int(affected) + _lenenc_int(0)
                   + struct.pack("<H", 0x0002) + struct.pack("<H", 0)
                   + info.encode())
        self._send(payload)

    def send_err(self, msg: str, code: int = 1105, state: str = "HY000"):
        payload = (b"\xff" + struct.pack("<H", code) + b"#"
                   + state.encode()[:5].ljust(5, b"0") + msg.encode()[:1024])
        self._send(payload)

    def send_eof(self):
        self._send(b"\xfe" + struct.pack("<H", 0) + struct.pack("<H", 0x0002))

    def send_resultset(self, result: Result):
        batch = result.batch
        names = result.column_names
        dtypes = [batch.columns[n].dtype for n in names]
        self._send(_lenenc_int(len(names)))
        for name, dtype in zip(names, dtypes):
            mysql_t = _MYSQL_TYPE.get(dtype.oid, 253)
            col = (_lenenc_str(b"def") + _lenenc_str(b"") + _lenenc_str(b"")
                   + _lenenc_str(b"") + _lenenc_str(name.encode())
                   + _lenenc_str(name.encode()) + bytes([0x0C])
                   + struct.pack("<H", 0x21) + struct.pack("<I", 1024)
                   + bytes([mysql_t]) + struct.pack("<H", 0)
                   + bytes([dtype.scale & 0xFF]) + b"\x00\x00")
            self._send(col)
        self.send_eof()
        for row in result.rows():
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                else:
                    out += _lenenc_str(str(v).encode())
            self._send(out)
        self.send_eof()

    # ---- command loop
    def run(self):
        try:
            self.send_handshake()
            if self._recv() is None:        # HandshakeResponse41 (auth
                return                      # accepted unconditionally)
            self.send_ok()
            while True:
                pkt = self._recv()
                if pkt is None or pkt[0] == _COM_QUIT:
                    return
                cmd, body = pkt[0], pkt[1:]
                if cmd in (_COM_PING, _COM_INIT_DB):
                    self.seq = 1
                    self.send_ok()
                    continue
                if cmd == _COM_QUERY:
                    self.seq = 1
                    sql = body.decode("utf-8", "replace")
                    try:
                        r = self.session.execute(sql)
                    except Exception as e:
                        self.send_err(str(e))
                        continue
                    if r.batch is not None:
                        self.send_resultset(r)
                    elif r.text is not None:
                        from matrixone_tpu.container import Batch, dtypes as dt
                        b = Batch.from_pydict(
                            {"EXPLAIN": r.text.split("\n")},
                            {"EXPLAIN": dt.TEXT})
                        self.send_resultset(Result(batch=b))
                    else:
                        self.send_ok(affected=r.affected)
                    continue
                self.send_err(f"unsupported command 0x{cmd:02x}")
        except (OSError, ConnectionError):
            return   # client went away mid-exchange; nothing to clean up
        finally:
            try:
                self.sock.close()
            except OSError:
                pass


class MOServer:
    """reference: frontend/server.go:611 NewMOServer / :99 Start."""

    def __init__(self, engine=None, host: str = "127.0.0.1", port: int = 6001):
        from matrixone_tpu.storage.engine import Engine
        self.engine = engine if engine is not None else Engine()
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        return self

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            session = Session(catalog=self.engine)
            conn = _Conn(sock, session)
            threading.Thread(target=conn.run, daemon=True).start()

    def stop(self):
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

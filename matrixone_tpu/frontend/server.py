"""MySQL wire-protocol server (reference: pkg/frontend MOServer,
server.go:611/:99/:329 + codec + authenticate.go — redesigned to the
protocol surface real clients need: handshake v10 with a random nonce,
mysql_native_password verification against configured users,
COM_QUERY/COM_PING/COM_INIT_DB/COM_QUIT text protocol, and the
COM_STMT_PREPARE / COM_STMT_EXECUTE / COM_STMT_CLOSE / COM_STMT_RESET
binary prepared-statement protocol (reference:
frontend/mysql_cmd_executor.go:4348 handlePrepareStmt wire path).

Auth model: `users` maps username -> plaintext password; the server stores
only SHA1(SHA1(password)) (stage2, what MySQL's mysql.user holds) and
verifies the client's 20-byte scramble against a per-connection random
nonce. Accept-all requires an explicit ``insecure=True``.

Real MySQL clients can connect on the configured port;
matrixone_tpu.client is the in-repo SDK speaking the same protocol.
"""

from __future__ import annotations

import hashlib
import secrets
import socket
import struct
import threading
from typing import Dict, Optional

from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.utils.lifecycle import ServiceThreads
from matrixone_tpu.frontend.session import Result, Session

# MySQL protocol constants
_CAP_PROTOCOL_41 = 0x0200
_CAP_PLUGIN_AUTH = 0x80000
_CAP_SECURE_CONN = 0x8000
_CAP_CONNECT_WITH_DB = 0x8
_CAP_PLUGIN_AUTH_LENENC = 0x200000
_CAPS = 0xF7FF | _CAP_PLUGIN_AUTH | _CAP_SECURE_CONN | _CAP_PLUGIN_AUTH_LENENC

_COM_QUIT = 0x01
_COM_INIT_DB = 0x02
_COM_QUERY = 0x03
_COM_PING = 0x0E
_COM_STMT_PREPARE = 0x16
_COM_STMT_EXECUTE = 0x17
_COM_STMT_CLOSE = 0x19
_COM_STMT_RESET = 0x1A

_MYSQL_TYPE = {
    TypeOid.BOOL: 1, TypeOid.INT8: 1, TypeOid.INT16: 2, TypeOid.INT32: 3,
    TypeOid.INT64: 8, TypeOid.UINT8: 1, TypeOid.UINT16: 2,
    TypeOid.UINT32: 3, TypeOid.UINT64: 8, TypeOid.FLOAT32: 4,
    TypeOid.FLOAT64: 5, TypeOid.DECIMAL64: 246, TypeOid.DATE: 10,
    TypeOid.DATETIME: 12, TypeOid.TIMESTAMP: 7,
}


def _lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def _lenenc_str(s: bytes) -> bytes:
    return _lenenc_int(len(s)) + s


def _read_lenenc(data: bytes, pos: int):
    b0 = data[pos]
    if b0 < 0xFB:
        return b0, pos + 1
    if b0 == 0xFB:            # NULL marker (only in row data)
        return None, pos + 1
    if b0 == 0xFC:
        return int.from_bytes(data[pos + 1:pos + 3], "little"), pos + 3
    if b0 == 0xFD:
        return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
    return int.from_bytes(data[pos + 1:pos + 9], "little"), pos + 9


# client-side scramble lives in the thin SDK (stdlib-only); re-exported
# here for protocol-level tests
from matrixone_tpu.client import native_password_scramble  # noqa: E402,F401


def password_stage2(password: str) -> bytes:
    """What the server persists: SHA1(SHA1(password)) (mysql.user style)."""
    return hashlib.sha1(hashlib.sha1(password.encode()).digest()).digest()


def verify_native_password(stage2: bytes, nonce: bytes,
                           auth_response: bytes) -> bool:
    """Server side: recover SHA1(pw) = response XOR SHA1(nonce+stage2) and
    check SHA1(recovered) == stage2 (reference: frontend/authenticate.go
    checkPassword)."""
    if not stage2:                      # empty password account
        return auth_response == b""
    if len(auth_response) != 20:
        return False
    mix = hashlib.sha1(nonce + stage2).digest()
    recovered = bytes(a ^ b for a, b in zip(auth_response, mix))
    return hashlib.sha1(recovered).digest() == stage2


def _count_params(node) -> int:
    """Number of ? placeholders in a parsed statement (max index + 1)."""
    import dataclasses as dc
    from matrixone_tpu.sql import ast
    best = 0
    if isinstance(node, ast.Param):
        return node.index + 1
    if dc.is_dataclass(node) and isinstance(node, ast.Node):
        for f in dc.fields(node):
            v = getattr(node, f.name)
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(x, ast.Node):
                    best = max(best, _count_params(x))
                elif isinstance(x, (list, tuple)):
                    for y in x:
                        if isinstance(y, ast.Node):
                            best = max(best, _count_params(y))
    return best


class _PreparedStmt:
    def __init__(self, stmt_id: int, sql: str, n_params: int):
        self.stmt_id = stmt_id
        self.sql = sql
        self.n_params = n_params
        self.param_types: Optional[list] = None   # sticky across executes


def _decode_binary_params(body: bytes, pos: int, stmt: _PreparedStmt) -> list:
    """Decode COM_STMT_EXECUTE parameter values (binary protocol)."""
    n = stmt.n_params
    nullmap = body[pos:pos + (n + 7) // 8]
    pos += (n + 7) // 8
    new_bound = body[pos]
    pos += 1
    if new_bound:
        stmt.param_types = [
            (body[pos + 2 * i], body[pos + 2 * i + 1]) for i in range(n)]
        pos += 2 * n
    if stmt.param_types is None:
        raise ValueError("COM_STMT_EXECUTE without bound parameter types")
    params = []
    for i, (ptype, flags) in enumerate(stmt.param_types):
        if nullmap[i // 8] & (1 << (i % 8)):
            params.append(None)
            continue
        unsigned = bool(flags & 0x80)
        if ptype in (1, 2, 3, 8, 9, 13):   # tiny/short/long/longlong/year
            width = {1: 1, 2: 2, 3: 4, 8: 8, 9: 4, 13: 2}[ptype]
            params.append(int.from_bytes(body[pos:pos + width], "little",
                                         signed=not unsigned))
            pos += width
        elif ptype == 4:                          # float
            params.append(struct.unpack("<f", body[pos:pos + 4])[0])
            pos += 4
        elif ptype == 5:                          # double
            params.append(struct.unpack("<d", body[pos:pos + 8])[0])
            pos += 8
        elif ptype == 6:                          # NULL type
            params.append(None)
        elif ptype in (10, 12, 7):                # date / datetime / timestamp
            ln = body[pos]
            pos += 1
            raw = body[pos:pos + ln]
            pos += ln
            import datetime
            if ln == 0:
                params.append(datetime.date(1970, 1, 1))
            else:
                y, m, d = struct.unpack("<HBB", raw[:4])
                if ptype == 10 or ln == 4:
                    params.append(datetime.date(y, m, d))
                else:
                    hh, mm, ss = raw[4:7] if ln >= 7 else (0, 0, 0)
                    params.append(datetime.datetime(y, m, d, hh, mm, ss))
        else:                                     # lenenc string-shaped
            ln, pos = _read_lenenc(body, pos)
            raw = body[pos:pos + (ln or 0)]
            pos += ln or 0
            if ptype == 246:                      # NEWDECIMAL
                params.append(float(raw.decode()))
            else:
                params.append(raw.decode("utf-8", "replace"))
    return params


class _Conn:
    def __init__(self, sock: socket.socket, server: "MOServer"):
        self.sock = sock
        self.server = server
        self.session: Optional[Session] = None
        self.insecure = server.insecure
        self.seq = 0
        self._stmts: Dict[int, _PreparedStmt] = {}
        self._next_stmt = 1

    # ---- packet framing
    def _send(self, payload: bytes):
        while True:
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self.seq & 0xFF])
            self.sock.sendall(header + chunk)
            self.seq += 1
            if len(chunk) < 0xFFFFFF:
                return

    def _recv(self) -> Optional[bytes]:
        """One logical payload: packets of exactly 0xFFFFFF bytes continue
        into the next packet (sender-side splitting mirrored here)."""
        payload = b""
        while True:
            header = self._recv_n(4)
            if header is None:
                return None
            length = int.from_bytes(header[:3], "little")
            self.seq = header[3] + 1
            part = self._recv_n(length)
            if part is None:
                return None
            payload += part
            if length < 0xFFFFFF:
                return payload

    def _recv_n(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                return None
            buf += part
        return buf

    # ---- packets
    def send_handshake(self) -> bytes:
        self.seq = 0
        # 20-byte random nonce, non-zero bytes (MySQL requirement)
        nonce = bytes(secrets.randbelow(254) + 1 for _ in range(20))
        payload = (bytes([10])
                   + b"8.0.0-matrixone-tpu\x00"
                   + struct.pack("<I", threading.get_ident() & 0xFFFFFFFF)
                   + nonce[:8] + b"\x00"                    # auth data part 1
                   + struct.pack("<H", _CAPS & 0xFFFF)
                   + bytes([0x21])                          # charset utf8
                   + struct.pack("<H", 0x0002)              # status
                   + struct.pack("<H", (_CAPS >> 16) & 0xFFFF)
                   + bytes([21])                            # auth data len
                   + b"\x00" * 10
                   + nonce[8:] + b"\x00"                    # auth data part 2
                   + b"mysql_native_password\x00")
        self._send(payload)
        return nonce

    def authenticate(self, nonce: bytes) -> bool:
        """Parse HandshakeResponse41, verify the scramble, and resolve
        the account context ('account:user' logins select the tenant —
        reference: authenticate.go)."""
        pkt = self._recv()
        if pkt is None:
            return False
        if self.insecure:
            self.session = self.server.make_session(None)
            return True
        try:
            caps = int.from_bytes(pkt[0:4], "little")
            pos = 4 + 4 + 1 + 23          # caps, max packet, charset, filler
            end = pkt.index(b"\x00", pos)
            user = pkt[pos:end].decode("utf-8", "replace")
            pos = end + 1
            if caps & _CAP_PLUGIN_AUTH_LENENC:
                ln, pos = _read_lenenc(pkt, pos)
                auth = pkt[pos:pos + (ln or 0)]
                pos += ln or 0
            elif caps & _CAP_SECURE_CONN:
                ln = pkt[pos]
                pos += 1
                auth = pkt[pos:pos + ln]
                pos += ln
            else:
                end = pkt.index(b"\x00", pos)
                auth = pkt[pos:end]
        except (ValueError, IndexError):
            self.send_err("malformed handshake response", code=1043,
                          state="08S01")
            return False
        resolved = self.server.auth_mgr.resolve_login(user)
        if resolved is None or not verify_native_password(
                resolved[2], nonce, auth):
            self.send_err(f"Access denied for user '{user}'",
                          code=1045, state="28000")
            return False
        account, uname, _stage2 = resolved
        ctx = self.server.auth_mgr.context_for(account, uname)
        self.session = self.server.make_session(ctx)
        return True

    def send_ok(self, affected: int = 0, info: str = ""):
        payload = (b"\x00" + _lenenc_int(affected) + _lenenc_int(0)
                   + struct.pack("<H", 0x0002) + struct.pack("<H", 0)
                   + info.encode())
        self._send(payload)

    def send_err(self, msg: str, code: int = 1105, state: str = "HY000"):
        payload = (b"\xff" + struct.pack("<H", code) + b"#"
                   + state.encode()[:5].ljust(5, b"0") + msg.encode()[:1024])
        self._send(payload)

    def send_eof(self):
        self._send(b"\xfe" + struct.pack("<H", 0) + struct.pack("<H", 0x0002))

    def _send_column_defs(self, result: Result, binary: bool):
        batch = result.batch
        names = result.column_names
        dtypes = [batch.columns[n].dtype for n in names]
        self._send(_lenenc_int(len(names)))
        for name, dtype in zip(names, dtypes):
            # binary rows are sent as lenenc strings, so declare VAR_STRING
            mysql_t = 253 if binary else _MYSQL_TYPE.get(dtype.oid, 253)
            col = (_lenenc_str(b"def") + _lenenc_str(b"") + _lenenc_str(b"")
                   + _lenenc_str(b"") + _lenenc_str(name.encode())
                   + _lenenc_str(name.encode()) + bytes([0x0C])
                   + struct.pack("<H", 0x21) + struct.pack("<I", 1024)
                   + bytes([mysql_t]) + struct.pack("<H", 0)
                   + bytes([dtype.scale & 0xFF]) + b"\x00\x00")
            self._send(col)
        self.send_eof()

    def send_resultset(self, result: Result):
        self._send_column_defs(result, binary=False)
        for row in result.rows():
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                else:
                    out += _lenenc_str(str(v).encode())
            self._send(out)
        self.send_eof()

    def send_binary_resultset(self, result: Result):
        """Binary-protocol resultset (COM_STMT_EXECUTE responses). All
        columns are declared VAR_STRING so every value is a lenenc string —
        type fidelity lives in the text; clients coerce by declared type."""
        self._send_column_defs(result, binary=True)
        ncols = len(result.column_names)
        nm_len = (ncols + 2 + 7) // 8
        for row in result.rows():
            nullmap = bytearray(nm_len)
            body = b""
            for i, v in enumerate(row):
                if v is None:
                    nullmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
                else:
                    body += _lenenc_str(str(v).encode())
            self._send(b"\x00" + bytes(nullmap) + body)
        self.send_eof()

    def _result_to_packets(self, r: Result, binary: bool):
        if r.batch is not None:
            if binary:
                self.send_binary_resultset(r)
            else:
                self.send_resultset(r)
        elif r.text is not None:
            from matrixone_tpu.container import Batch, dtypes as dt
            b = Batch.from_pydict({"EXPLAIN": r.text.split("\n")},
                                  {"EXPLAIN": dt.TEXT})
            rr = Result(batch=b)
            if binary:
                self.send_binary_resultset(rr)
            else:
                self.send_resultset(rr)
        else:
            self.send_ok(affected=r.affected)

    # ---- prepared statements
    def _handle_prepare(self, sql: str):
        from matrixone_tpu.sql.parser import parse
        stmts = parse(sql)
        if len(stmts) != 1:
            raise ValueError("can only prepare a single statement")
        n_params = _count_params(stmts[0])
        stmt = _PreparedStmt(self._next_stmt, sql, n_params)
        self._next_stmt += 1
        self._stmts[stmt.stmt_id] = stmt
        # COM_STMT_PREPARE_OK: num_columns=0 (defs are sent per-execute)
        self._send(b"\x00" + struct.pack("<I", stmt.stmt_id)
                   + struct.pack("<H", 0) + struct.pack("<H", n_params)
                   + b"\x00" + struct.pack("<H", 0))
        for _ in range(n_params):
            col = (_lenenc_str(b"def") + _lenenc_str(b"") * 3
                   + _lenenc_str(b"?") * 2 + bytes([0x0C])
                   + struct.pack("<H", 0x21) + struct.pack("<I", 1024)
                   + bytes([253]) + struct.pack("<H", 0) + b"\x00\x00\x00")
            self._send(col)
        if n_params:
            self.send_eof()

    def _handle_execute(self, body: bytes):
        stmt_id = int.from_bytes(body[0:4], "little")
        stmt = self._stmts.get(stmt_id)
        if stmt is None:
            raise ValueError(f"unknown statement id {stmt_id}")
        pos = 4 + 1 + 4                  # stmt_id, flags, iteration_count
        params = (_decode_binary_params(body, pos, stmt)
                  if stmt.n_params else [])
        r = self.session.execute(stmt.sql, params=params)
        self._result_to_packets(r, binary=True)

    # ---- command loop
    def run(self):
        try:
            nonce = self.send_handshake()
            if not self.authenticate(nonce):
                return
            self.send_ok()
            while True:
                pkt = self._recv()
                if pkt is None or pkt[0] == _COM_QUIT:
                    return
                cmd, body = pkt[0], pkt[1:]
                if cmd in (_COM_PING, _COM_INIT_DB):
                    self.seq = 1
                    self.send_ok()
                    continue
                if cmd == _COM_QUERY:
                    self.seq = 1
                    sql = body.decode("utf-8", "replace")
                    try:
                        r = self.session.execute(sql)
                    except Exception as e:  # noqa: BLE001 — wire ERR pkt
                        self.send_err(str(e))
                        continue
                    self._result_to_packets(r, binary=False)
                    continue
                if cmd == _COM_STMT_PREPARE:
                    self.seq = 1
                    try:
                        self._handle_prepare(body.decode("utf-8", "replace"))
                    except Exception as e:  # noqa: BLE001 — wire ERR pkt
                        self.send_err(str(e))
                    continue
                if cmd == _COM_STMT_EXECUTE:
                    self.seq = 1
                    try:
                        self._handle_execute(body)
                    except Exception as e:  # noqa: BLE001 — wire ERR pkt
                        self.send_err(str(e))
                    continue
                if cmd == _COM_STMT_CLOSE:
                    self._stmts.pop(int.from_bytes(body[0:4], "little"), None)
                    continue              # no response by protocol
                if cmd == _COM_STMT_RESET:
                    self.seq = 1
                    self.send_ok()
                    continue
                self.send_err(f"unsupported command 0x{cmd:02x}")
        except (OSError, ConnectionError):
            return   # client went away mid-exchange; nothing to clean up
        finally:
            if self.session is not None:
                self.session.close()   # release the processlist slot
            try:
                self.sock.close()
            except OSError:
                pass


class MOServer:
    """reference: frontend/server.go:611 NewMOServer / :99 Start.

    ``users`` maps username -> plaintext password (stored internally as
    SHA1(SHA1(pw)) stage2 hashes). Default: {"root": ""}. Pass
    ``insecure=True`` to skip credential verification entirely."""

    def __init__(self, engine=None, host: str = "127.0.0.1", port: int = 6001,
                 users: Optional[Dict[str, str]] = None,
                 insecure: bool = False):
        from matrixone_tpu.storage.engine import Engine
        self.engine = engine if engine is not None else Engine()
        self.host = host
        self.port = port
        if users is None:
            users = {"root": ""}
        # empty-password accounts are marked with b"" (expect an empty
        # scramble); others store the stage2 hash
        self.users = {u: (password_stage2(p) if p else b"")
                      for u, p in users.items()}
        self.insecure = insecure
        self.auth_mgr = None
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    def make_session(self, ctx) -> Session:
        return Session(catalog=self.engine, auth=ctx,
                       auth_manager=self.auth_mgr)

    def start(self):
        if not self.insecure:
            # accounts/users/roles live in engine tables and replicate
            # through the logtail; the seeded users land in the sys
            # account (frontend/auth.py)
            from matrixone_tpu.frontend.auth import AccountManager
            self.auth_mgr = AccountManager(self.engine,
                                           seed_users=dict(self.users))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._svc = ServiceThreads("mo-server")
        self._thread = self._svc.spawn_accept(self._accept_loop)
        return self

    def _accept_loop(self):
        while not self._stopping.is_set():
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            conn = _Conn(sock, self)
            self._svc.spawn_handler(lambda s, c=conn: c.run(), sock)

    def stop(self):
        self._stopping.set()
        if self._sock is not None:
            # interrupt blocked accept + session recv()s and JOIN with a
            # deadline (mosan leak checker gates abandoned threads)
            self._svc.shutdown(self._sock)

"""Session: SQL text in, result batches out.

Reference analogue: the frontend's doComQuery -> buildPlan -> Compile -> Run
chain (`frontend/mysql_cmd_executor.go:4160`) minus the wire protocol (the
server lives in matrixone_tpu.frontend.server). DDL/DML execute directly
against the catalog; SELECT goes parse -> bind -> compile -> pull loop ->
host Batch.
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container import Batch, Vector, dtypes as dt, from_device
from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.sql import ast, plan as P
from matrixone_tpu.sql.binder import Binder, BindError, type_from_name
from matrixone_tpu.sql.parser import parse
from matrixone_tpu.storage.memtable import Catalog, IndexMeta, MemTable, TableMeta
from matrixone_tpu.vm.compile import compile_plan


@dataclasses.dataclass
class Result:
    batch: Optional[Batch] = None        # SELECT results
    affected: int = 0                    # DML row count
    text: Optional[str] = None           # EXPLAIN / SHOW output

    def rows(self) -> List[tuple]:
        if self.batch is None:
            return []
        names = list(self.batch.columns)
        cols = [self.batch.columns[n].to_pylist() for n in names]
        return [tuple(vals) for vals in zip(*cols)] if cols else []

    @property
    def column_names(self) -> List[str]:
        return list(self.batch.columns) if self.batch else []


class Session:
    """One client session (reference: frontend.Session); system variables
    and (later) transaction state hang off this object."""

    def __init__(self, catalog: Optional[Catalog] = None):
        self.catalog = catalog if catalog is not None else Catalog()
        self.variables = {"gpu_mode": 1, "batch_rows": 1 << 20}

    # ------------------------------------------------------------ execute
    def execute(self, sql: str, params: Optional[list] = None) -> Result:
        stmts = parse(sql)
        if params is not None:
            stmts = [_substitute_params(st, params) for st in stmts]
        results = [self._execute_stmt(s) for s in stmts]
        return results[-1] if results else Result()

    def _execute_stmt(self, stmt: ast.Node) -> Result:
        if isinstance(stmt, ast.Select):
            return self._select(stmt)
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, ast.DropTable):
            self.catalog.drop_table(stmt.name, stmt.if_exists)
            return Result()
        if isinstance(stmt, ast.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt)
        if isinstance(stmt, ast.Explain):
            binder = Binder(self.catalog)
            if not isinstance(stmt.stmt, ast.Select):
                raise BindError("EXPLAIN supports SELECT only for now")
            node = binder.bind_select(stmt.stmt)
            return Result(text=P.explain(node))
        if isinstance(stmt, ast.ShowTables):
            names = sorted(self.catalog.tables)
            b = Batch.from_pydict({"Tables": names},
                                  {"Tables": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.SetVariable):
            if isinstance(stmt.value, ast.Literal):
                self.variables[stmt.name] = stmt.value.value
            return Result()
        if isinstance(stmt, (ast.BeginTxn, ast.CommitTxn, ast.RollbackTxn)):
            return Result()   # txn layer lands with the MVCC storage engine
        raise BindError(f"unsupported statement {type(stmt).__name__}")

    # ------------------------------------------------------------- select
    def _select(self, sel: ast.Select) -> Result:
        node = Binder(self.catalog).bind_select(sel)
        op = compile_plan(node, self.catalog)
        out_batches = []
        for ex in op.execute():
            out_batches.append(self._to_host(ex, node.schema))
        if not out_batches:
            empty = {n: Vector.from_values([], d) for n, d in node.schema}
            return Result(batch=Batch(empty))
        if len(out_batches) == 1:
            return Result(batch=out_batches[0])
        # concatenate host batches
        cols = {}
        for n, d in node.schema:
            vals = []
            for b in out_batches:
                vals.extend(b.columns[n].to_pylist())
            cols[n] = Vector.from_values(vals, d)
        return Result(batch=Batch(cols))

    def _to_host(self, ex, schema) -> Batch:
        from matrixone_tpu.ops import filter as F
        # compact masked rows before leaving device
        n_out = jnp.sum(ex.mask.astype(jnp.int32))
        cap = ex.padded_len
        db = F.compact(ex.batch, ex.mask, cap)
        return from_device(db, ex.dicts, schema=dict(schema))

    # --------------------------------------------------------------- ddl
    def _create_table(self, stmt: ast.CreateTable) -> Result:
        schema = [(c.name, type_from_name(c.type_name, c.type_args))
                  for c in stmt.columns]
        self.catalog.create_table(
            TableMeta(stmt.name, schema, stmt.primary_key),
            if_not_exists=stmt.if_not_exists)
        return Result()

    def _create_index(self, stmt: ast.CreateIndex) -> Result:
        table = self.catalog.get_table(stmt.table)
        algo = (stmt.using or "").lower()
        if algo in ("ivfflat", "ivf_flat"):
            from matrixone_tpu.vectorindex import ivf_flat
            col = stmt.columns[0]
            coltype = dict(table.meta.schema)[col]
            if not coltype.is_vector:
                raise BindError(f"ivfflat index requires a vecf32 column")
            data = table.read_column_f32(col)
            nlist = int(stmt.options.get("lists", 64))
            op_type = stmt.options.get("op_type", "vector_l2_ops")
            metric = {"vector_l2_ops": "l2", "vector_cosine_ops": "cosine",
                      "vector_ip_ops": "ip"}.get(op_type, "l2")
            idx = ivf_flat.build(jnp.asarray(data), nlist=nlist,
                                 metric=metric)
            self.catalog.indexes[stmt.name] = IndexMeta(
                stmt.name, stmt.table, stmt.columns, "ivfflat",
                dict(stmt.options), index_obj=idx)
            return Result()
        raise BindError(f"unsupported index algo {stmt.using!r}")

    # --------------------------------------------------------------- dml
    def _insert(self, stmt: ast.Insert) -> Result:
        table = self.catalog.get_table(stmt.table)
        schema = table.meta.schema
        cols = stmt.columns or [c for c, _ in schema]
        if stmt.select is not None:
            sub = self._select(stmt.select)
            data = {c: sub.batch.columns[n].to_pylist()
                    for c, n in zip(cols, sub.column_names)}
        else:
            data = {c: [] for c in cols}
            for row in stmt.rows:
                if len(row) != len(cols):
                    raise BindError("INSERT arity mismatch")
                for c, v in zip(cols, row):
                    data[c].append(_literal_value(v))
        full = {}
        n = len(next(iter(data.values()))) if data else 0
        for c, d in schema:
            vals = data.get(c, [None] * n)
            if d.oid == TypeOid.DATE:
                vals = [(datetime.date.fromisoformat(v)
                         - datetime.date(1970, 1, 1)).days
                        if isinstance(v, str) else v for v in vals]
            elif d.is_vector:
                vals = [[float(x) for x in v.strip()[1:-1].split(",")]
                        if isinstance(v, str) else v for v in vals]
            full[c] = vals
        batch = Batch.from_pydict(full, {c: d for c, d in schema})
        n = table.insert_batch(batch)
        return Result(affected=n)


def _param_literal(v) -> ast.Node:
    if v is None:
        return ast.Literal(None, "null")
    if isinstance(v, bool):
        return ast.Literal(v, "bool")
    if isinstance(v, int):
        return ast.Literal(v, "int")
    if isinstance(v, float):
        return ast.Literal(repr(v), "float")
    if isinstance(v, str):
        return ast.Literal(v, "str")
    if isinstance(v, datetime.date):
        return ast.DateLiteral((v - datetime.date(1970, 1, 1)).days)
    raise BindError(f"unsupported parameter type {type(v).__name__}")


def _substitute_params(node, params: list):
    """Replace ? placeholders (ast.Param) with literal values."""
    import dataclasses as dc
    if isinstance(node, ast.Param):
        if node.index >= len(params):
            raise BindError(f"missing value for parameter {node.index + 1}")
        return _param_literal(params[node.index])
    if dc.is_dataclass(node) and isinstance(node, ast.Node):
        def sub(x):
            if isinstance(x, ast.Node):
                return _substitute_params(x, params)
            if isinstance(x, tuple):
                return tuple(sub(y) for y in x)
            if isinstance(x, list):
                return [sub(y) for y in x]
            return x
        for f in dc.fields(node):
            setattr(node, f.name, sub(getattr(node, f.name)))
    return node


def _literal_value(v: ast.Node):
    if isinstance(v, ast.Literal):
        if v.kind == "float":
            return float(v.value)
        return v.value
    if isinstance(v, ast.DateLiteral):
        return v.days
    if isinstance(v, ast.UnaryOp) and v.op == "-":
        inner = _literal_value(v.operand)
        return -inner
    if isinstance(v, ast.Cast):
        return _literal_value(v.expr)
    raise BindError("INSERT VALUES must be literals")

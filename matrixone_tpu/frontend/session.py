"""Session: SQL text in, result batches out.

Reference analogue: the frontend's doComQuery -> buildPlan -> Compile -> Run
chain (`frontend/mysql_cmd_executor.go:4160`) minus the wire protocol (the
server lives in matrixone_tpu.frontend.server). DDL/DML execute directly
against the catalog; SELECT goes parse -> bind -> compile -> pull loop ->
host Batch.
"""

from __future__ import annotations

import contextvars
import dataclasses
import datetime
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container import Batch, Vector, dtypes as dt, from_device
from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.sql import ast, plan as P
from matrixone_tpu.sql.binder import Binder, BindError, type_from_name
from matrixone_tpu.sql.parser import parse
from matrixone_tpu.storage.engine import (Catalog, Engine, IndexMeta,
                                          TableMeta)
from matrixone_tpu.storage.engine import ROWID
from matrixone_tpu.txn.client import TxnClient, TxnState
from matrixone_tpu.vm.compile import compile_plan
from matrixone_tpu.vm.process import ExecContext

#: the session currently executing a statement on this thread — info
#: functions (connection_id()/user()/last_insert_id()/...) resolve
#: against it at bind time (reference: frontend session variables)
_CURRENT_SESSION: contextvars.ContextVar = contextvars.ContextVar(
    "mo_current_session", default=None)


def current_session():
    return _CURRENT_SESSION.get()


@dataclasses.dataclass
class Result:
    batch: Optional[Batch] = None        # SELECT results
    affected: int = 0                    # DML row count
    text: Optional[str] = None           # EXPLAIN / SHOW output

    def rows(self) -> List[tuple]:
        if self.batch is None:
            return []
        names = list(self.batch.columns)
        cols = [self.batch.columns[n].to_pylist() for n in names]
        return [tuple(vals) for vals in zip(*cols)] if cols else []

    @property
    def column_names(self) -> List[str]:
        return list(self.batch.columns) if self.batch is not None else []


class Session:
    """One client session (reference: frontend.Session); system variables
    and (later) transaction state hang off this object."""

    def __init__(self, catalog: Optional[Engine] = None, fs=None,
                 user: str = "root", auth=None, auth_manager=None):
        from matrixone_tpu.queryservice import registry_for
        self.catalog = catalog if catalog is not None else Engine(fs)
        #: AuthContext of the logged-in user (None = trusted embedded
        #: session, unrestricted); non-sys accounts see a tenant-scoped
        #: catalog (frontend/auth.py, reference: authenticate.go)
        self.auth = auth
        self.auth_mgr = auth_manager
        if auth is not None and auth.account != "sys":
            from matrixone_tpu.frontend.auth import ScopedCatalog
            self.catalog = ScopedCatalog(self.catalog, auth.account)
        # a NEW session on a CN starts at the cluster frontier (the
        # reference's reads gate on the logtail reaching the snapshot;
        # here one catch-up per connection keeps cross-connection
        # read-your-writes without a per-statement RPC)
        sync = getattr(self.catalog, "sync_frontier", None)
        if sync is not None:
            sync()
        self.txn_client = TxnClient(self.catalog)
        self.txn = None                 # active explicit transaction
        self.last_insert_id = 0         # MySQL LAST_INSERT_ID()
        import os as _os
        self.variables = {"gpu_mode": 1, "batch_rows": 1 << 20,
                          # SET ivf_shards = N routes vector queries onto
                          # an N-device mesh (vm/vector_scan.py); the env
                          # default serves deployments that shard always
                          "ivf_shards": int(_os.environ.get(
                              "MO_IVF_SHARDS", "0") or 0),
                          # SET query_shards = N routes eligible SQL
                          # fragments onto an N-device mesh
                          # (parallel/dist_query.py shard executor)
                          "query_shards": int(_os.environ.get(
                              "MO_QUERY_SHARDS", "0") or 0)}
        self._procs = registry_for(self.catalog)
        self._admission_depth = 0      # re-entrant execute() guard
        self.conn_id = self._procs.register(user if auth is None
                                            else f"{auth.account}:"
                                                 f"{auth.user}")

    def close(self) -> None:
        """Release the session's process-registry slot (the wire server
        and embed cluster call this on disconnect/shutdown)."""
        self._procs.unregister(self.conn_id)

    def _ctx(self, frozen_ts: Optional[int] = None) -> ExecContext:
        if frozen_ts is None and self.txn is None:
            frozen_ts = self.catalog.committed_ts
        return ExecContext(catalog=self.catalog, txn=self.txn,
                           variables=self.variables,
                           frozen_ts=(None if self.txn is not None
                                      else frozen_ts))

    def _index_skip_tables(self) -> frozenset:
        """Index rewrites serve only frontier (autocommit) reads: an open
        txn reads an older snapshot + workspace that a frontier-built
        index cannot realize."""
        if self.txn is not None:
            return frozenset(self.catalog.tables)
        return frozenset()

    # ------------------------------------------------------------ execute
    def execute(self, sql: str, params: Optional[list] = None) -> Result:
        from matrixone_tpu.utils import motrace
        # the statement is the trace boundary: parse, cache lookups,
        # admission wait, fragment compile/dispatch, RPC hops, worker
        # offload and TN commit all become children of this root span
        # (re-entrant executes nest as child spans, not new traces)
        with motrace.statement_span(sql):
            return self._execute_traced(sql, params)

    def _execute_traced(self, sql: str,
                        params: Optional[list] = None) -> Result:
        import time as _time
        from matrixone_tpu.utils import metrics as M
        from matrixone_tpu.utils import motrace
        from matrixone_tpu.utils.trace import STMT_TABLE, StatementRecorder
        # statement tracing is engine-global (one system table), never
        # tenant-scoped — always hang it off the TRUE engine: unwrap the
        # tenant scope AND the CN's RemoteCatalog facade. Writing through
        # the facade is how round 5's nastiest bug happened: the trace
        # flush's `engine.committed_ts = ...` created an INSTANCE
        # attribute on the RemoteCatalog that permanently shadowed the
        # replica's live committed_ts behind __getattr__, freezing every
        # later transaction's begin snapshot (stale snapshots ->
        # spurious write-write conflicts on busy CN sessions)
        rec_host = getattr(self.catalog, "_inner", self.catalog)
        rec_host = getattr(rec_host, "_replica", rec_host)
        if not hasattr(rec_host, "stmt_recorder"):
            rec_host.stmt_recorder = StatementRecorder(rec_host)
        if STMT_TABLE in sql:
            self.catalog.stmt_recorder.flush()
        # serving layer (matrixone_tpu/serving): normalize the statement
        # and route repeated shapes through the plan/result caches; falls
        # back to the raw parse path whenever anything is off-template
        sv = self._serving_prepare(sql, params)
        stmts = sv.make_stmts() if sv is not None else None
        if stmts is None:
            # raw path: first occurrence of a template (or an
            # unusable one) — the result cache still participates
            # through sv, the plan cache does not (template_mode off)
            if sv is not None:
                sv.template_mode = False
                if not sv.result_enabled():
                    sv = None
            with motrace.span("parse"):
                stmts = parse(sql)
            if params is not None:
                stmts = [_substitute_params(st, params) for st in stmts]
        _tok = _CURRENT_SESSION.set(self)
        try:
            return self._execute_stmts(stmts, sql, sv)
        finally:
            _CURRENT_SESSION.reset(_tok)

    def _serving_prepare(self, sql: str, params):
        """-> _ServingCtx when this statement may use the serving caches
        (single statement, autocommit, deterministic, plain params)."""
        if self.txn is not None:
            return None
        from matrixone_tpu.serving import serving_for
        state = serving_for(self.catalog)
        if not (state.plan_cache.enabled or state.result_cache.enabled):
            return None
        # nondeterministic UDFs must bypass both caches exactly like
        # now()/rand(): feed the registry's nondet names to statement
        # normalization (version-cached: a few attr reads when idle)
        from matrixone_tpu.udf.catalog import sync_serving as _udf_sync
        _udf_sync(self.catalog, state)
        norm = state.plan_cache.normalized(sql)
        if norm is None or norm.n_stmts != 1 or norm.nondet:
            return None
        try:
            full = norm.full_params(params)
        except (IndexError, TypeError, ValueError):
            return None            # arity mismatch: raw path raises it
        for p in full:
            if not isinstance(p, (int, float, str, bool, type(None),
                                  datetime.date)):
                return None
        return _ServingCtx(state, norm, full, self._acct())

    def _execute_stmts(self, stmts, sql: str, serving=None) -> Result:
        import time as _time
        from matrixone_tpu.serving import serving_for
        from matrixone_tpu.utils import metrics as M
        from matrixone_tpu.utils import motrace
        adm = serving_for(self.catalog).admission
        results = []
        # per-statement span attribution in a multi-statement batch:
        # the shared statement-root trace is one ring sequence; each
        # statement records only the spans past the previous mark (the
        # first statement's window starts at 0 so it owns `parse`)
        tr_mark = 0
        for st in stmts:
            if self._procs.is_terminated(self.conn_id):
                from matrixone_tpu.queryservice import QueryKilled
                raise QueryKilled(
                    f"connection {self.conn_id} was killed")
            t0 = _time.perf_counter()
            self._procs.start_query(self.conn_id, sql)
            self._liid_set = False     # last_insert_id(): per statement
            ann = {"cache_hit": "none", "queue_wait_ms": 0}
            self._exec_ann = ann
            ticket = None
            try:
                with motrace.span("run", stmt=type(st).__name__):
                    if adm.enabled and self._admission_gated(st):
                        lane = ("background" if str(self.variables.get(
                            "query_priority", "")).lower() == "background"
                            else "interactive")
                        ticket = adm.acquire(account=self._acct(),
                                             lane=lane,
                                             conn_id=self.conn_id,
                                             registry=self._procs)
                        self._admission_depth += 1
                        ann["queue_wait_ms"] = int(
                            ticket.queue_wait_s * 1000)
                    r = self._execute_stmt(st, serving)
                    motrace.annotate(cache_hit=ann["cache_hit"])
            except Exception as e:   # noqa: BLE001 — recorded, re-raised
                dt_ = _time.perf_counter() - t0
                M.query_seconds.observe(dt_)
                tr_id, n_sp, summ, tree = motrace.statement_record(
                    dt_ * 1000.0, since=tr_mark)
                self.catalog.stmt_recorder.record(
                    sql, "error", dt_, 0, error=str(e)[:1024],
                    cache_hit=ann["cache_hit"],
                    queue_wait_ms=ann["queue_wait_ms"],
                    trace_id=tr_id, span_count=n_sp,
                    span_summary=summ, span_tree=tree)
                raise
            finally:
                if ticket is not None:
                    self._admission_depth -= 1
                    ticket.release()
                self._procs.end_query(self.conn_id)
            dt_ = _time.perf_counter() - t0
            M.query_seconds.observe(dt_)
            rows_out = len(r.batch) if r.batch is not None else r.affected
            # slow-query hook: past MO_TRACE_SLOW_MS the FULL span tree
            # persists into the statement table (motrace.statement_record)
            tr_id, n_sp, summ, tree = motrace.statement_record(
                dt_ * 1000.0, since=tr_mark)
            tr_mark += n_sp
            self.catalog.stmt_recorder.record(
                sql, "ok", dt_, rows_out, cache_hit=ann["cache_hit"],
                queue_wait_ms=ann["queue_wait_ms"],
                trace_id=tr_id, span_count=n_sp, span_summary=summ,
                span_tree=tree)
            results.append(r)
        return results[-1] if results else Result()

    def _admission_gated(self, st: ast.Node) -> bool:
        """Workload statements pass admission; control statements (SET,
        txn control, KILL, SHOW, mo_ctl) never queue — an operator must
        always be able to inspect and kill. Re-entrant executes (dynamic
        table refresh inside an admitted statement) bypass too, or a
        1-slot server would deadlock against itself."""
        if self._admission_depth > 0:
            return False
        if isinstance(st, (ast.Select, ast.Union)):
            return not self._is_ctl_select(st)
        return isinstance(st, (ast.Insert, ast.Update, ast.Delete,
                               ast.LoadData))

    @staticmethod
    def _is_ctl_select(st: ast.Node) -> bool:
        return (isinstance(st, ast.Select) and st.from_ is None
                and len(st.items) == 1
                and isinstance(st.items[0].expr, ast.FuncCall)
                and st.items[0].expr.name == "mo_ctl")

    # ------------------------------------------------------ privileges
    def _mgr(self):
        """The engine's AccountManager (shared; lazily bootstrapped so
        embedded sessions can manage accounts too)."""
        if self.auth_mgr is not None:
            return self.auth_mgr
        inner = getattr(self.catalog, "_inner", self.catalog)
        mgr = getattr(inner, "_auth_mgr", None)
        if mgr is None:
            from matrixone_tpu.frontend.auth import AccountManager
            mgr = AccountManager(inner)
            inner._auth_mgr = mgr
        self.auth_mgr = mgr
        return mgr

    def _acct(self) -> str:
        return self.auth.account if self.auth is not None else "sys"

    def _visible_account(self) -> Optional[str]:
        """Process-registry visibility scope: None = cluster-wide (sys
        tenant / embedded sessions), else restricted to this account."""
        if self.auth is None or self.auth.account == "sys":
            return None
        return self.auth.account

    def _check(self, priv: str, obj: str = "*") -> None:
        if self.auth is None or self.auth.is_admin:
            return
        self._mgr().check(self.auth, priv, obj)

    def _check_admin(self) -> None:
        if self.auth is not None and not self.auth.is_admin:
            from matrixone_tpu.frontend.auth import AuthError
            raise AuthError(
                f"access denied: {self.auth.user!r} is not an account "
                f"administrator")

    def _enforce(self, stmt: ast.Node) -> None:
        """Per-statement privilege gate (reference: authenticate.go
        determinePrivilege + privilege check before execution)."""
        if self.auth is None or self.auth.is_admin:
            return
        if isinstance(stmt, ast.Insert):
            self._check("insert", stmt.table)
        elif isinstance(stmt, ast.Update):
            self._check("update", stmt.table)
        elif isinstance(stmt, ast.Delete):
            self._check("delete", stmt.table)
        elif isinstance(stmt, ast.LoadData):
            self._check("insert", stmt.table)
        elif isinstance(stmt, ast.DropTable):
            self._check("drop", stmt.name)
        elif isinstance(stmt, ast.CreateFunction):
            self._check("create")
        elif isinstance(stmt, ast.DropFunction):
            self._check("drop")
        elif isinstance(stmt, ast.DropMaterializedView):
            self._check("drop", stmt.name)
        elif isinstance(stmt, (ast.CreateTable, ast.CreateIndex,
                               ast.CreateExternalTable, ast.CreateSource,
                               ast.CreateDynamicTable, ast.CreateStage,
                               ast.CreateSnapshot, ast.CreatePublication,
                               ast.CreateMaterializedView,
                               ast.AlterPartition, ast.RestoreTable)):
            self._check("create")

    def _execute_stmt(self, stmt: ast.Node, serving=None) -> Result:
        self._enforce(stmt)
        acc = self._account_stmt(stmt)
        if acc is not None:
            return acc
        if isinstance(stmt, (ast.Select, ast.Union)):
            return self._select(stmt, serving=serving)
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, ast.DropTable):
            from matrixone_tpu.mview import catalog as vcat
            if vcat.lookup(self.catalog, stmt.name) is not None:
                raise BindError(
                    f"{stmt.name!r} is a materialized view; use DROP "
                    f"MATERIALIZED VIEW")
            self.catalog.drop_table(stmt.name, stmt.if_exists)
            return Result()
        if isinstance(stmt, ast.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, ast.CreateFunction):
            return self._create_function(stmt)
        if isinstance(stmt, ast.DropFunction):
            return self._drop_function(stmt)
        if isinstance(stmt, ast.ShowFunctions):
            return self._show_functions()
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt)
        if isinstance(stmt, ast.Explain):
            from matrixone_tpu.sql.optimize import apply_indices
            binder = Binder(self.catalog)
            if not isinstance(stmt.stmt, (ast.Select, ast.Union)):
                raise BindError("EXPLAIN supports SELECT only for now")
            self._prepare_select(stmt.stmt)
            node = binder.bind_statement(stmt.stmt)
            node = self._cbo(node)
            node = apply_indices(
                node, self.catalog,
                nprobe=int(self.variables.get("ivf_nprobe", 8)),
                skip_tables=self._index_skip_tables())
            if stmt.analyze:
                return Result(text=self._explain_analyze(node))
            anns = [a for a in (self._fragment_annotator(node),
                                self._mview_annotator(),
                                self._exchange_annotator(node))
                    if a is not None]
            annotate = (None if not anns else
                        (lambda pn: "".join(a(pn) for a in anns)))
            return Result(text=P.explain(node, annotate=annotate))
        if isinstance(stmt, ast.CreatePublication):
            self.catalog.create_publication(stmt.name, stmt.tables)
            return Result()
        if isinstance(stmt, ast.DropPublication):
            self.catalog.drop_publication(stmt.name)
            return Result()
        if isinstance(stmt, ast.ShowPublications):
            names = sorted(self.catalog.publications)
            b = Batch.from_pydict(
                {"Publication": names,
                 "Tables": [", ".join(self.catalog.publications[n])
                            for n in names]},
                {"Publication": dt.VARCHAR, "Tables": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.CreateSource):
            schema = [(c.name, type_from_name(c.type_name, c.type_args))
                      for c in stmt.columns]
            self.catalog.create_table(TableMeta(stmt.name, schema, []))
            self.catalog.mark_source(stmt.name)
            return Result()
        if isinstance(stmt, ast.CreateDynamicTable):
            return self._create_dynamic_table(stmt)
        if isinstance(stmt, ast.RefreshDynamicTable):
            from matrixone_tpu.stream import refresh_dynamic_table
            if stmt.name not in self.catalog.dynamic_tables:
                raise BindError(f"no such dynamic table {stmt.name!r}")
            n = refresh_dynamic_table(self, stmt.name)
            return Result(affected=n)
        if isinstance(stmt, ast.CreateMaterializedView):
            return self._create_materialized_view(stmt)
        if isinstance(stmt, ast.DropMaterializedView):
            return self._drop_materialized_view(stmt)
        if isinstance(stmt, ast.ShowMaterializedViews):
            return self._show_materialized_views()
        if isinstance(stmt, ast.RefreshMaterializedView):
            return Result(affected=self._refresh_mview(stmt.name))
        if isinstance(stmt, ast.LoadData):
            return self._load_data(stmt)
        if isinstance(stmt, ast.CreateStage):
            self.catalog.create_stage(stmt.name, stmt.url)
            return Result()
        if isinstance(stmt, ast.DropStage):
            self.catalog.drop_stage(stmt.name)
            return Result()
        if isinstance(stmt, ast.ShowStages):
            names = sorted(self.catalog.stages)
            b = Batch.from_pydict(
                {"Stage": names,
                 "URL": [self.catalog.stages[n] for n in names]},
                {"Stage": dt.VARCHAR, "URL": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.CreateExternalTable):
            schema = [(c.name, type_from_name(c.type_name, c.type_args))
                      for c in stmt.columns]
            fmt = _resolve_format(stmt.fmt, stmt.location)
            if stmt.snapshot is not None and fmt != "iceberg":
                raise BindError("SNAPSHOT applies to FORMAT iceberg only")
            self.catalog.create_external(
                TableMeta(stmt.name, schema, []), stmt.location, fmt,
                snapshot=stmt.snapshot)
            return Result()
        if isinstance(stmt, ast.ShowProcesslist):
            # tenant isolation (reference: authenticate.go account
            # scoping): the registry is engine-global, but a non-sys
            # session must not see other tenants' connections — their
            # SQL text can carry data
            from matrixone_tpu.queryservice import account_of
            pl = self._procs.processlist()
            scope = self._visible_account()
            if scope is not None:
                pl = [p for p in pl if account_of(p["User"]) == scope]
            b = Batch.from_pydict(
                {"Id": [p["Id"] for p in pl],
                 "User": [p["User"] for p in pl],
                 "State": [p["State"] for p in pl],
                 "Time": [p["Time"] for p in pl],
                 "Query": [p["Query"] for p in pl]},
                {"Id": dt.INT64, "User": dt.VARCHAR, "State": dt.VARCHAR,
                 "Time": dt.FLOAT64, "Query": dt.TEXT})
            return Result(batch=b)
        if isinstance(stmt, ast.Kill):
            scope = self._visible_account()
            owner = self._procs.owner_account(stmt.conn_id)
            if scope is not None and owner != scope:
                # cross-tenant KILL is a DoS vector; deny with ONE
                # indistinguishable error whether the conn is another
                # tenant's or nonexistent (no conn-id existence oracle)
                from matrixone_tpu.frontend.auth import AuthError
                raise AuthError(
                    f"access denied: connection {stmt.conn_id} does not "
                    f"belong to account {scope!r}")
            if owner is None:
                raise BindError(f"no connection {stmt.conn_id}")
            if not self._procs.kill(stmt.conn_id,
                                    query_only=stmt.query_only):
                raise BindError(f"no connection {stmt.conn_id}")
            return Result()
        if isinstance(stmt, ast.AlterPartition):
            return self._alter_partition(stmt)
        if isinstance(stmt, ast.ShowPartitions):
            return self._show_partitions(stmt)
        if isinstance(stmt, ast.AnalyzeTable):
            from matrixone_tpu.sql.stats import provider_for
            if getattr(self.catalog.get_table(stmt.name), "is_external",
                       False):
                raise BindError(
                    f"{stmt.name!r} is an external table; it has no "
                    f"segment statistics to analyze")
            st = provider_for(self.catalog).refresh(stmt.name)
            b = Batch.from_pydict(
                {"table": [stmt.name], "rows": [st.row_count],
                 "columns": [len(st.cols)]},
                {"table": dt.VARCHAR, "rows": dt.INT64,
                 "columns": dt.INT64})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowTables):
            names = sorted(self.catalog.tables)
            b = Batch.from_pydict({"Tables": names},
                                  {"Tables": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowCreateTable):
            t = self.catalog.get_table(stmt.name)
            cols = []
            for c, d in t.meta.schema:
                extra = " auto_increment" if c == t.meta.auto_increment else ""
                cols.append(f"  `{c}` {d}{extra}")
            if t.meta.primary_key:
                cols.append("  primary key ("
                            + ", ".join(t.meta.primary_key) + ")")
            ddl = f"create table `{stmt.name}` (\n" + ",\n".join(cols) + "\n)"
            b = Batch.from_pydict({"Table": [stmt.name],
                                   "Create Table": [ddl]},
                                  {"Table": dt.VARCHAR,
                                   "Create Table": dt.TEXT})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowColumns):
            t = self.catalog.get_table(stmt.name)
            b = Batch.from_pydict(
                {"Field": [c for c, _ in t.meta.schema],
                 "Type": [str(d) for _, d in t.meta.schema],
                 "Key": ["PRI" if c in t.meta.primary_key else ""
                         for c, _ in t.meta.schema]},
                {"Field": dt.VARCHAR, "Type": dt.VARCHAR,
                 "Key": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowIndexes):
            ixs = self.catalog.indexes_on(stmt.name)
            b = Batch.from_pydict(
                {"Key_name": [ix.name for ix in ixs],
                 "Algo": [ix.algo for ix in ixs],
                 "Columns": [",".join(ix.columns) for ix in ixs],
                 "Dirty": [int(ix.dirty) for ix in ixs]},
                {"Key_name": dt.VARCHAR, "Algo": dt.VARCHAR,
                 "Columns": dt.VARCHAR, "Dirty": dt.INT64})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowVariables):
            import re as _re
            names = sorted(self.variables)
            if stmt.like:
                # SQL LIKE: only % and _ are wildcards; everything
                # else (incl. regex/fnmatch metachars) is literal
                pat = "".join(".*" if ch == "%" else "." if ch == "_"
                              else _re.escape(ch) for ch in stmt.like)
                rx = _re.compile(f"^{pat}$")
                names = [n for n in names if rx.match(n)]
            b = Batch.from_pydict(
                {"Variable_name": names,
                 "Value": [str(self.variables[n]) for n in names]},
                {"Variable_name": dt.VARCHAR, "Value": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.SetVariable):
            if isinstance(stmt.value, ast.Literal):
                value = stmt.value.value
                # fault injection control (reference: mo_ctl addfaultpoint)
                from matrixone_tpu.utils.fault import INJECTOR, parse_spec
                if stmt.name == "fault_point" and isinstance(value, str):
                    try:
                        INJECTOR.add(**parse_spec(value))
                    except ValueError as e:
                        raise BindError(str(e))
                elif stmt.name == "fault_point_clear":
                    INJECTOR.remove(str(value))
                else:
                    self.variables[stmt.name] = value
            return Result()
        if isinstance(stmt, ast.CreateSnapshot):
            self.catalog.create_snapshot(stmt.name)
            return Result()
        if isinstance(stmt, ast.DropSnapshot):
            self.catalog.drop_snapshot(stmt.name)
            return Result()
        if isinstance(stmt, ast.ShowTrace):
            # recent traces from the motrace ring, oldest first
            from matrixone_tpu.utils import motrace
            ts = motrace.TRACER.traces()
            b = Batch.from_pydict(
                {"TraceId": [t["trace_id"] for t in ts],
                 "Root": [t["root"] for t in ts],
                 "Procs": [t["procs"] for t in ts],
                 "Spans": [t["spans"] for t in ts],
                 "StartUs": [t["ts_us"] for t in ts],
                 "DurationMs": [t["dur_ms"] for t in ts]},
                {"TraceId": dt.VARCHAR, "Root": dt.VARCHAR,
                 "Procs": dt.VARCHAR, "Spans": dt.INT64,
                 "StartUs": dt.INT64, "DurationMs": dt.FLOAT64})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowSnapshots):
            names = sorted(self.catalog.snapshots)
            b = Batch.from_pydict(
                {"Snapshot": names,
                 "Timestamp": [self.catalog.snapshots[n] for n in names]},
                {"Snapshot": dt.VARCHAR, "Timestamp": dt.INT64})
            return Result(batch=b)
        if isinstance(stmt, ast.RestoreTable):
            snaps = self.catalog.snapshots
            if stmt.snapshot not in snaps:
                raise BindError(f"no such snapshot {stmt.snapshot!r}")
            n = self.catalog.restore_table(stmt.table, snaps[stmt.snapshot])
            return Result(affected=n)
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt)
        if isinstance(stmt, ast.Update):
            return self._update(stmt)
        if isinstance(stmt, ast.BeginTxn):
            if self.txn is not None:
                old, self.txn = self.txn, None
                old.commit()            # MySQL: BEGIN commits the open txn
            self.txn = self.txn_client.begin()
            return Result()
        if isinstance(stmt, ast.CommitTxn):
            if self.txn is not None:
                old, self.txn = self.txn, None   # clear even on conflict
                affected = old.commit()
                return Result(affected=affected)
            return Result()
        if isinstance(stmt, ast.RollbackTxn):
            if self.txn is not None:
                self.txn.rollback()
                self.txn = None
            return Result()
        raise BindError(f"unsupported statement {type(stmt).__name__}")

    def _fragment_annotator(self, node):
        """EXPLAIN decoration: compile the operator tree (cheap, no
        execution) and mark which plan nodes fused into which fragment."""
        from matrixone_tpu.vm import fusion
        if not fusion.enabled(self._ctx()):
            return None
        op = compile_plan(node, self._ctx())
        fmap = fusion.fragment_map(op)
        if not fmap:
            return None
        roles = fusion.fragment_roles(op)
        return lambda n: ((f" fragment=f{fmap[id(n)]}"
                           + (f" {roles[id(n)]}" if id(n) in roles
                              else ""))
                          if id(n) in fmap else "")

    def _exchange_annotator(self, node):
        """EXPLAIN decoration for the device-shard executor: mark each
        exchange the CBO planned — exchange=broadcast|shuffle|local on
        the spine joins and the probe scan (parallel/dist_query.py)."""
        shards = int(self.variables.get("query_shards", 0) or 0)
        if shards < 2:
            return None
        from matrixone_tpu.parallel import dist_query as DQ
        modes = DQ.explain_exchanges(
            node, self.catalog, shards,
            min_rows=int(self.variables.get("dist_min_rows", 100_000)))
        if not modes:
            return None
        return lambda n: (f" exchange={modes[id(n)]}"
                          if id(n) in modes else "")

    def _explain_analyze(self, node) -> str:
        """Run the plan, recording per-operator batches/rows/time
        (reference: EXPLAIN ANALYZE via process.Analyzer/OpAnalyzer,
        vm/types.go:256 + compile/analyze_module.go)."""
        import time as _time
        import jax as _jax
        import jax.numpy as _jnp
        op = compile_plan(node, self._ctx())
        stats = {}

        def wrap(o):
            orig = o.execute
            st = stats.setdefault(id(o), {"op": type(o).__name__,
                                          "batches": 0, "rows": 0,
                                          "seconds": 0.0})

            def timed():
                it = orig()
                while True:
                    t0 = _time.perf_counter()
                    try:
                        ex = next(it)
                    except StopIteration:
                        st["seconds"] += _time.perf_counter() - t0
                        return
                    # settle the batch's device work before stamping the
                    # operator: JAX dispatch is async, so without the
                    # sync a heavy projection's time would be billed to
                    # whichever DOWNSTREAM operator first touches the
                    # arrays (lazy-dispatch skew)
                    from matrixone_tpu.utils import san as _san
                    _san.check_blocking("device.sync")
                    for c in ex.batch.columns.values():
                        _jax.block_until_ready(c.data)
                    st["seconds"] += _time.perf_counter() - t0
                    st["batches"] += 1
                    st["rows"] += int(_jax.device_get(
                        _jnp.sum(ex.mask.astype(_jnp.int32))))
                    yield ex
            o.execute = timed
            for attr in ("child", "left", "right"):
                c = getattr(o, attr, None)
                if c is not None:
                    wrap(c)
            for c in getattr(o, "children", []) or []:
                wrap(c)
        wrap(op)
        for _ in op.execute():
            pass

        def render(o, indent=0):
            from matrixone_tpu.sql.plan import _udf_call_notes
            from matrixone_tpu.vm.fusion import FusedFragmentOp
            st = stats[id(o)]
            notes = _udf_call_notes(getattr(o, "node", None)) \
                if getattr(o, "node", None) is not None else ""
            line = ("  " * indent + f"{st['op']}{notes}  rows={st['rows']} "
                    f"batches={st['batches']} time={st['seconds']*1000:.1f}ms")
            out = [line]
            if isinstance(o, FusedFragmentOp):
                fs = o.last_stats
                build = ("" if "build_dispatches" not in fs else
                         f" build_dispatches={fs['build_dispatches']}")
                out.append(
                    "  " * (indent + 1)
                    + f"fragment f{o.fragment_id} [{o.describe()}] "
                      f"mode={fs['mode']} dispatches={fs['dispatches']} "
                      f"trace_ms={fs['trace_ms']:.1f} "
                      f"compile_cache={fs['cache']}" + build)
            if notes:
                # the UdfCall rides the operator's pull loop: its
                # rows/batches ARE the operator's (EXPLAIN ANALYZE
                # surface for the udf subsystem)
                out.append("  " * (indent + 1)
                           + f"{notes.strip()}  rows={st['rows']} "
                             f"batches={st['batches']}")
            for attr in ("child", "left", "right"):
                c = getattr(o, attr, None)
                if c is not None:
                    out.extend(render(c, indent + 1))
            for c in getattr(o, "children", []) or []:
                out.extend(render(c, indent + 1))
            return out
        return "\n".join(render(op))

    # ---------------------------------------------------- subquery inlining
    def _run_subquery(self, sel):
        """Execute a nested subquery with a nesting bound (clean
        BindError instead of a RecursionError deep in the engine)."""
        d = getattr(self, "_subq_depth", 0)
        if d > 64:
            raise BindError("subquery nesting too deep")
        self._subq_depth = d + 1
        try:
            return self._select(sel)
        finally:
            self._subq_depth = d

    def _inline_subqueries(self, node, ctes=None):
        """Execute uncorrelated subqueries once and inline the results
        (reference: the planner turns these into joins; execute-once has
        identical semantics for the uncorrelated case). Correlated
        subqueries surface as 'unknown column' from the inner bind."""
        import dataclasses as dc
        if isinstance(node, ast.Subquery):
            if ctes:
                node.select.ctes = list(ctes) + list(node.select.ctes)
            r = self._run_subquery(node.select)
            rows = r.rows()
            if len(r.column_names) != 1:
                raise BindError("scalar subquery must return one column")
            if len(rows) > 1:
                raise BindError("scalar subquery returned more than one row")
            v = rows[0][0] if rows else None
            return _param_literal(v)
        if isinstance(node, ast.Exists):
            inner_limit = (1 if node.select.limit is None
                           else min(1, node.select.limit))
            sub = dc.replace(node.select, limit=inner_limit)
            if ctes:
                sub.ctes = list(ctes) + list(sub.ctes)
            r = self._run_subquery(sub)
            has = len(r.rows()) > 0
            return ast.Literal(has != node.negated, "bool")
        if isinstance(node, ast.InList) and len(node.items) == 1 \
                and isinstance(node.items[0], ast.Subquery):
            if ctes:
                node.items[0].select.ctes = \
                    list(ctes) + list(node.items[0].select.ctes)
            r = self._run_subquery(node.items[0].select)
            if len(r.column_names) != 1:
                raise BindError("IN subquery must return one column")
            vals = [row[0] for row in r.rows()]
            if node.negated and any(v is None for v in vals):
                # NOT IN with NULLs is never TRUE (SQL ternary logic)
                return ast.Literal(False, "bool")
            vals = [v for v in vals if v is not None]
            if not vals:
                return ast.Literal(bool(node.negated), "bool")
            return ast.InList(node.expr,
                              [_param_literal(v) for v in vals],
                              node.negated)
        if dc.is_dataclass(node) and isinstance(node, ast.Node) \
                and not isinstance(node, (ast.SubqueryRef,)):
            for f in dc.fields(node):
                v = getattr(node, f.name)
                if isinstance(v, ast.Node):
                    setattr(node, f.name,
                            self._inline_subqueries(v, ctes))
                elif isinstance(v, list):
                    setattr(node, f.name, [
                        self._inline_subqueries(x, ctes)
                        if isinstance(x, ast.Node) else
                        tuple(self._inline_subqueries(y, ctes)
                              if isinstance(y, ast.Node) else y
                              for y in x) if isinstance(x, tuple) else x
                        for x in v])
        return node

    def _prepare_select(self, sel) -> None:
        """Inline uncorrelated subqueries in WHERE/HAVING/select items
        (not derived tables — those bind as plans)."""
        if isinstance(sel, ast.Union):
            for arm in sel.selects:
                self._prepare_select(arm)
            return
        if not isinstance(sel, ast.Select):
            return
        ctes = sel.ctes   # WITH scope is visible inside subqueries
        for i, (_name, sub) in enumerate(ctes):
            # a CTE body sees only EARLIER ctes
            if isinstance(sub, ast.Select) and not sub.ctes:
                sub.ctes = list(ctes[:i])
            self._prepare_select(sub)
        # derived tables in FROM get the same treatment (their subqueries
        # may be correlated against their own FROM); guarded by a marker so
        # the decorrelation-added derived table below is prepared exactly
        # once
        def prep_from(f):
            if isinstance(f, ast.SubqueryRef):
                if getattr(f.select, "_mo_prepared", False):
                    return
                if isinstance(f.select, ast.Select) and not f.select.ctes:
                    f.select.ctes = list(ctes)
                self._prepare_select(f.select)
            elif isinstance(f, ast.Join):
                prep_from(f.left)
                prep_from(f.right)
        prep_from(sel.from_)
        # decorrelate correlated EXISTS / scalar-agg subqueries into joins
        # (reference: plan builder subquery flattening); uncorrelated ones
        # are inlined below by executing once
        from matrixone_tpu.sql.decorrelate import decorrelate_select
        decorrelate_select(sel, self.catalog, dict(ctes))
        for sj in sel.semijoins:
            self._prepare_select(sj.select)
        prep_from(sel.from_)   # derived tables ADDED by decorrelation
        for it in sel.items:
            it.expr = self._inline_subqueries(it.expr, ctes=ctes)
        if sel.where is not None:
            sel.where = self._inline_subqueries(sel.where, ctes=ctes)
        if sel.having is not None:
            sel.having = self._inline_subqueries(sel.having, ctes=ctes)
        sel._mo_prepared = True

    def _try_mo_ctl(self, sel) -> Optional[Result]:
        """`select mo_ctl('cmd'[, 'arg'])` — ops control functions
        (reference: plan/function/ctl mo_ctl): checkpoint | merge | flush."""
        if not (isinstance(sel, ast.Select) and sel.from_ is None
                and len(sel.items) == 1):
            return None
        e = sel.items[0].expr
        if not (isinstance(e, ast.FuncCall) and e.name == "mo_ctl"):
            return None
        args = [a.value for a in e.args if isinstance(a, ast.Literal)]
        cmd = str(args[0]).lower() if args else ""
        arg = str(args[1]) if len(args) > 1 else ""
        if cmd == "checkpoint":
            self.catalog.checkpoint()
            out = "checkpoint done"
        elif cmd == "merge":
            def describe(code):
                if code == -1:
                    return "skipped (too few segments)"
                if code == -2:
                    return "deferred (open transactions)"
                if code == -3:
                    return "deferred (lost race with a concurrent " \
                           "write — retry)"
                return f"kept {code} rows"
            if arg in ("status", "run", "pause", "resume", "gc"):
                # background compaction scheduler ops surface
                # (storage/merge_sched) — the lint/san/crash pattern
                import json as _json
                from matrixone_tpu.storage import merge_sched
                sched = merge_sched.scheduler_for(self.catalog)
                if arg == "status":
                    out = _json.dumps(sched.status(), sort_keys=True,
                                      default=str)
                elif arg == "run":
                    out = _json.dumps(sched.run_cycle(), sort_keys=True,
                                      default=str)
                elif arg == "gc":
                    out = _json.dumps(self.catalog.gc_fences(),
                                      sort_keys=True)
                elif arg == "pause":
                    sched.pause()
                    out = "merge scheduler paused"
                else:
                    sched.resume()
                    out = "merge scheduler resumed"
            elif not arg:
                results = []
                for name in list(self.catalog.tables):
                    if not name.startswith("system_"):
                        r = self.catalog.merge_table(name,
                                                     checkpoint=False)
                        if r >= 0:
                            results.append(f"{name}: {describe(r)}")
                if results:
                    self.catalog.checkpoint()
                out = "; ".join(results) or "nothing to merge"
            else:
                out = f"merge {arg}: " + describe(
                    self.catalog.merge_table(arg))
        elif cmd == "flush":
            if hasattr(self.catalog, "stmt_recorder"):
                self.catalog.stmt_recorder.flush()
            out = "flushed"
        elif cmd == "fault":
            # operational fault-point surface (reference: mo_ctl
            # addfaultpoint): status | clear | arm:<spec>
            import json as _json
            from matrixone_tpu.utils.fault import INJECTOR, parse_spec
            if arg in ("", "status"):
                out = _json.dumps(INJECTOR.describe(), sort_keys=True)
            elif arg == "clear":
                INJECTOR.clear()
                out = "faults cleared"
            elif arg.startswith("arm:"):
                try:
                    INJECTOR.add(**parse_spec(arg[4:]))
                except ValueError as e:
                    raise BindError(str(e))
                out = f"armed {arg[4:].split(':', 1)[0]}"
            else:
                raise BindError(f"unknown fault subcommand {arg!r}; "
                                "use status | clear | arm:<spec>")
        elif cmd == "serving":
            # serving-layer ops surface: plan/result cache + admission
            # (matrixone_tpu/serving; reference: proxy/queryservice tier)
            import json as _json
            from matrixone_tpu.serving import serving_for
            sv = serving_for(self.catalog)
            if arg in ("", "status"):
                out = _json.dumps(sv.status(), sort_keys=True,
                                  default=str)
            elif arg == "clear":
                sv.clear()
                out = "serving caches cleared"
            elif arg.startswith("slots:"):
                try:
                    sv.admission.slots = int(arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad slot count in {arg!r}")
                out = f"admission slots = {sv.admission.slots}"
            elif arg.startswith("account_slots:"):
                try:
                    sv.admission.account_slots = int(
                        arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad account slot count in {arg!r}")
                out = (f"admission account_slots = "
                       f"{sv.admission.account_slots}")
            elif arg in ("plan:on", "plan:off"):
                sv.plan_cache.enabled = arg.endswith(":on")
                if not sv.plan_cache.enabled:
                    sv.plan_cache.clear()
                out = f"plan cache {'on' if sv.plan_cache.enabled else 'off'}"
            elif arg.startswith("result:"):
                sub = arg.split(":", 1)[1]
                if sub == "off":
                    sv.result_cache.max_bytes = 0
                    sv.result_cache.clear()
                elif sub == "on":
                    if sv.result_cache.max_bytes <= 0:
                        sv.result_cache.max_bytes = 64 << 20
                else:
                    try:
                        mb = int(sub)
                    except ValueError:
                        raise BindError(
                            f"unknown result subcommand {sub!r}; use "
                            f"on | off | <mb>")
                    # shrinking must evict NOW: a read-hot workload never
                    # calls put(), so its eviction loop would not run
                    sv.result_cache.set_max_bytes(mb << 20)
                    if sv.result_cache.max_bytes <= 0:
                        sv.result_cache.clear()
                out = f"result cache {sv.result_cache.max_bytes >> 20} MB"
            else:
                raise BindError(
                    f"unknown serving subcommand {arg!r}; use status | "
                    f"clear | slots:<n> | account_slots:<n> | "
                    f"plan:<on|off> | result:<on|off|mb>")
        elif cmd == "udf":
            # UDF subsystem ops surface: compile-cache + tier counters
            import json as _json
            from matrixone_tpu import udf as U
            from matrixone_tpu.udf import catalog as ucat
            if arg in ("", "status"):
                st = U.stats()
                st["functions"] = len(ucat.registry_for(self.catalog))
                out = _json.dumps(st, sort_keys=True)
            elif arg == "clear":
                U.COMPILE_CACHE.clear()
                out = "udf compile cache cleared"
            else:
                raise BindError(f"unknown udf subcommand {arg!r}; "
                                "use status | clear")
        elif cmd == "fusion":
            # whole-plan fusion ops surface (vm/fusion.py): fragment
            # compile-cache + execution-mode counters, matching the
            # mo_ctl('udf'|'serving') pattern
            import json as _json
            from matrixone_tpu.vm import fusion
            if arg in ("", "status"):
                out = _json.dumps(fusion.stats(), sort_keys=True)
            elif arg == "clear":
                fusion.CACHE.clear()
                out = "fusion compile cache cleared"
            else:
                raise BindError(f"unknown fusion subcommand {arg!r}; "
                                "use status | clear")
        elif cmd == "lint":
            # static-analysis ops surface (tools/molint): checker
            # inventory, last-run findings, suppression count —
            # mirrors the mo_ctl('udf'|'serving'|'rpc') pattern
            import json as _json
            try:
                from tools import molint
            except ImportError:
                raise BindError(
                    "molint unavailable: the tools/ package is not on "
                    "sys.path (run from a repo checkout)")
            if arg in ("", "status"):
                out = _json.dumps(molint.last_run_status(),
                                  sort_keys=True)
            elif arg == "run":
                _f, st = molint.run_checks(molint.repo_root())
                out = _json.dumps(st, sort_keys=True)
            else:
                raise BindError(f"unknown lint subcommand {arg!r}; "
                                "use status | run")
        elif cmd == "san":
            # runtime concurrency sanitizer ops surface (utils/san.py):
            # findings/edges/daemon report + clear — mirrors the
            # mo_ctl('fault'|'lint') pattern
            import json as _json
            from matrixone_tpu.utils import san as _san
            if arg in ("", "status"):
                out = _json.dumps(_san.report(), sort_keys=True)
            elif arg == "clear":
                _san.clear()
                out = "san findings cleared"
            else:
                raise BindError(f"unknown san subcommand {arg!r}; "
                                "use status | clear")
        elif cmd == "qa":
            # differential query-equivalence analyzer ops surface
            # (tools/moqa + utils/qa.py): pair inventory, canary
            # report, last corpus run; run:<seed> executes a small
            # in-process corpus — mirrors the mo_ctl('lint'|'san')
            # pattern
            import json as _json
            try:
                from tools import moqa
            except ImportError:
                raise BindError(
                    "moqa unavailable: the tools/ package is not on "
                    "sys.path (run from a repo checkout)")
            if arg in ("", "status"):
                out = _json.dumps(moqa.last_run_status(),
                                  sort_keys=True, default=str)
            elif arg == "clear":
                from matrixone_tpu.utils import qa as _qa
                _qa.clear()
                out = "qa findings cleared"
            elif arg.startswith("run:"):
                try:
                    seed = int(arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad seed in {arg!r}")
                # a QUICK in-process probe: env-toggled pairs only
                # (the heavyweight replay pairs belong to the corpus
                # gate / CLI, not an ops command)
                rep = moqa.run_corpus(seed=seed,
                                      queries_per_scenario=6,
                                      pairs=["fusion", "dense-groups",
                                             "plan-cache"],
                                      reduce_findings=0,
                                      oracle_fraction=0.34)
                out = _json.dumps(
                    {k: rep[k] for k in ("seed", "queries", "pairs",
                                         "total_checks", "seconds")}
                    | {"findings": len(rep["findings"])},
                    sort_keys=True)
            else:
                raise BindError(f"unknown qa subcommand {arg!r}; "
                                "use status | clear | run:<seed>")
        elif cmd == "keys":
            # trace-capture / cache-key auditor ops surface
            # (utils/keys.py + tools/mokey): armed state, audited
            # sites, mismatch findings with both stacks, last static
            # run — mirrors the mo_ctl('lint'|'san'|'qa') pattern
            import json as _json
            from matrixone_tpu.utils import keys as _keys
            if arg in ("", "status"):
                st = _keys.report()
                try:
                    from tools import mokey as _mokey
                    st["static"] = _mokey.last_run_status()
                except ImportError:
                    st["static"] = None
                out = _json.dumps(st, sort_keys=True, default=str)
            elif arg == "clear":
                _keys.clear()
                out = "key-audit records and findings cleared"
            elif arg == "audit:on":
                _keys.arm()
                out = "key audit armed"
            elif arg == "audit:off":
                _keys.disarm()
                out = "key audit disarmed"
            else:
                raise BindError(f"unknown keys subcommand {arg!r}; "
                                "use status | clear | audit:on | "
                                "audit:off")
        elif cmd == "crash":
            # crash-recovery sweep ops surface (utils/crash.py +
            # tools/mocrash): journal/recording state, last sweep
            # summary; run:<seed> executes a small in-process sweep —
            # mirrors the mo_ctl('lint'|'san'|'qa'|'keys') pattern
            import json as _json
            from matrixone_tpu.utils import crash as _crash
            if arg in ("", "status"):
                try:
                    from tools import mocrash as _mocrash
                    out = _json.dumps(_mocrash.last_run_status(),
                                      sort_keys=True, default=str)
                except ImportError:
                    out = _json.dumps(_crash.report(), sort_keys=True,
                                      default=str)
            elif arg == "clear":
                _crash.clear()
                out = "crash sweep records cleared"
            elif arg.startswith("run:"):
                try:
                    seed = int(arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad seed in {arg!r}")
                try:
                    from tools import mocrash as _mocrash
                except ImportError:
                    raise BindError(
                        "mocrash unavailable: the tools/ package is "
                        "not on sys.path (run from a repo checkout)")
                # a QUICK in-process probe: capped points, engine
                # scenario only (the full sweep belongs to the gate /
                # CLI, not an ops command)
                rep = _mocrash.run_sweep(seed=seed, points=40,
                                         scenario="engine")
                out = _json.dumps(
                    {k: rep[k] for k in ("seed", "events", "points",
                                         "recoveries", "seconds")}
                    | {"findings": len(rep["findings"])},
                    sort_keys=True)
            else:
                raise BindError(f"unknown crash subcommand {arg!r}; "
                                "use status | clear | run:<seed>")
        elif cmd == "mview":
            # materialized-view ops surface: registry + per-view
            # watermark/mode, on-demand refresh — matching the
            # mo_ctl('udf'|'fusion'|'serving') pattern
            import json as _json
            from matrixone_tpu import mview as MV
            if arg in ("", "status"):
                out = _json.dumps(MV.stats(self.catalog),
                                  sort_keys=True, default=str)
            elif arg.startswith("refresh:"):
                name = arg.split(":", 1)[1]
                n = self._refresh_mview(name)
                out = f"refreshed {name}: {n} rows"
            else:
                raise BindError(f"unknown mview subcommand {arg!r}; "
                                "use status | refresh:<view>")
        elif cmd == "trace":
            # distributed-tracing ops surface (utils/motrace.py):
            # status | on | off | clear | sample:<f> | slow:<ms> |
            # dump:<path> — mirrors the mo_ctl('fault'|'san') pattern
            import json as _json
            from matrixone_tpu.utils import motrace as _mt
            if arg in ("", "status"):
                out = _json.dumps(_mt.TRACER.status(), sort_keys=True)
            elif arg == "on":
                _mt.TRACER.arm()
                out = "trace armed"
            elif arg == "off":
                _mt.TRACER.disarm()
                out = "trace disarmed"
            elif arg == "clear":
                _mt.TRACER.clear()
                out = "trace ring cleared"
            elif arg.startswith("sample:"):
                try:
                    _mt.TRACER.sample = float(arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad sample fraction in {arg!r}")
                out = f"trace sample = {_mt.TRACER.sample}"
            elif arg.startswith("slow:"):
                try:
                    _mt.TRACER.slow_ms = float(arg.split(":", 1)[1])
                except ValueError:
                    raise BindError(f"bad slow threshold in {arg!r}")
                out = f"trace slow_ms = {_mt.TRACER.slow_ms}"
            elif arg.startswith("dump:"):
                paths = _mt.dump(arg.split(":", 1)[1])
                out = (f"dumped {len(paths)} trace(s) -> "
                       + (paths[0].rsplit('/', 1)[0] if paths
                          else "nothing to dump"))
            else:
                raise BindError(
                    f"unknown trace subcommand {arg!r}; use status | "
                    f"on | off | clear | sample:<f> | slow:<ms> | "
                    f"dump:<path>")
        elif cmd == "metrics":
            # scrape surface: the full registry in Prometheus text
            # exposition format (also served by `python -m
            # tools.moscrape`); 'snapshot' returns the structured dict
            import json as _json
            from matrixone_tpu.utils import metrics as _m
            if arg in ("", "dump"):
                out = _m.REGISTRY.render()
            elif arg == "snapshot":
                out = _json.dumps(_m.REGISTRY.snapshot(),
                                  sort_keys=True)
            else:
                raise BindError(f"unknown metrics subcommand {arg!r}; "
                                "use dump | snapshot")
        elif cmd == "rpc":
            # per-peer circuit breaker state + the CN's logtail breaker
            import json as _json
            from matrixone_tpu.cluster.rpc import breaker_states
            st = {"breakers": breaker_states()}
            consumer = getattr(self.catalog, "consumer", None)
            if consumer is not None:
                st["logtail"] = {
                    "state": "open" if consumer.broken else "closed",
                    "strikes": consumer.strikes,
                    "applied_ts": consumer.applied_ts,
                    "last_error": consumer.last_error}
            out = _json.dumps(st, sort_keys=True)
        else:
            raise BindError(f"unknown mo_ctl command {cmd!r}")
        b = Batch.from_pydict({"mo_ctl": [out]}, {"mo_ctl": dt.VARCHAR})
        return Result(batch=b)

    def _cbo(self, node):
        """Stats-driven join reordering (reference: plan/query_builder.go
        determineJoinOrder). `SET cbo = 0` disables it for plan debugging."""
        if str(self.variables.get("cbo", 1)) in ("0", "off", "false"):
            return node
        from matrixone_tpu.sql.cbo import optimize_plan
        return optimize_plan(node, self.catalog)

    # ------------------------------------------------------------- select
    def _select(self, sel: ast.Select, serving=None) -> Result:
        from matrixone_tpu.sql.optimize import apply_indices
        ctl = self._try_mo_ctl(sel)
        if ctl is not None:
            return ctl
        sv = serving if (serving is not None and self.txn is None) else None
        lazy = sv is not None and sv.owns_pristine(sel)
        if sv is not None and not sv.usable_for(sel):
            sv = None
        if sv is None and lazy:
            # caches declined but the caller handed us the pristine
            # template: bind a private substituted copy, never the
            # shared template itself
            sel = serving.instantiate(raise_errors=True)
            lazy = False
        ann = getattr(self, "_exec_ann", None)
        # ---- result cache: serve the whole statement if every scanned
        # table is still at the version the entry was stored under
        if sv is not None and sv.result_enabled():
            hit = sv.state.result_cache.get(
                sv.result_key(), self._recompute_versions)
            if hit is not None:
                batch, stored = hit
                # privileges gate CACHED results too: the entry's
                # version tuple carries the scanned table names, so an
                # unprivileged user in the same account can never read
                # a colleague's warm rows
                if self.auth is not None and not self.auth.is_admin:
                    for ent in stored[1]:
                        self._check("select", ent[0])
                if ann is not None:
                    ann["cache_hit"] = "result"
                return Result(batch=batch)
        # ---- plan cache: skip prepare/bind/optimize on a hit (only in
        # template mode — raw-path literals carry no parameter tags)
        node = None
        plan_missed = False
        if sv is not None and sv.template_mode and sv.plan_enabled():
            gens = self._serving_gens()
            outcome, node = sv.state.plan_cache.lookup(
                sv.plan_key(), gens[0], gens[1], sv.full)
            plan_missed = outcome == "miss"
            if node is not None and ann is not None \
                    and ann["cache_hit"] == "none":
                ann["cache_hit"] = "plan"
        if node is None:
            from matrixone_tpu.utils import motrace
            if lazy:
                # instantiate the template only now: a plan-cache hit
                # above never pays the AST deepcopy at all
                sel = sv.instantiate(raise_errors=True)
            with motrace.span("plan"):
                self._prepare_select(sel)
                node = Binder(self.catalog).bind_statement(sel)
                node = self._cbo(node)
                node = apply_indices(
                    node, self.catalog,
                    nprobe=int(self.variables.get("ivf_nprobe", 8)),
                    skip_tables=self._index_skip_tables())
            if sv is not None and sv.template_mode \
                    and sv.plan_enabled() and plan_missed:
                # store under the gens captured at LOOKUP time: a DDL
                # racing the bind must orphan this entry, so the plan
                # bound against the old schema never passes the gen
                # check under the post-DDL generation
                sv.state.plan_cache.store(
                    sv.plan_key(), node, len(sv.full), gens[0], gens[1])
        if self.auth is not None and not self.auth.is_admin:
            for tname in _plan_tables(node):
                self._check("select", tname)
        # versions and the execution snapshot must be captured
        # ATOMICALLY under the engine commit lock: a commit bumps table
        # versions BEFORE advancing committed_ts, so a lock-free capture
        # can pair mid-commit versions with an old snapshot — the entry
        # then publishes old rows under a key that matches the
        # post-commit state (the staleness chaos drill caught exactly
        # this).  Execution is then FROZEN at the captured ts.
        versions = frozen = None
        if sv is not None and sv.result_enabled():
            versions, frozen = self._capture_versions(node)
        ctx = self._ctx(frozen_ts=frozen)
        node2 = self._maybe_distribute(node, ctx)
        # ---- compiled-tree reuse: a plan-cache hit used to rebuild the
        # full operator tree anyway; the tree of the last completed
        # execution rides the plan-cache entry (identity-guard POP: a
        # concurrent execution finds None and compiles its own)
        op = None
        tree_cacheable = (sv is not None and sv.template_mode
                          and sv.plan_enabled() and node2 is node)
        tree_vars = self._tree_vars_sig() if tree_cacheable else None
        if tree_cacheable:
            from matrixone_tpu.utils import keys as keyaudit
            if keyaudit.armed():
                # each build-time knob re-read INDEPENDENTLY of
                # _tree_vars_sig: a knob that starts steering tree
                # construction without riding the signature (the
                # kill-switches-not-in-_tree_vars_sig bug class)
                # mismatches here instead of reusing a wrong tree
                keyaudit.audit(
                    "serving/plan_cache.py:tree",
                    (sv.plan_key(), gens[0], gens[1], tree_vars),
                    self._tree_vars_deps())
            cached = sv.state.plan_cache.take_tree(
                sv.plan_key(), gens[0], gens[1], tree_vars)
            if cached is not None:
                op = sv.state.plan_cache.rebind_tree(cached, sv.full)
                if op is not None:
                    from matrixone_tpu.vm.compile import retarget_tree
                    retarget_tree(op, ctx)
                    # the tree's plan nodes are the authoritative ones
                    # for this execution (params patched in place)
                    node = cached["plan"]
        built = None
        if op is None:
            op = compile_plan(node2, ctx)
            node = node2
            if tree_cacheable:
                built = {"op": op, "plan": node2}
        else:
            built = cached
        out_batches = []
        for ex in op.execute():
            # KILL lands between device batches (queryservice): the pull
            # loop is the engine's natural preemption point
            self._procs.check_killed(self.conn_id)
            out_batches.append(self._to_host(ex, node.schema))
        if tree_cacheable and built is not None:
            sv.state.plan_cache.put_tree(sv.plan_key(), built, gens[0],
                                         gens[1], tree_vars)
        if not out_batches:
            empty = {n: Vector.from_values([], d) for n, d in node.schema}
            result = Result(batch=Batch(empty))
        elif len(out_batches) == 1:
            result = Result(batch=out_batches[0])
        else:
            # concatenate host batches
            cols = {}
            for n, d in node.schema:
                vals = []
                for b in out_batches:
                    vals.extend(b.columns[n].to_pylist())
                cols[n] = Vector.from_values(vals, d)
            result = Result(batch=Batch(cols))
        if versions is not None and result.batch is not None:
            sv.state.result_cache.put(sv.result_key(), result.batch,
                                      versions)
        return result

    def _tree_vars_sig(self) -> tuple:
        """Session state BAKED into a compiled operator tree at build
        time (everything else is re-read through the ExecContext at
        execute time).  DERIVED from _tree_vars_deps so the signature
        and the audited dep set cannot drift: a knob added to the deps
        rides the signature automatically, and there is no second list
        to forget."""
        return tuple(self._tree_vars_deps().values())

    def _tree_vars_deps(self) -> dict:
        """Every build-time knob a compiled operator tree bakes, NAMED:
        pallas kernel selection, the fusion gates — incl. the
        join/window/topk kill-switches the planner consults while
        building fragments — and the join build budget (JoinOp
        snapshots it at construction).  The armed key auditor
        (utils/keys.py) hashes these per tree take/put; adding a
        build-time knob means adding a row HERE (dict order is part of
        the signature — append, don't reorder)."""
        from matrixone_tpu.ops import pallas_kernels as PK
        from matrixone_tpu.vm import fusion
        return {
            "use_pallas": bool(PK.effective_use_pallas(
                self.variables.get("use_pallas"))),
            "plan_fusion": fusion.enabled(self._ctx()),
            "fusion_join": fusion.join_fusion_enabled(),
            "fusion_window": fusion.window_fusion_enabled(),
            "fusion_topk": fusion.topk_fusion_enabled(),
            "join_build_budget":
                self.variables.get("join_build_budget"),
        }

    # ------------------------------------------------- serving versions
    def _serving_gens(self):
        return (getattr(self.catalog, "ddl_gen", 0),
                getattr(self.catalog, "stats_gen", 0))

    def _capture_versions(self, node):
        """-> ((ddl_gen, per-scan table versions), frozen_ts) for the
        result cache, or (None, None) when any scanned table is
        unversionable (external / scan-in-place tables change outside
        the commit funnel).  Runs under the engine commit lock so the
        version tuple and the snapshot ts are one consistent point —
        never a mid-commit mixture."""
        from matrixone_tpu.serving.plan_cache import iter_plan_values
        lock = getattr(self.catalog, "_commit_lock", None)
        if lock is None:
            return None, None
        scans = set()
        for v in iter_plan_values(node):
            if isinstance(v, (P.Scan, P.VectorTopK, P.FulltextTopK)):
                scans.add((v.table, getattr(v, "as_of_ts", None)))
        with lock:
            ts0 = getattr(self.catalog, "committed_ts", None)
            entries = []
            for table, as_of in sorted(scans, key=lambda x: (x[0],
                                                             x[1] or -1)):
                try:
                    t = self.catalog.get_table(table)
                except Exception:   # noqa: BLE001 — raced drop: bypass
                    return None, None
                if as_of is not None and ts0 is not None \
                        and as_of <= ts0:
                    # strictly in the committed past: immutable (every
                    # future commit gets ts > committed_ts >= as_of).
                    # A future-dated as-of still SEES later commits, so
                    # it falls through to live versioning below.
                    entries.append((table, "asof", as_of))
                    continue
                ver = getattr(t, "last_commit_ts", None)
                if ver is None or getattr(t, "is_external", False):
                    return None, None
                entries.append((table, ver, len(t.segments),
                                len(t.tombstones)))
            if ts0 is None:
                return None, None
            return (getattr(self.catalog, "ddl_gen", 0),
                    tuple(entries)), ts0

    def _recompute_versions(self, stored):
        """Re-evaluate a stored entry's version tuple against the live
        catalog (under the commit lock: a mid-commit read could match a
        consistent future tuple and serve rows ahead of the frontier);
        any mismatch (incl. a dropped table) orphans the entry."""
        lock = getattr(self.catalog, "_commit_lock", None)
        if lock is None:
            return None
        try:
            with lock:
                entries = []
                for ent in stored[1]:
                    if ent[1] == "asof":
                        entries.append(ent)     # immutable past
                        continue
                    t = self.catalog.get_table(ent[0])
                    entries.append(
                        (ent[0], getattr(t, "last_commit_ts", -1),
                         len(t.segments), len(t.tombstones)))
                return (getattr(self.catalog, "ddl_gen", 0),
                        tuple(entries))
        except Exception:       # noqa: BLE001 — table gone: never match
            return None

    def _account_stmt(self, stmt: ast.Node) -> Optional[Result]:
        """CREATE ACCOUNT/USER/ROLE, GRANT/REVOKE, SHOW GRANTS
        (reference: frontend/authenticate.go handlers)."""
        from matrixone_tpu.frontend.auth import SYS_ACCOUNT, AuthError
        if isinstance(stmt, ast.CreateAccount):
            # only the sys account provisions tenants (reference rule)
            if self.auth is not None and self._acct() != SYS_ACCOUNT:
                raise AuthError("only the sys account can create accounts")
            self._check_admin()
            self._mgr().create_account(stmt.name, stmt.admin_user,
                                       stmt.admin_password,
                                       stmt.if_not_exists)
            return Result()
        if isinstance(stmt, ast.DropAccount):
            if self.auth is not None and self._acct() != SYS_ACCOUNT:
                raise AuthError("only the sys account can drop accounts")
            self._check_admin()
            self._mgr().drop_account(stmt.name)
            return Result()
        if isinstance(stmt, ast.CreateUser):
            self._check_admin()
            self._mgr().create_user(self._acct(), stmt.name,
                                    stmt.password, stmt.if_not_exists)
            return Result()
        if isinstance(stmt, ast.DropUser):
            self._check_admin()
            self._mgr().drop_user(self._acct(), stmt.name)
            return Result()
        if isinstance(stmt, ast.CreateRole):
            self._check_admin()
            self._mgr().create_role(self._acct(), stmt.name)
            return Result()
        if isinstance(stmt, ast.DropRole):
            self._check_admin()
            self._mgr().drop_role(self._acct(), stmt.name)
            return Result()
        if isinstance(stmt, ast.GrantPriv):
            self._check_admin()
            self._mgr().grant_priv(self._acct(), stmt.privs, stmt.obj,
                                   stmt.role)
            return Result()
        if isinstance(stmt, ast.RevokePriv):
            self._check_admin()
            self._mgr().revoke_priv(self._acct(), stmt.privs, stmt.obj,
                                    stmt.role)
            return Result()
        if isinstance(stmt, ast.GrantRole):
            self._check_admin()
            self._mgr().grant_role(self._acct(), stmt.role, stmt.user)
            return Result()
        if isinstance(stmt, ast.RevokeRole):
            self._check_admin()
            self._mgr().revoke_role(self._acct(), stmt.role, stmt.user)
            return Result()
        if isinstance(stmt, ast.ShowAccounts):
            from matrixone_tpu.frontend.auth import SYS_ACCOUNT, AuthError
            if self.auth is not None and self._acct() != SYS_ACCOUNT:
                raise AuthError(
                    "only the sys account can list accounts")
            m = self._mgr()._m()
            names = sorted(m["accounts"])
            b = Batch.from_pydict(
                {"Account": names,
                 "AdminName": [m["accounts"][n].get("admin_user", "")
                               for n in names]},
                {"Account": dt.VARCHAR, "AdminName": dt.VARCHAR})
            return Result(batch=b)
        if isinstance(stmt, ast.ShowGrants):
            user = stmt.user or (self.auth.user if self.auth else "root")
            if stmt.user and stmt.user != (
                    self.auth.user if self.auth else "root"):
                self._check_admin()
            rows = self._mgr().grants_for(self._acct(), user)
            b = Batch.from_pydict(
                {"Role": [r for r, _o, _p in rows],
                 "Object": [o for _r, o, _p in rows],
                 "Privilege": [p for _r, _o, p in rows]},
                {"Role": dt.VARCHAR, "Object": dt.VARCHAR,
                 "Privilege": dt.VARCHAR})
            return Result(batch=b)
        return None

    def _maybe_distribute(self, node, ctx):
        """Distributed scopes (reference: compile decides Magic: Remote,
        compile/types.go:162): when this CN knows peer fragment
        endpoints, qualifying plans execute their lower subtree across
        the peers and re-enter locally as a Materialized node. `SET
        dist = 0` disables; `dist_min_rows` tunes the size threshold.

        Device shards take PRIORITY over host peers: `SET query_shards
        = N` (env MO_QUERY_SHARDS) runs the same fragment split across
        N device shards of the local mesh — no serialization, no
        network — and falls through to peers/local when the plan or
        mesh does not qualify (parallel/dist_query.py)."""
        if self.txn is not None:
            return node
        if str(self.variables.get("dist", 1)) in ("0", "off", "false"):
            return node
        shards = int(self.variables.get("query_shards", 0) or 0)
        if shards >= 2:
            from matrixone_tpu.parallel import dist_query as DQ
            rebuilt = DQ.try_shard(
                node, self.catalog, ctx, shards,
                min_rows=int(self.variables.get("dist_min_rows",
                                                100_000)))
            if rebuilt is not None:
                return rebuilt
        peers = getattr(self.catalog, "dist_peers", None)
        if not peers:
            return node
        from matrixone_tpu.parallel import fragments as FR
        pool = FR.pool_for(self.catalog)
        rebuilt = FR.try_distribute(
            node, self.catalog, ctx, pool,
            min_rows=int(self.variables.get("dist_min_rows", 100_000)),
            batch_rows=int(self.variables.get("dist_batch_rows", 1 << 16)))
        return rebuilt if rebuilt is not None else node

    def _to_host(self, ex, schema) -> Batch:
        from matrixone_tpu.ops import filter as F
        # compact masked rows before leaving device
        n_out = jnp.sum(ex.mask.astype(jnp.int32))
        cap = ex.padded_len
        db = F.compact(ex.batch, ex.mask, cap)
        return from_device(db, ex.dicts, schema=dict(schema))

    # --------------------------------------------------------------- ddl
    def _create_table(self, stmt: ast.CreateTable) -> Result:
        schema = [(c.name, type_from_name(c.type_name, c.type_args))
                  for c in stmt.columns]
        auto = [c.name for c in stmt.columns if c.auto_increment]
        if len(auto) > 1:
            raise BindError("only one AUTO_INCREMENT column allowed")
        not_null = [c.name for c in stmt.columns if c.not_null]
        part = None
        if stmt.partition_by is not None:
            from matrixone_tpu.storage.partition import build_spec
            part = build_spec(stmt.partition_by, schema)
        self.catalog.create_table(
            TableMeta(stmt.name, schema, stmt.primary_key,
                      auto_increment=auto[0] if auto else None,
                      not_null=not_null, partition=part),
            if_not_exists=stmt.if_not_exists)
        return Result()

    def _derived_table_schema(self, sel, what: str) -> list:
        """Bind a stored SELECT and derive its backing-table schema
        (alias qualifiers stripped, names validated) — ONE validator
        shared by dynamic tables and materialized views so the two
        surfaces cannot drift."""
        import re
        self._prepare_select(sel)
        node = Binder(self.catalog).bind_statement(sel)
        schema = [(n.split(".")[-1], d) for n, d in node.schema]
        if len({c for c, _ in schema}) != len(schema):
            raise BindError(f"{what} SELECT has duplicate output names")
        for c, _ in schema:
            if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", c):
                raise BindError(
                    f"{what} output {c!r} is not a valid column "
                    f"name; alias the expression (AS name)")
        return schema

    def _create_dynamic_table(self, stmt: ast.CreateDynamicTable) -> Result:
        """CREATE DYNAMIC TABLE name AS SELECT ... — materialize once now,
        store the defining SELECT for REFRESH (reference: stream dynamic
        tables driven by the task framework)."""
        from matrixone_tpu.stream import refresh_dynamic_table
        schema = self._derived_table_schema(stmt.select, "dynamic table")
        self.catalog.create_table(TableMeta(stmt.name, schema, []))
        self.catalog.register_dynamic(stmt.name, stmt.sql_text)
        try:
            n = refresh_dynamic_table(self, stmt.name)
        except Exception:  # noqa: BLE001 — compensating drop, re-raised
            # no orphan catalog/WAL state from a failed CREATE: the
            # drop is WAL-logged too, so replay converges to "absent"
            self.catalog.drop_table(stmt.name, if_exists=True)
            raise
        return Result(affected=n)

    # ------------------------------------------------- materialized views
    def _create_materialized_view(self,
                                  stmt: ast.CreateMaterializedView
                                  ) -> Result:
        """CREATE MATERIALIZED VIEW: backing table + one system_mview
        catalog row (riding the ordinary commit+logtail funnels for
        durability/restart/replication).  Maintainable shapes run
        incremental — the catalog row's own post-commit hook initializes
        the state and first materialization; everything else
        materializes once here and refreshes fully on demand."""
        import copy
        import time as _time
        from matrixone_tpu import mview as MV
        from matrixone_tpu.mview import catalog as vcat
        if self.txn is not None:
            raise BindError(
                "CREATE MATERIALIZED VIEW inside an explicit "
                "transaction is not supported (view DDL is autocommit)")
        # maintainability first, on a pristine copy (bind errors for
        # genuinely broken SQL surface from the schema bind below)
        spec, why = None, "tenant sessions run full refresh"
        host = getattr(self.catalog, "_inner", self.catalog)
        if (self.auth is None or self.auth.account == "sys") \
                and hasattr(host, "commit_txn"):
            try:
                spec, why = MV.analyze(copy.deepcopy(stmt.select),
                                       self.catalog)
            except BindError:
                spec = None        # real bind errors re-raise below
        schema = self._derived_table_schema(stmt.select,
                                            "materialized view")
        if vcat.lookup(self.catalog, stmt.name) is not None:
            raise BindError(
                f"materialized view {stmt.name!r} already exists")
        self.catalog.create_table(TableMeta(stmt.name, schema, []))
        vcat.ensure_table(self.catalog)
        d = vcat.MViewDef(
            name=stmt.name.lower(), sql=stmt.sql_text,
            mode="incremental" if spec is not None else "full",
            source=spec.source if spec is not None else "")
        t = self.catalog.get_table(vcat.MVIEW_TABLE)
        batch = vcat.row_batch(d, _time.time_ns() // 1000)
        arrays, validity = t.batch_to_arrays(batch)
        txn = self.txn_client.begin()
        try:
            txn.write_batch(vcat.MVIEW_TABLE, arrays, validity)
            # the commit's post-commit hook syncs the maintenance
            # service, which initializes incremental state + the first
            # materialization before this returns
            txn.commit()
        except BaseException:  # noqa: BLE001 — compensate, re-raise
            txn.rollback()
            self.catalog.drop_table(stmt.name, if_exists=True)
            raise
        if spec is None:
            from matrixone_tpu.stream import rematerialize
            try:
                n = rematerialize(self, stmt.name, stmt.sql_text)
            except Exception:  # noqa: BLE001 — compensating drop, then
                # re-raised: a failed CREATE leaves no orphan state
                self._drop_mview_row(stmt.name)
                self.catalog.drop_table(stmt.name, if_exists=True)
                raise
        else:
            # the post-commit hook swallows maintenance errors (it must
            # never fail an unrelated writer's commit) — but THIS
            # statement's own init failure must surface, not report a
            # registered-yet-permanently-empty view.  Only checkable
            # where the maintaining engine is local; on a CN the TN
            # initializes asynchronously.
            if isinstance(host, Engine):
                svc = getattr(host, "_mview_service", None)
                rt = svc.runtime(d.name) if svc is not None else None
                if rt is None or rt.watermark is None:
                    self._drop_mview_row(stmt.name)
                    self.catalog.drop_table(stmt.name, if_exists=True)
                    raise BindError(
                        f"materialized view {stmt.name!r} failed to "
                        f"initialize (see mo_ctl('mview','status'))")
            n = self.catalog.get_table(stmt.name).n_rows
        return Result(affected=n)

    def _drop_mview_row(self, name: str) -> None:
        from matrixone_tpu.mview import catalog as vcat
        gids = vcat.gids_for_name(self.catalog, name)
        if not len(gids):
            return
        txn = self.txn_client.begin()
        try:
            txn.delete_rows(vcat.MVIEW_TABLE, gids)
            txn.commit()
        except BaseException:  # noqa: BLE001 — rollback, re-raised
            txn.rollback()
            raise

    def _drop_materialized_view(self, stmt: ast.DropMaterializedView
                                ) -> Result:
        from matrixone_tpu.mview import catalog as vcat
        d = vcat.lookup(self.catalog, stmt.name)
        if d is None:
            if stmt.if_exists:
                return Result()
            raise BindError(f"no such materialized view {stmt.name!r}")
        # catalog row first: its commit's hook detaches the maintainer
        # BEFORE the backing table disappears under it
        self._drop_mview_row(stmt.name)
        self.catalog.drop_table(stmt.name, if_exists=True)
        return Result()

    def _show_materialized_views(self) -> Result:
        from matrixone_tpu.mview import catalog as vcat
        reg = vcat.registry_for(self.catalog)
        host = getattr(self.catalog, "_inner", self.catalog)
        svc = getattr(host, "_mview_service", None)
        names = sorted(reg)
        wms, rows = [], []
        for n in names:
            rt = svc.runtime(n) if svc is not None else None
            wms.append(rt.watermark if rt is not None else None)
            try:
                rows.append(self.catalog.get_table(n).n_rows)
            except Exception:  # noqa: BLE001 — backing table dropped
                rows.append(None)
        b = Batch.from_pydict(
            {"Name": names,
             "Mode": [reg[n].mode for n in names],
             "Source": [reg[n].source or None for n in names],
             "Watermark": wms,
             "Rows": rows,
             "Definition": [reg[n].sql for n in names]},
            {"Name": dt.VARCHAR, "Mode": dt.VARCHAR,
             "Source": dt.VARCHAR, "Watermark": dt.INT64,
             "Rows": dt.INT64, "Definition": dt.TEXT})
        return Result(batch=b)

    def _refresh_mview(self, name: str) -> int:
        """REFRESH MATERIALIZED VIEW: incremental views are maintained
        continuously (refresh just reports); full views rematerialize."""
        from matrixone_tpu.mview import catalog as vcat
        d = vcat.lookup(self.catalog, name)
        if d is None:
            raise BindError(f"no such materialized view {name!r}")
        if d.mode == "incremental":
            return self.catalog.get_table(name).n_rows
        from matrixone_tpu.stream import rematerialize
        return rematerialize(self, name, d.sql)

    def _reject_mview_write(self, table: str) -> None:
        """Direct DML against a materialized view would be clobbered by
        the next maintenance/refresh — reject it cleanly.  (Maintenance
        itself writes through engine.commit_txn, never a session.)"""
        if getattr(self, "_mview_refresh", 0):
            return            # the refresh machinery's own writes
        from matrixone_tpu.mview import catalog as vcat
        if vcat.lookup(self.catalog, table) is not None:
            raise BindError(
                f"{table!r} is a materialized view; it is maintained "
                f"from its source — write to the source table instead")

    def _mview_annotator(self):
        """EXPLAIN decoration: mark scans of materialized-view backing
        tables with their maintenance mode."""
        from matrixone_tpu.mview import catalog as vcat
        reg = vcat.registry_for(self.catalog)
        if not reg:
            return None

        def ann(n):
            t = getattr(n, "table", None)
            if isinstance(n, P.Scan) and isinstance(t, str) \
                    and t.lower() in reg:
                return f" mview={reg[t.lower()].mode}"
            return ""
        return ann

    # --------------------------------------------------------------- udf
    def _create_function(self, stmt: ast.CreateFunction) -> Result:
        """CREATE [OR REPLACE] FUNCTION: validate + trial-compile the
        body, then persist one row in the system_udf catalog table via
        the ordinary commit pipeline — durability, restart replay, and
        CN replication all ride the existing funnels (udf/catalog.py)."""
        import time as _time
        from matrixone_tpu import udf as U
        from matrixone_tpu.udf import catalog as ucat
        if self.txn is not None:
            raise BindError(
                "CREATE FUNCTION inside an explicit transaction is not "
                "supported (function DDL is autocommit)")
        props = {str(k).lower(): str(v).lower()
                 for k, v in stmt.properties.items()}
        for k in props:
            if k not in ("deterministic", "vectorized"):
                raise BindError(f"unknown function property {k!r}; "
                                f"use 'deterministic' | 'vectorized'")
        meta = U.UdfMeta(
            name=stmt.name.lower(),
            kind="aggregate" if stmt.aggregate else "scalar",
            arg_names=[a for a, _t, _ta in stmt.args],
            arg_types=[type_from_name(t, ta) for _a, t, ta in stmt.args],
            ret_type=type_from_name(stmt.ret_type, stmt.ret_args),
            language=stmt.language, body=stmt.body,
            deterministic=props.get("deterministic", "true") != "false",
            vectorized=props.get("vectorized", "true") != "false")
        try:
            U.validate_meta(meta)
        except U.UdfError as e:
            raise BindError(str(e))
        ucat.ensure_table(self.catalog)
        existing = ucat.registry_for(self.catalog)
        if meta.name in existing and not stmt.or_replace:
            raise BindError(f"function {meta.name!r} already exists "
                            f"(use CREATE OR REPLACE FUNCTION)")
        t = self.catalog.get_table(ucat.UDF_TABLE)
        batch = ucat.row_batch(meta, _time.time_ns() // 1000)
        arrays, validity = t.batch_to_arrays(batch)
        txn = self.txn_client.begin()
        try:
            if meta.name in existing:
                # OR REPLACE: delete + insert commit atomically
                txn.delete_rows(ucat.UDF_TABLE, ucat.gids_for_name(
                    self.catalog, meta.name))
            txn.write_batch(ucat.UDF_TABLE, arrays, validity)
            txn.commit()
        except BaseException:   # noqa: BLE001 — rollback, then re-raised
            txn.rollback()
            raise
        return Result()

    def _drop_function(self, stmt: ast.DropFunction) -> Result:
        from matrixone_tpu.udf import catalog as ucat
        if self.txn is not None:
            raise BindError(
                "DROP FUNCTION inside an explicit transaction is not "
                "supported (function DDL is autocommit)")
        u = ucat.registry_for(self.catalog).get(stmt.name.lower())
        if u is None:
            if stmt.if_exists:
                return Result()
            raise BindError(f"no such function {stmt.name!r}")
        gids = ucat.gids_for_name(self.catalog, stmt.name)
        txn = self.txn_client.begin()
        try:
            txn.delete_rows(ucat.UDF_TABLE, gids)
            txn.commit()
        except BaseException:   # noqa: BLE001 — rollback, then re-raised
            txn.rollback()
            raise
        return Result(affected=len(gids))

    def _show_functions(self) -> Result:
        from matrixone_tpu.udf import catalog as ucat
        reg = ucat.registry_for(self.catalog)
        names = sorted(reg)
        b = Batch.from_pydict(
            {"Function": names,
             "Kind": [reg[n].kind for n in names],
             "Signature": [reg[n].signature() for n in names],
             "Language": [reg[n].language for n in names],
             "Deterministic": [int(reg[n].deterministic) for n in names],
             "Vectorized": [int(reg[n].vectorized) for n in names]},
            {"Function": dt.VARCHAR, "Kind": dt.VARCHAR,
             "Signature": dt.TEXT, "Language": dt.VARCHAR,
             "Deterministic": dt.INT64, "Vectorized": dt.INT64})
        return Result(batch=b)

    def _alter_partition(self, stmt: ast.AlterPartition) -> Result:
        """TRUNCATE/DROP PARTITION (partitionservice management ops):
        rows leave via an ordinary tombstone commit, so MVCC snapshots
        and time travel keep seeing the pre-truncate state."""
        import numpy as np
        t = self.catalog.get_table(stmt.table)
        spec = t.meta.partition
        if spec is None:
            raise BindError(f"table {stmt.table!r} is not partitioned")
        if stmt.part not in spec.names:
            raise BindError(f"no partition {stmt.part!r} on {stmt.table!r}")
        if stmt.action == "drop":
            # validate BEFORE the tombstone commit: a refused DROP must
            # not have already destroyed the partition's rows
            if spec.kind != "range":
                raise BindError("DROP PARTITION requires RANGE partitioning")
            if len(spec.names) == 1:
                raise BindError("cannot drop the last partition")
        pid = spec.names.index(stmt.part)
        dead = t._dead_gids(None, None)
        gids = []
        for seg in t.segments:
            if seg.part_id != pid:
                continue
            g = np.arange(seg.base_gid, seg.base_gid + seg.n_rows,
                          dtype=np.int64)
            if len(dead):
                g = g[~np.isin(g, dead)]
            gids.append(g)
        all_gids = (np.concatenate(gids) if gids
                    else np.zeros(0, np.int64))
        if len(all_gids):
            self.catalog.commit_txn(None, {}, {stmt.table: all_gids})
        if stmt.action == "drop":
            self.catalog.alter_partition_drop(stmt.table, stmt.part)
        b = Batch.from_pydict(
            {"partition": [stmt.part], "rows_removed": [len(all_gids)]},
            {"partition": dt.VARCHAR, "rows_removed": dt.INT64})
        return Result(batch=b)

    def _show_partitions(self, stmt: ast.ShowPartitions) -> Result:
        import numpy as np
        t = self.catalog.get_table(stmt.name)
        spec = t.meta.partition
        if spec is None:
            raise BindError(f"table {stmt.name!r} is not partitioned")
        dead = t._dead_gids(None, None)
        rows = {i: 0 for i in range(spec.n_parts)}
        for seg in t.segments:
            if seg.part_id < 0:
                continue
            alive = seg.n_rows
            if len(dead):
                g = np.arange(seg.base_gid, seg.base_gid + seg.n_rows,
                              dtype=np.int64)
                alive = int((~np.isin(g, dead)).sum())
            rows[seg.part_id] = rows.get(seg.part_id, 0) + alive
        bounds = [("MAXVALUE" if b is None else str(b))
                  for b in spec.bounds] if spec.kind == "range" \
            else [""] * spec.n_parts
        b = Batch.from_pydict(
            {"partition": list(spec.names),
             "method": [spec.kind] * spec.n_parts,
             "expr": [spec.column] * spec.n_parts,
             "bound": bounds,
             "rows": [rows[i] for i in range(spec.n_parts)]},
            {"partition": dt.VARCHAR, "method": dt.VARCHAR,
             "expr": dt.VARCHAR, "bound": dt.VARCHAR, "rows": dt.INT64})
        return Result(batch=b)

    def _create_index(self, stmt: ast.CreateIndex) -> Result:
        table = self.catalog.get_table(stmt.table)
        algo = (stmt.using or "").lower()
        if algo in ("ivfflat", "ivf_flat", "ivfpq", "ivf_pq", "hnsw"):
            col = stmt.columns[0]
            coltype = dict(table.meta.schema)[col]
            if not coltype.is_vector:
                raise BindError(f"{algo} index requires a vecf32 column")
            from matrixone_tpu import indexing
            op_type = stmt.options.get("op_type", "vector_l2_ops")
            metric = {"vector_l2_ops": "l2", "vector_cosine_ops": "cosine",
                      "vector_ip_ops": "ip"}.get(op_type, "l2")
            algo_name = ("hnsw" if algo == "hnsw"
                         else "ivfpq" if "pq" in algo else "ivfflat")
            if algo_name == "ivfpq" and metric == "ip":
                raise BindError(
                    "ivfpq does not support vector_ip_ops; use ivfflat")
            build_fn = (indexing.build_hnsw if algo_name == "hnsw"
                        else indexing.build_ivfflat)
            meta = IndexMeta(stmt.name, stmt.table, stmt.columns, algo_name,
                             dict(stmt.options), dirty=True)
            meta.options["_metric"] = metric
            try:
                build_fn(self.catalog, meta)
            except ValueError as e:
                raise BindError(str(e))
            self.catalog.register_index(meta)
            indexing.register_in_cache(self.catalog, meta)
            return Result()
        if algo == "fulltext":
            from matrixone_tpu import indexing
            for col in stmt.columns:
                if not dict(table.meta.schema)[col].is_varlen:
                    raise BindError(
                        f"fulltext index requires text columns ({col})")
            meta = IndexMeta(stmt.name, stmt.table, stmt.columns,
                             "fulltext", dict(stmt.options), dirty=True)
            indexing.build_fulltext(self.catalog, meta)
            self.catalog.register_index(meta)
            indexing.register_in_cache(self.catalog, meta)
            return Result()
        raise BindError(f"unsupported index algo {stmt.using!r}")

    # --------------------------------------------------------------- etl
    def load_csv(self, table: str, path: str, **read_kwargs) -> int:
        """Bulk CSV load (reference: colexec/external CSV reader) via
        pyarrow.csv into the table's schema."""
        import pyarrow.csv as pacsv
        return self._ingest_arrow(table, pacsv.read_csv(path, **read_kwargs))

    def load_parquet(self, table: str, path: str) -> int:
        """Bulk parquet load (reference: colexec/external parquet path)."""
        import pyarrow.parquet as papq
        return self._ingest_arrow(table, papq.read_table(path))

    def _load_data(self, stmt: ast.LoadData) -> Result:
        """LOAD DATA INFILE: path may be local / file:// / fs:// /
        stage:// — resolved through the stage registry + fileservice."""
        import pyarrow.csv as pacsv
        import pyarrow.parquet as papq
        from matrixone_tpu.storage.external import open_location
        self._reject_mview_write(stmt.table)
        fmt = _resolve_format(stmt.fmt, stmt.path)
        if fmt == "iceberg":
            raise BindError(
                "LOAD DATA does not support FORMAT iceberg; create an "
                "external table over it and INSERT ... SELECT instead")
        src = open_location(self.catalog, stmt.path)
        tbl = (papq.read_table(src) if fmt == "parquet"
               else pacsv.read_csv(src))
        n = self._ingest_arrow(stmt.table, tbl)
        return Result(affected=n)

    def _ingest_arrow(self, table: str, tbl) -> int:
        t = self.catalog.get_table(table)
        auto_col = t.meta.auto_increment
        required = [c for c, _ in t.meta.schema if c != auto_col]
        missing = [c for c in required if c not in tbl.schema.names]
        if missing:
            raise BindError(
                f"load into {table!r}: file is missing columns {missing}; "
                f"file has {tbl.schema.names}")
        # extra CSV columns are ignored; the auto_increment column may be
        # absent (values are allocated) or present (counter advances past)
        want = [c for c, _ in t.meta.schema if c in tbl.schema.names]
        from matrixone_tpu.container.batch import Batch as _B
        total = 0
        schema_map = dict(t.meta.schema)
        # every chunk buffers in a txn workspace — explicit txn or a
        # statement-scoped one — so a KILL (or any error) mid-file
        # discards the WHOLE statement; chunk-at-a-time autocommit would
        # leave a killed LOAD half-applied (MySQL rolls the statement back)
        txn = self.txn or self.txn_client.begin()
        try:
            for rb in tbl.select(want).to_batches(max_chunksize=1 << 20):
                # KILL cancels long LOAD DATA between chunks (MySQL KILL
                # QUERY semantics; same preemption contract as _select)
                self._procs.check_killed(self.conn_id)
                batch = _B.from_arrow(rb, schema=schema_map)
                if auto_col is not None:
                    if auto_col in batch.columns:
                        t.observe_auto(np.asarray(
                            batch.columns[auto_col].data, np.int64))
                    else:
                        n = len(batch)
                        from matrixone_tpu.container.vector import Vector
                        batch.columns[auto_col] = Vector.from_values(
                            [int(v) for v in t.allocate_auto(n)],
                            schema_map[auto_col])
                arrays, validity = t.batch_to_arrays(batch)
                total += txn.write_batch(table, arrays, validity)
            if self.txn is None:
                txn.commit()
        except BaseException:  # noqa: BLE001 — rollback, then re-raised
            if self.txn is None:
                txn.rollback()
            raise
        return total

    # --------------------------------------------------------------- dml
    def _pessimistic(self, txn) -> bool:
        return (self.txn is not None
                and self.variables.get("txn_mode") == "pessimistic")

    def _maybe_lock(self, txn, table: str, gids) -> None:
        """Pessimistic mode (reference: colexec/lockop + lockservice.Lock):
        DML takes exclusive row locks before buffering the write; released
        at commit/rollback. `set txn_mode = 'pessimistic'` arms it. A
        deadlock victim is auto-rolled-back (InnoDB/reference behavior) so
        its locks release immediately and the survivor proceeds."""
        if not self._pessimistic(txn):
            return     # autocommit DML serializes through the commit lock
        from matrixone_tpu.lockservice import DeadlockError
        committed = np.asarray(gids)[np.asarray(gids) >= 0]
        if len(committed):
            timeout = float(self.variables.get("lock_timeout", 10.0))
            try:
                self.catalog.locks.lock(txn.txn_id, table, committed,
                                        timeout=timeout)
            except DeadlockError:
                if self.txn is txn:
                    txn.rollback()
                    self.txn = None
                raise

    def _dml_read_ctx(self, txn) -> ExecContext:
        """Row-planning context for DML. Pessimistic txns plan against the
        CURRENT frontier (MySQL 'current read'): after the lock wait, the
        statement must see the rows the lock winner left behind, not its
        own stale snapshot — otherwise the wait ends in a write-write
        conflict anyway."""
        import types
        if self._pessimistic(txn):
            cur = types.SimpleNamespace(
                snapshot_ts=self.catalog.committed_ts,
                workspace=txn.workspace)
            return ExecContext(catalog=self.catalog, txn=cur,
                               variables=self.variables)
        return ExecContext(catalog=self.catalog, txn=txn,
                           variables=self.variables)

    def _plan_and_lock_rows(self, txn, table: str, run_plan):
        """run_plan(ctx) -> (gids, payload). In pessimistic mode: plan at
        the frontier, lock, re-plan (the frontier may have advanced while
        we waited) until the row set stabilizes."""
        result = run_plan(self._dml_read_ctx(txn))
        if not self._pessimistic(txn):
            return result
        for _ in range(5):
            self._maybe_lock(txn, table, result[0])
            again = run_plan(self._dml_read_ctx(txn))
            if set(np.asarray(again[0]).tolist()) == \
                    set(np.asarray(result[0]).tolist()):
                return again
            result = again
        return result

    def _dml_plan(self, table_name: str, where, extra_exprs=None,
                  extra_names=None):
        """Plan `SELECT __rowid [, extra...] FROM t WHERE ...` for DML."""
        from matrixone_tpu.sql.binder import Scope
        from matrixone_tpu.sql.expr import BoundCol
        table = self.catalog.get_table(table_name)
        scope = Scope()
        for col, dtype in table.meta.schema:
            scope.add(table_name, col, dtype)
        binder = Binder(self.catalog)
        scan_cols = [c for c, _ in table.meta.schema] + [ROWID]
        scan_schema = [(f"{table_name}.{c}", d)
                       for c, d in table.meta.schema] + [(ROWID, dt.INT64)]
        node = P.Scan(table_name, scan_cols, scan_schema)
        if where is not None:
            pred = binder.bind_expr(where, scope)
            node = P.Filter(node, pred, node.schema)
        exprs = [BoundCol(ROWID, dt.INT64)]
        names = [ROWID]
        out_types = [dt.INT64]
        for e, nm in zip(extra_exprs or [], extra_names or []):
            b = binder.bind_expr(e, scope) if not hasattr(e, "dtype") else e
            exprs.append(b)
            names.append(nm)
            out_types.append(b.dtype)
        proj = P.Project(node, exprs, list(zip(names, out_types)))
        return proj, binder, scope

    def _delete(self, stmt: ast.Delete) -> Result:
        self._reject_mview_write(stmt.table)
        txn = self.txn or self.txn_client.begin()
        proj, _, _ = self._dml_plan(stmt.table, stmt.where)

        def run_plan(ctx):
            op = compile_plan(proj, ctx)
            gids = []
            for ex in op.execute():
                self._procs.check_killed(self.conn_id)   # KILL during DML
                b = self._to_host(ex, proj.schema)
                gids.extend(b.columns[ROWID].data.tolist())
            return np.asarray(gids, np.int64), None

        gids, _ = self._plan_and_lock_rows(txn, stmt.table, run_plan)
        txn.delete_rows(stmt.table, gids)
        if self.txn is None:
            txn.commit()
        return Result(affected=len(gids))

    def _update(self, stmt: ast.Update) -> Result:
        self._reject_mview_write(stmt.table)
        txn = self.txn or self.txn_client.begin()
        table = self.catalog.get_table(stmt.table)
        schema = table.meta.schema
        assigned = dict(stmt.assignments)
        extra_exprs, extra_names = [], []
        for col, dtype in schema:
            e = assigned.get(col, ast.ColumnRef(col, stmt.table))
            extra_exprs.append(e)
            extra_names.append(col)
        proj, _, _ = self._dml_plan(stmt.table, stmt.where,
                                    extra_exprs, extra_names)

        def run_plan(ctx):
            op = compile_plan(proj, ctx)
            gids, new_cols = [], {c: [] for c, _ in schema}
            for ex in op.execute():
                self._procs.check_killed(self.conn_id)   # KILL during DML
                b = self._to_host(ex, proj.schema)
                gids.extend(b.columns[ROWID].data.tolist())
                for c, _ in schema:
                    new_cols[c].extend(b.columns[c].to_pylist())
            return np.asarray(gids, np.int64), new_cols

        gids, new_cols = self._plan_and_lock_rows(txn, stmt.table, run_plan)
        if len(gids) == 0:
            return Result(affected=0)
        # rows must round-trip through the table's SQL types (e.g. the
        # assignment may produce float for a decimal column)
        batch = Batch.from_pydict(new_cols, {c: d for c, d in schema})
        arrays, validity = table.batch_to_arrays(batch)
        txn.delete_rows(stmt.table, gids)
        txn.write_batch(stmt.table, arrays, validity)
        if self.txn is None:
            txn.commit()
        return Result(affected=len(gids))

    def _insert(self, stmt: ast.Insert) -> Result:
        self._reject_mview_write(stmt.table)
        table = self.catalog.get_table(stmt.table)
        schema = table.meta.schema
        cols = stmt.columns or [c for c, _ in schema]
        if stmt.select is not None:
            sub = self._select(stmt.select)
            data = {c: sub.batch.columns[n].to_pylist()
                    for c, n in zip(cols, sub.column_names)}
        else:
            data = {c: [] for c in cols}
            for row in stmt.rows:
                if len(row) != len(cols):
                    raise BindError("INSERT arity mismatch")
                for c, v in zip(cols, row):
                    data[c].append(_literal_value(v))
        full = {}
        n = len(next(iter(data.values()))) if data else 0
        auto_col = table.meta.auto_increment
        for c, d in schema:
            vals = data.get(c, [None] * n)
            if c == auto_col:
                # row order matters: an explicit value advances the counter
                # for subsequent NULLs in the same statement (MySQL behavior)
                vals = list(vals)
                for i, v in enumerate(vals):
                    if v is None:
                        vals[i] = int(table.allocate_auto(1)[0])
                        # MySQL last_insert_id(): FIRST generated id
                        # of the statement
                        if not getattr(self, "_liid_set", False):
                            self.last_insert_id = vals[i]
                            self._liid_set = True
                    else:
                        table.observe_auto(np.asarray([v], np.int64))
            if d.oid == TypeOid.DATE:
                vals = [dt.epoch_days_from_iso(v)
                        if isinstance(v, str) else v for v in vals]
            elif d.oid in (TypeOid.DATETIME, TypeOid.TIMESTAMP):
                vals = [dt.epoch_micros_from_iso(v)
                        if isinstance(v, str) else v for v in vals]
            elif d.is_vector:
                vals = [[float(x) for x in v.strip()[1:-1].split(",")]
                        if isinstance(v, str) else v for v in vals]
                for v in vals:
                    if v is not None and len(v) != d.dim:
                        raise BindError(
                            f"vector literal has {len(v)} dimensions, "
                            f"column {c!r} expects {d.dim}")
            full[c] = vals
        batch = Batch.from_pydict(full, {c: d for c, d in schema})
        if self.txn is not None:
            arrays, validity = table.batch_to_arrays(batch)
            n = self.txn.write_batch(stmt.table, arrays, validity)
        else:
            n = table.insert_batch(batch)
        return Result(affected=n)


class _ServingCtx:
    """Per-execution serving context: one normalized statement routed
    through the plan/result caches (matrixone_tpu/serving).

    Two operating modes: `template_mode` (template activated — plan
    cache participates, parameter literals are tagged) and raw mode
    (first occurrence of a template — only the result cache
    participates, the statement executes through the ordinary parse
    path at zero added cost)."""

    def __init__(self, state, norm, full_params, scope: str):
        self.state = state
        self.norm = norm
        self.full = full_params
        self.scope = scope
        self.template_mode = False
        self._pristine = None      # cached template AST (never mutated)
        self._usable = None        # lazily computed on the template AST

    def make_stmts(self):
        """-> [stmt] from the cached template AST, or None (raw path).
        SELECT/UNION return the PRISTINE template — `_select`
        instantiates lazily, so a plan-cache hit never pays the AST
        deepcopy; other statement kinds instantiate eagerly (their
        executors mutate the AST)."""
        tpl = self.state.plan_cache.template_ast(self.norm.template)
        if tpl is None:
            return None
        # every `?` must surface as an ast.Param: a parser that absorbs
        # one as raw text (e.g. index option values) would execute with
        # a literal '?' — structurally-consumed params mean the template
        # is unusable, not just uncacheable
        if _param_indexes(tpl) != set(range(len(self.full))):
            return None
        self.template_mode = True
        self._pristine = tpl
        if isinstance(tpl, (ast.Select, ast.Union)):
            return [tpl]
        st = self.instantiate()
        return None if st is None else [st]

    def owns_pristine(self, stmt) -> bool:
        return self._pristine is not None and stmt is self._pristine

    def instantiate(self, raise_errors: bool = False):
        """Fresh substituted copy of the template.  Bind-time parameter
        errors raise when `raise_errors` (callers already committed to
        the template path), else return None (the raw path reports
        them properly)."""
        import copy as _copy
        st = _copy.deepcopy(self._pristine)
        try:
            return _substitute_params(st, self.full)
        except BindError:
            if raise_errors:
                raise
            return None

    def usable_for(self, sel) -> bool:
        """Caches are only safe for statements whose execution is fully
        visible in the final plan: uncorrelated subqueries / EXISTS
        execute at prepare time and fold to constants (their tables
        would escape the version key), and @@sysvars read session state."""
        if self._usable is None:
            self._usable = not _ast_has(
                sel, (ast.Subquery, ast.Exists, ast.SysVar))
        return self._usable

    def result_enabled(self) -> bool:
        return self.state.result_cache.enabled

    def plan_enabled(self) -> bool:
        return self.state.plan_cache.enabled

    def _vars_key(self, variables=None):
        s = current_session()
        v = s.variables if s is not None else {}
        return (str(v.get("cbo", 1)), int(v.get("ivf_nprobe", 8) or 8),
                int(v.get("ivf_shards", 0) or 0),
                int(v.get("query_shards", 0) or 0))

    def plan_key(self) -> tuple:
        return ("plan", self.scope, self.norm.template,
                self.norm.sig_for(self.full), self._vars_key())

    def result_key(self) -> tuple:
        # the sig guards numerically-equal params of different types:
        # tuple((1,)) == tuple((1.0,)) but INT64 and decimal results differ
        return ("result", self.scope, self.norm.template,
                self.norm.sig_for(self.full), tuple(self.full),
                self._vars_key())


def _param_indexes(node) -> set:
    """All ast.Param indexes reachable in a statement."""
    from matrixone_tpu.serving.plan_cache import iter_plan_values
    return {x.index for x in iter_plan_values(node)
            if isinstance(x, ast.Param)}


def _ast_has(node, kinds) -> bool:
    """Does any reachable node match `kinds`?"""
    from matrixone_tpu.serving.plan_cache import iter_plan_values
    return any(isinstance(x, kinds) for x in iter_plan_values(node))


def _plan_tables(node) -> set:
    """Base tables a plan reads (SELECT privilege targets)."""
    out = set()
    t = getattr(node, "table", None)
    if isinstance(t, str):
        out.add(t)
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if c is not None:
            out |= _plan_tables(c)
    for c in getattr(node, "children", []) or []:
        out |= _plan_tables(c)
    return out


def _param_literal(v) -> ast.Node:
    if v is None:
        return ast.Literal(None, "null")
    if isinstance(v, bool):
        return ast.Literal(v, "bool")
    if isinstance(v, int):
        return ast.Literal(v, "int")
    if isinstance(v, float):
        return ast.Literal(repr(v), "float")
    if isinstance(v, str):
        return ast.Literal(v, "str")
    if isinstance(v, datetime.date):
        return ast.DateLiteral((v - datetime.date(1970, 1, 1)).days)
    raise BindError(f"unsupported parameter type {type(v).__name__}")


def _resolve_format(fmt: str, location: str) -> str:
    """Shared LOAD/EXTERNAL format defaulting + validation (one place so
    the two DDL paths cannot drift; always a BindError on bad input)."""
    if not fmt:
        fmt = "parquet" if location.endswith(".parquet") else "csv"
    if fmt not in ("csv", "parquet", "iceberg"):
        raise BindError(f"unsupported external format {fmt!r}")
    return fmt


def _substitute_params(node, params: list):
    """Replace ? placeholders (ast.Param) with literal values."""
    import dataclasses as dc
    if isinstance(node, ast.Param):
        if node.index >= len(params):
            raise BindError(f"missing value for parameter {node.index + 1}")
        lit = _param_literal(params[node.index])
        # serving plan cache: remember which parameter produced this
        # literal so a cached plan can be re-parameterized (the tag
        # survives into BoundLiteral via binder._bind_literal)
        lit._param_idx = node.index
        return lit
    if dc.is_dataclass(node) and isinstance(node, ast.Node):
        def sub(x):
            if isinstance(x, ast.Node):
                return _substitute_params(x, params)
            if isinstance(x, tuple):
                return tuple(sub(y) for y in x)
            if isinstance(x, list):
                return [sub(y) for y in x]
            return x
        for f in dc.fields(node):
            setattr(node, f.name, sub(getattr(node, f.name)))
    return node


def _literal_value(v: ast.Node):
    if isinstance(v, ast.Literal):
        if v.kind == "float":
            return float(v.value)
        return v.value
    if isinstance(v, ast.DateLiteral):
        return v.days
    if isinstance(v, ast.UnaryOp) and v.op == "-":
        inner = _literal_value(v.operand)
        return -inner
    if isinstance(v, ast.Cast):
        return _literal_value(v.expr)
    raise BindError("INSERT VALUES must be literals")

"""Full-text search: inverted index + BM25 scoring on device.

Reference analogue: `pkg/fulltext` (inverted index tables, TF-IDF/BM25
ranking, fulltext.go:215-222) + `pkg/monlp` tokenizers. Redesign:

 * tokenize host-side (unicode word splitting + CJK character bigrams —
   the jieba cgo dictionary tokenizer's role, monlp/tokenizer/jieba.go);
 * the inverted index lives on device as CSR postings:
   term -> (doc_idx[], tf[]) contiguous slices, plus doc_len / idf arrays;
 * a query scores by scatter-adding each term's BM25 contribution into a
   dense [n_docs] score vector (jax segment ops) and taking top-k — the
   TPU-native form of the reference's per-doc accumulator maps.
"""

from __future__ import annotations

import dataclasses
import re
import unicodedata
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_WORD_RE = re.compile(r"[A-Za-z0-9_]+")
_CJK_RUN_RE = re.compile(r"[\u3040-\u30FF\u3400-\u9FFF]+")


def tokenize(text: str) -> List[str]:
    """Lowercased word tokens; each CONTIGUOUS CJK run is segmented by
    the dictionary-driven monlp segmenter (reference: pkg/monlp jieba
    tokenizer), with character bigrams as the out-of-vocabulary
    fallback so unknown text stays searchable."""
    from matrixone_tpu import monlp
    out: List[str] = []
    if not text:
        return out
    for m in _WORD_RE.finditer(text):
        out.append(m.group(0).lower())
    for m in _CJK_RUN_RE.finditer(text):
        out.extend(monlp.tokenize_cjk_run(m.group(0)))
    return out


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FulltextIndex:
    """Device-resident BM25 index (pytree, persistent like IvfFlatIndex)."""

    doc_idx: jnp.ndarray      # [nnz] int32: document position per posting
    tf: jnp.ndarray           # [nnz] f32: term frequency per posting
    term_offsets: jnp.ndarray  # [V+1] int32 CSR into doc_idx/tf
    idf: jnp.ndarray          # [V] f32
    doc_norm: jnp.ndarray     # [n_docs] f32: k1*(1-b+b*len/avgdl)
    # static / host:
    vocab: dict = dataclasses.field(default_factory=dict)
    n_docs: int = 0
    max_postings: int = 0     # longest postings list (padded gather budget)
    k1: float = 1.2
    b: float = 0.75

    def tree_flatten(self):
        return ((self.doc_idx, self.tf, self.term_offsets, self.idf,
                 self.doc_norm),
                (self.n_docs, self.max_postings, self.k1, self.b))

    @classmethod
    def tree_unflatten(cls, aux, children):
        di, tf, to, idf, dn = children
        n, mp, k1, b = aux
        return cls(doc_idx=di, tf=tf, term_offsets=to, idf=idf, doc_norm=dn,
                   vocab={}, n_docs=n, max_postings=mp, k1=k1, b=b)


def build(texts: List[Optional[str]], k1: float = 1.2,
          b: float = 0.75) -> FulltextIndex:
    n_docs = len(texts)
    vocab: Dict[str, int] = {}
    postings: List[Dict[int, int]] = []   # term -> {doc: tf}
    doc_len = np.zeros(n_docs, np.float32)
    for di, text in enumerate(texts):
        toks = tokenize(text or "")
        doc_len[di] = len(toks)
        for t in toks:
            tid = vocab.setdefault(t, len(vocab))
            while len(postings) <= tid:
                postings.append({})
            postings[tid][di] = postings[tid].get(di, 0) + 1
    V = len(vocab)
    sizes = np.array([len(p) for p in postings], np.int64)
    nnz = int(sizes.sum())
    offsets = np.zeros(V + 1, np.int32)
    np.cumsum(sizes, out=offsets[1:])
    doc_idx = np.zeros(max(nnz, 1), np.int32)
    tf = np.zeros(max(nnz, 1), np.float32)
    for tid, p in enumerate(postings):
        base = offsets[tid]
        for j, (di, f) in enumerate(sorted(p.items())):
            doc_idx[base + j] = di
            tf[base + j] = f
    # Robertson/Sparck-Jones idf with +1 flooring (the Lucene/reference form)
    df = sizes.astype(np.float64)
    idf = np.log(1.0 + (n_docs - df + 0.5) / (df + 0.5)).astype(np.float32) \
        if V else np.zeros(0, np.float32)
    avgdl = float(doc_len.mean()) if n_docs else 1.0
    doc_norm = (k1 * (1.0 - b + b * doc_len / max(avgdl, 1e-9))
                ).astype(np.float32)
    return FulltextIndex(
        doc_idx=jnp.asarray(doc_idx), tf=jnp.asarray(tf),
        term_offsets=jnp.asarray(offsets), idf=jnp.asarray(idf),
        doc_norm=jnp.asarray(doc_norm), vocab=vocab, n_docs=n_docs,
        max_postings=int(sizes.max()) if V else 1, k1=k1, b=b)


def _score_terms(index: FulltextIndex, term_ids: jnp.ndarray,
                 pad: int) -> jnp.ndarray:
    """Dense BM25 scores [n_docs] for the given term ids (-1 = missing)."""
    n = index.n_docs

    def one_term(carry, tid):
        scores = carry
        valid_t = tid >= 0
        t = jnp.maximum(tid, 0)
        start = index.term_offsets[t]
        end = index.term_offsets[t + 1]
        lane = jnp.arange(pad, dtype=jnp.int32)
        pos = jnp.clip(start + lane, 0, index.doc_idx.shape[0] - 1)
        ok = (start + lane < end) & valid_t
        docs = index.doc_idx[pos]
        tfs = index.tf[pos]
        norm = index.doc_norm[docs]
        contrib = index.idf[t] * tfs * (index.k1 + 1.0) / (tfs + norm)
        contrib = jnp.where(ok, contrib, 0.0)
        scores = scores.at[docs].add(contrib, mode="drop")
        return scores, None

    init = jnp.zeros((n,), jnp.float32)
    scores, _ = jax.lax.scan(one_term, init, term_ids)
    return scores


def search(index: FulltextIndex, query: str, k: int = 10
           ) -> Tuple[np.ndarray, np.ndarray]:
    """-> (scores [k], doc positions [k]) best-first; score 0 = no match."""
    if index.n_docs == 0:
        return np.zeros(0, np.float32), np.zeros(0, np.int64)
    terms = tokenize(query)
    tids = np.asarray([index.vocab.get(t, -1) for t in terms] or [-1],
                      np.int32)
    scores = _score_terms(index, jnp.asarray(tids), index.max_postings)
    k = min(k, index.n_docs) or 1
    top_s, top_i = jax.lax.top_k(scores, k)
    return np.asarray(top_s), np.asarray(top_i)


def score_all(index: FulltextIndex, query: str) -> np.ndarray:
    """Dense scores for every document (SQL scalar-function path)."""
    terms = tokenize(query)
    tids = np.asarray([index.vocab.get(t, -1) for t in terms] or [-1],
                      np.int32)
    return np.asarray(_score_terms(index, jnp.asarray(tids),
                                   index.max_postings))

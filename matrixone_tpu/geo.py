"""Geospatial primitives over WKT (reference: pkg/geo — WKT/WKB types,
overlay predicates, geohash). Redesign for this engine's execution
model: geometries travel as WKT strings (varchar), and the ST_*
functions evaluate at the DICTIONARY level like every other string
function (O(distinct geometries) host work, device gathers) — planar
(cartesian) semantics.

Covered: POINT / LINESTRING / POLYGON (outer ring) parsing,
ST_GeomFromText (normalize/validate), ST_X/ST_Y, ST_Distance
(point-to-point / point-to-segment / segment-to-segment minimum),
ST_Within / ST_Contains (point-in-polygon, ray casting; polygon
containment via all-vertices + no-edge-crossing), ST_Area (shoelace),
ST_GeoHash (standard base32 geohash of a point, lon/lat order).
"""

from __future__ import annotations

import math
import re
from typing import List, Optional, Tuple

Coords = List[Tuple[float, float]]

_NUM = r"[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?"
_PAIR_RE = re.compile(rf"({_NUM})\s+({_NUM})")


class Geometry:
    def __init__(self, kind: str, coords: Coords):
        self.kind = kind            # POINT | LINESTRING | POLYGON
        self.coords = coords        # polygon: closed outer ring

    def wkt(self) -> str:
        # repr: shortest round-trip formatting — %g's 6 significant
        # digits would shift real-world coordinates by ~30m
        pts = ", ".join(f"{x!r} {y!r}" for x, y in self.coords)
        if self.kind == "POINT":
            return f"POINT({pts})"
        if self.kind == "LINESTRING":
            return f"LINESTRING({pts})"
        return f"POLYGON(({pts}))"


def parse_wkt(text: str) -> Optional[Geometry]:
    """WKT subset parser; None for anything malformed (SQL NULL)."""
    if not isinstance(text, str):
        return None
    s = text.strip().upper()
    m = re.match(r"^(POINT|LINESTRING|POLYGON)\s*\((.*)\)$", s,
                 re.DOTALL)
    if not m:
        return None
    kind, body = m.group(1), m.group(2).strip()
    if kind == "POLYGON":
        if not (body.startswith("(") and body.endswith(")")):
            return None
        body = body[1:-1]
        if ")" in body or "(" in body:
            return None        # interior rings unsupported (v1)
    coords = [(float(a), float(b)) for a, b in _PAIR_RE.findall(body)]
    if kind == "POINT" and len(coords) != 1:
        return None
    if kind == "LINESTRING" and len(coords) < 2:
        return None
    if kind == "POLYGON":
        if len(coords) < 4 or coords[0] != coords[-1]:
            return None
    return Geometry(kind, coords)


# ------------------------------------------------------------ measures
def _seg_point_d2(p, a, b) -> float:
    ax, ay = a
    bx, by = b
    px, py = p
    dx, dy = bx - ax, by - ay
    ll = dx * dx + dy * dy
    if ll == 0:
        return (px - ax) ** 2 + (py - ay) ** 2
    t = max(0.0, min(1.0, ((px - ax) * dx + (py - ay) * dy) / ll))
    cx, cy = ax + t * dx, ay + t * dy
    return (px - cx) ** 2 + (py - cy) ** 2


def _segs(g: Geometry):
    return list(zip(g.coords[:-1], g.coords[1:]))


def _segs_cross(a1, a2, b1, b2) -> bool:
    def orient(p, q, r):
        v = (q[0] - p[0]) * (r[1] - p[1]) - (q[1] - p[1]) * (r[0] - p[0])
        return 0 if abs(v) < 1e-12 else (1 if v > 0 else -1)
    o1, o2 = orient(a1, a2, b1), orient(a1, a2, b2)
    o3, o4 = orient(b1, b2, a1), orient(b1, b2, a2)
    return o1 != o2 and o3 != o4 and 0 not in (o1, o2, o3, o4)


def distance(g1: Geometry, g2: Geometry) -> float:
    """Minimum planar distance between the two geometries' boundaries/
    points (0 when a point lies inside a polygon)."""
    if g1.kind != "POINT" and g2.kind == "POINT":
        return distance(g2, g1)
    if g1.kind == "POINT" and g2.kind == "POINT":
        (x1, y1), (x2, y2) = g1.coords[0], g2.coords[0]
        return math.hypot(x2 - x1, y2 - y1)
    if g1.kind == "POINT":
        if g2.kind == "POLYGON" and contains(g2, g1):
            return 0.0
        p = g1.coords[0]
        return math.sqrt(min(_seg_point_d2(p, a, b)
                             for a, b in _segs(g2)))
    # line/polygon vs line/polygon: min over segment pairs (+ endpoint
    # containment for polygons)
    for g, other in ((g1, g2), (g2, g1)):
        if g.kind == "POLYGON" and \
                contains(g, Geometry("POINT", [other.coords[0]])):
            return 0.0
    best = math.inf
    for a1, a2 in _segs(g1):
        for b1, b2 in _segs(g2):
            if _segs_cross(a1, a2, b1, b2):
                return 0.0
            best = min(best,
                       _seg_point_d2(a1, b1, b2), _seg_point_d2(a2, b1, b2),
                       _seg_point_d2(b1, a1, a2), _seg_point_d2(b2, a1, a2))
    return math.sqrt(best)


def area(g: Geometry) -> float:
    if g.kind != "POLYGON":
        return 0.0
    s = 0.0
    for (x1, y1), (x2, y2) in _segs(g):
        s += x1 * y2 - x2 * y1
    return abs(s) / 2.0


def _point_in_polygon(p, ring: Coords) -> bool:
    """Ray casting; boundary points count as inside (MySQL ST_Within
    on the boundary is a gray zone — we choose closed semantics)."""
    x, y = p
    for a, b in zip(ring[:-1], ring[1:]):
        if _seg_point_d2((x, y), a, b) < 1e-18:
            return True
    inside = False
    j = len(ring) - 2
    for i in range(len(ring) - 1):
        xi, yi = ring[i]
        xj, yj = ring[j]
        if (yi > y) != (yj > y) and \
                x < (xj - xi) * (y - yi) / (yj - yi) + xi:
            inside = not inside
        j = i
    return inside


def contains(outer: Geometry, inner: Geometry) -> bool:
    """outer CONTAINS inner (planar). Polygon outer only."""
    if outer.kind != "POLYGON":
        return False
    if not all(_point_in_polygon(p, outer.coords)
               for p in inner.coords):
        return False
    if inner.kind == "POINT":
        return True
    # every vertex inside and no edge escapes through the boundary
    for a1, a2 in _segs(inner):
        for b1, b2 in _segs(outer):
            if _segs_cross(a1, a2, b1, b2):
                return False
    return True


_GH32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def geohash(lon: float, lat: float, precision: int = 12) -> str:
    """Standard geohash (interleaved lon/lat bits, base32)."""
    lat_r = [-90.0, 90.0]
    lon_r = [-180.0, 180.0]
    out = []
    bit = 0
    ch = 0
    even = True
    while len(out) < precision:
        if even:
            mid = (lon_r[0] + lon_r[1]) / 2
            if lon >= mid:
                ch = (ch << 1) | 1
                lon_r[0] = mid
            else:
                ch <<= 1
                lon_r[1] = mid
        else:
            mid = (lat_r[0] + lat_r[1]) / 2
            if lat >= mid:
                ch = (ch << 1) | 1
                lat_r[0] = mid
            else:
                ch <<= 1
                lat_r[1] = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_GH32[ch])
            bit = 0
            ch = 0
    return "".join(out)

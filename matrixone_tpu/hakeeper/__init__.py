"""HAKeeper: the cluster control plane — membership, failure detection,
and repair.

Reference analogue: `pkg/hakeeper` (the Raft-backed cluster brain:
heartbeat ingestion per service kind, checkers/coordinator.go:32 turning
state deltas into repair operators, logservice/clusterservice feeding
routing) — redesigned to this engine's shape: one keeper process/thread
with a TCP API (same length-prefixed JSON frames as the log service),
services push heartbeats, a ticker marks services DOWN after
`down_after_s` of silence and runs registered repair hooks (the
"operator" half of the reference's checkers). Cluster state is
persisted through a pluggable store function so a restarted keeper
resumes the same membership view (the reference stores it in the Raft
state machine; here the fileservice plays that role).

The keeper is deliberately the HUB of membership (the reference adds
memberlist gossip for CN discovery; with a keeper present gossip is an
optimization, not a requirement — `details()` is the clusterservice
query surface the proxy/router consumes).
"""

from __future__ import annotations

import socket
import threading

from matrixone_tpu.utils import san
from matrixone_tpu.utils.lifecycle import ServiceThreads
import time
from typing import Callable, Dict, List, Optional, Tuple

from matrixone_tpu.logservice.replicated import _recv_msg, _send_msg
from matrixone_tpu.utils.sync import notify_waiters

STATE_UP = "up"
STATE_DOWN = "down"


class HAKeeper:
    """Cluster-state keeper + failure detector + repair coordinator."""

    def __init__(self, port: int = 0, down_after_s: float = 2.0,
                 tick_s: float = 0.5,
                 persist: Optional[Callable[[dict], None]] = None,
                 restore: Optional[Callable[[], Optional[dict]]] = None,
                 standby_of: Optional[Tuple[str, int]] = None,
                 takeover_after_s: float = 2.0):
        self.down_after_s = down_after_s
        self.tick_s = tick_s
        self.persist = persist
        self._restore = restore
        #: control-plane survival (reference: the HAKeeper Raft group
        #: keeps running on replica loss): a standby keeper shares the
        #: persist store with the primary, answers every state op with
        #: {"standby": True} (clients fail over), and promotes itself
        #: when the primary stays silent past `takeover_after_s`
        self.standby_of = standby_of
        self.takeover_after_s = takeover_after_s
        self.role = "standby" if standby_of else "primary"
        self.last_persist_error: Optional[str] = None
        self.persist_failures = 0
        #: generation fencing through the shared store: promote() bumps
        #: it, and a primary that reads a HIGHER stored generation
        #: demotes itself — so a paused-not-dead primary that resumes
        #: after a takeover steps down instead of split-braining the
        #: snapshot (the reference gets this from Raft terms)
        self.keeper_gen = 1
        # sid -> record dict
        self.services: Dict[str, dict] = {}
        if standby_of is None:
            self._restore_services()
            self.keeper_gen = max(self.keeper_gen, self._stored_gen())
        self.operators: List[dict] = []     # repair audit log
        self._repair: Dict[str, Callable[[dict], None]] = {}
        self._lock = san.lock("HAKeeper._lock")
        self._stopping = threading.Event()
        self._svc = ServiceThreads("mo-ha")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(32)

    def _restore_services(self) -> None:
        """Resume the persisted membership view (the reference keeps it
        in the HAKeeper Raft state machine); restored services get a
        fresh heartbeat grace window before the checker may expire
        them."""
        if self._restore is None:
            return
        try:
            snap = self._restore() or {}
        except Exception:   # noqa: BLE001 — operator-supplied restore
            snap = {}       # callback; promotion must proceed on a
                            # fresh state rather than crash the keeper
        for sid, rec in snap.items():
            if sid.startswith("__"):       # reserved store keys (gen)
                continue
            r = dict(rec)
            r["meta"] = dict(rec.get("meta", {}))
            # fresh heartbeat grace, but persisted DOWN stays DOWN —
            # resurrecting it would route traffic to a dead endpoint
            # and re-fire its repair on the next expiry
            r["last_hb"] = time.monotonic()
            self.services[sid] = r

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HAKeeper":
        self._svc.spawn_accept(self._serve)
        if self.role == "primary":
            self._svc.spawn_loop(self._tick_loop, "tick")
        else:
            self._svc.spawn_loop(self._watch_primary, "watch")
        return self

    # ------------------------------------------------------- standby mode
    def _watch_primary(self) -> None:
        last_seen = time.monotonic()
        while not self._stopping.wait(min(self.tick_s, 0.25)):
            try:
                s = socket.create_connection(self.standby_of, timeout=1)
                try:
                    _send_msg(s, {"op": "status"})
                    resp, _ = _recv_msg(s)
                    if resp.get("role") == "primary":
                        last_seen = time.monotonic()
                finally:
                    s.close()
            except (OSError, ConnectionError):
                pass
            if time.monotonic() - last_seen > self.takeover_after_s:
                self.promote()
                return

    def _stored_gen(self) -> int:
        if self._restore is None:
            return 0
        try:
            snap = self._restore() or {}
            return int(snap.get("__keeper_gen", {}).get("gen", 0))
        except Exception:   # noqa: BLE001 — operator-supplied restore
            return 0        # callback; a missing/corrupt store reads
                            # as generation 0

    def promote(self) -> None:
        """Standby -> primary: adopt the shared persisted state (grace
        window restarts), bump the keeper generation (fences the old
        primary), and begin running checkers."""
        with self._lock:
            if self.role == "primary":
                return
            self.role = "primary"
            self._restore_services()
            self.keeper_gen = self._stored_gen() + 1
            self.operators.append({"op": "takeover", "at": time.time(),
                                   "gen": self.keeper_gen})
            self._persist_locked()
        notify_waiters()
        threading.Thread(target=self._tick_loop, daemon=True).start()

    def demote(self) -> None:
        """A fenced primary steps down: stop answering state ops (the
        tick loop exits when role != primary)."""
        import sys
        with self._lock:
            if self.role != "primary":
                return
            self.role = "standby"
            self.operators.append({"op": "demoted", "at": time.time()})
        notify_waiters()
        print("[hakeeper] demoted: a newer keeper generation owns the "
              "store", file=sys.stderr, flush=True)

    def stop(self) -> None:
        self._stopping.set()
        # a stopped keeper must look dead to CONNECTED clients too, so
        # their heartbeats fail over to the standby instead of landing
        # on a zombie's accepted sockets: ServiceThreads shuts down the
        # listener + every tracked conn (shutdown() — close() alone does
        # not wake a blocked accept/recv) and joins serve/tick/watch
        # loops + handlers with a deadline
        self._svc.shutdown(self._sock)

    def on_down(self, kind: str, fn: Callable[[dict], None]) -> None:
        """Register a repair hook for a service kind (checkers analogue):
        called once per up->down transition with the service record."""
        self._repair[kind] = fn

    # ------------------------------------------------------------ state ops
    def register(self, kind: str, sid: str, addr: str = "",
                 meta: Optional[dict] = None) -> None:
        with self._lock:
            self.services[sid] = {
                "kind": kind, "sid": sid, "addr": addr,
                "meta": meta or {}, "state": STATE_UP,
                "last_hb": time.monotonic(), "registered_at": time.time(),
                "downs": self.services.get(sid, {}).get("downs", 0),
            }
            self._persist_locked()
        notify_waiters()

    def heartbeat(self, sid: str, stats: Optional[dict] = None) -> bool:
        with self._lock:
            rec = self.services.get(sid)
            if rec is None:
                return False            # caller must re-register
            rec["last_hb"] = time.monotonic()
            if stats:
                rec["meta"].update(stats)
            if rec["state"] == STATE_DOWN:
                rec["state"] = STATE_UP   # service came back on its own
        notify_waiters()
        return True

    def deregister(self, sid: str) -> None:
        with self._lock:
            self.services.pop(sid, None)
            self._persist_locked()
        notify_waiters()

    def details(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = []
            for rec in self.services.values():
                if kind is None or rec["kind"] == kind:
                    r = dict(rec)
                    r["meta"] = dict(rec["meta"])   # deep enough: callers
                    # serialize/iterate outside the lock while heartbeats
                    # mutate the live meta dict
                    r["age_s"] = time.monotonic() - rec["last_hb"]
                    out.append(r)
            return sorted(out, key=lambda r: r["sid"])

    def up_addrs(self, kind: str) -> List[str]:
        """Healthy endpoints of one kind — the clusterservice routing
        query the proxy consumes."""
        return [r["addr"] for r in self.details(kind)
                if r["state"] == STATE_UP and r["addr"]]

    def _persist_locked(self) -> None:
        if self.persist is None:
            return
        # Generation fencing on the WRITE path too: after a standby
        # takeover bumps the stored gen to N+1, the old not-yet-demoted
        # primary still serves register/deregister until its next tick —
        # one unconditional persist would roll the store back to N and
        # unfence BOTH keepers (persistent split-brain). Refuse the
        # write and step down inline (the lock is already held, so
        # demote() would deadlock).
        stored = self._stored_gen()
        if stored > self.keeper_gen:
            if self.role == "primary":
                import sys
                self.role = "standby"
                self.operators.append({"op": "demoted", "at": time.time()})
                notify_waiters()
                print("[hakeeper] demoted: a newer keeper generation "
                      "owns the store; persist refused", file=sys.stderr,
                      flush=True)
            return
        snap = {sid: {k: v for k, v in rec.items() if k != "last_hb"}
                for sid, rec in self.services.items()}
        snap["__keeper_gen"] = {"gen": self.keeper_gen}
        try:
            self.persist(snap)
            self.last_persist_error = None
        except Exception as e:           # noqa: BLE001
            # LOUD: a keeper that silently loses its snapshot hands the
            # next takeover an empty cluster view
            import sys
            self.persist_failures += 1
            self.last_persist_error = f"{type(e).__name__}: {e}"
            print(f"[hakeeper] PERSIST FAILED "
                  f"({self.persist_failures}x): "
                  f"{self.last_persist_error}", file=sys.stderr,
                  flush=True)

    # ------------------------------------------------------- failure check
    def _tick_loop(self) -> None:
        while not self._stopping.wait(self.tick_s):
            if self.role != "primary":
                return
            if self._stored_gen() > self.keeper_gen:
                self.demote()
                return
            if self._stored_gen() < self.keeper_gen:
                # the store regressed below our generation: a stale
                # primary's check-then-write raced our takeover persist
                # (the store is a plain file, no CAS — the reference
                # gets atomicity from Raft). Re-assert our generation;
                # the stale keeper then demotes at ITS next persist or
                # tick, so any split-brain window is bounded by one
                # tick interval instead of lasting indefinitely.
                with self._lock:
                    self._persist_locked()
            self.tick()

    def tick(self) -> None:
        """One checker pass (coordinator.go:32 analogue): expire silent
        services, run repair hooks on up->down edges."""
        now = time.monotonic()
        newly_down = []
        with self._lock:
            for rec in self.services.values():
                if rec["state"] == STATE_UP and \
                        now - rec["last_hb"] > self.down_after_s:
                    rec["state"] = STATE_DOWN
                    rec["downs"] += 1
                    snap = dict(rec)
                    snap["meta"] = dict(rec["meta"])
                    newly_down.append(snap)
            if newly_down:
                self._persist_locked()
        for rec in newly_down:
            op = {"op": "service_down", "sid": rec["sid"],
                  "kind": rec["kind"], "at": time.time()}
            repair = self._repair.get(rec["kind"])
            if repair is not None:
                op["repair"] = "dispatched"
                try:
                    repair(rec)
                except Exception as e:   # noqa: BLE001
                    op["repair"] = f"failed: {e}"
            with self._lock:
                self.operators.append(op)
        if newly_down:
            notify_waiters()

    # ---------------------------------------------------------- TCP server
    def _serve(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._svc.spawn_handler(self._handle, conn)

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                header, _ = _recv_msg(conn)
                op = header.get("op")
                if op == "status":
                    _send_msg(conn, {"ok": True, "role": self.role,
                                     "persist_failures":
                                         self.persist_failures,
                                     "last_persist_error":
                                         self.last_persist_error})
                    continue
                if self.role != "primary":
                    # clients fail over to the keeper that holds state
                    _send_msg(conn, {"ok": False, "standby": True})
                    continue
                if op == "register":
                    self.register(header["kind"], header["sid"],
                                  header.get("addr", ""),
                                  header.get("meta"))
                    _send_msg(conn, {"ok": True})
                elif op == "heartbeat":
                    ok = self.heartbeat(header["sid"], header.get("stats"))
                    _send_msg(conn, {"ok": ok})
                elif op == "details":
                    _send_msg(conn, {"ok": True,
                                     "services": self.details(
                                         header.get("kind"))})
                elif op == "deregister":
                    self.deregister(header["sid"])
                    _send_msg(conn, {"ok": True})
                else:
                    _send_msg(conn, {"ok": False, "err": f"bad op {op}"})
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class HAClient:
    """Service-side agent: registers once and heartbeats on a thread
    (the reference's per-service heartbeat senders, cnservice/tnservice
    heartbeat.go). `addr` may be a single (host, port) or a LIST of
    keeper endpoints — on silence or a standby answer the client rotates
    to the next keeper (routing recovery after a takeover)."""

    def __init__(self, addr, kind: str, sid: str,
                 service_addr: str = "", meta: Optional[dict] = None,
                 interval_s: float = 0.5,
                 stats_fn: Optional[Callable[[], dict]] = None):
        if isinstance(addr, tuple) or (isinstance(addr, list)
                                       and len(addr) == 2
                                       and isinstance(addr[1], int)):
            addr = [tuple(addr)]
        self.addrs = [tuple(a) for a in addr]
        self._cur = 0
        self.kind = kind
        self.sid = sid
        self.service_addr = service_addr
        self.meta = meta or {}
        self.interval_s = interval_s
        self.stats_fn = stats_fn
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        # serialize frames: stop()'s deregister must not interleave with
        # an in-flight heartbeat on the shared socket
        self._call_lock = san.lock("HAClient._call_lock")

    def _call_one(self, header: dict) -> Optional[dict]:
        try:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self.addrs[self._cur], timeout=2)
                # molint: disable=deadline-propagation -- control-plane
                # heartbeat: runs on its own thread with no statement
                # deadline in scope; the fixed 2s bound IS the liveness
                # contract (a heartbeat slower than that is a miss)
                self._sock.settimeout(2)
            _send_msg(self._sock, header)
            resp, _ = _recv_msg(self._sock)
            return resp
        except (OSError, ConnectionError):
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            self._sock = None
            return None

    def _call(self, header: dict) -> Optional[dict]:
        with self._call_lock:
            for _ in range(len(self.addrs)):
                resp = self._call_one(header)
                if resp is not None and not resp.get("standby"):
                    return resp
                # dead or standby keeper: rotate and retry
                self._cur = (self._cur + 1) % len(self.addrs)
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                self._sock = None
            return None

    def start(self) -> "HAClient":
        self._call({"op": "register", "kind": self.kind, "sid": self.sid,
                    "addr": self.service_addr, "meta": self.meta})
        self._hb_thread = threading.Thread(target=self._loop, daemon=True,
                                           name="mo-ha-heartbeat")
        self._hb_thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                stats = self.stats_fn() if self.stats_fn else None
            except Exception:   # noqa: BLE001 — user stats callback:
                # a metrics read must never kill the heartbeat thread —
                # that would read as a service failure and trigger repair
                stats = None
            r = self._call({"op": "heartbeat", "sid": self.sid,
                            "stats": stats})
            if r is not None and r.get("ok") is False:
                # keeper restarted and lost us: re-register
                self._call({"op": "register", "kind": self.kind,
                            "sid": self.sid, "addr": self.service_addr,
                            "meta": self.meta})

    def stop(self) -> None:
        self._stop.set()
        self._call({"op": "deregister", "sid": self.sid})
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # the heartbeat loop wakes from its interval wait on _stop; join
        # it with a deadline instead of abandoning it
        hb = getattr(self, "_hb_thread", None)
        if hb is not None:
            hb.join(timeout=5)


def details_via_tcp(addr, kind: Optional[str] = None) -> List[dict]:
    """One-shot clusterservice query; `addr` may be one endpoint (tuple
    OR ['host', port] list, e.g. from JSON) or a list of endpoints
    (first primary keeper answers)."""
    if isinstance(addr, tuple) or (isinstance(addr, list)
                                   and len(addr) == 2
                                   and isinstance(addr[1], int)):
        addrs = [tuple(addr)]
    else:
        addrs = [tuple(a) for a in addr]
    last: Exception = ConnectionError("no keeper reachable")
    for a in addrs:
        try:
            s = socket.create_connection(a, timeout=2)
            try:
                _send_msg(s, {"op": "details", "kind": kind})
                resp, _ = _recv_msg(s)
                if resp.get("standby"):
                    continue
                return resp.get("services", [])
            finally:
                s.close()
        except (OSError, ConnectionError) as e:
            last = e
    raise last

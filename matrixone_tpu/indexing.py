"""Index build/refresh shared by DDL and the scan operators.

Reference analogue: the iscp IndexSync consumer + idxcron re-clustering
(`pkg/iscp`, `pkg/vectorindex/idxcron`): the reference maintains indexes
asynchronously off the logtail; here commits mark dependent indexes dirty
(engine.commit_txn) and the next index-accelerated query rebuilds lazily —
same freshness contract (eventually consistent), simpler machinery.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from matrixone_tpu.storage.engine import Engine, IndexMeta


def build_ivfflat(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu.vectorindex import ivf_flat, ivf_pq
    table = engine.get_table(ix.table)
    data, gids = table.read_column_f32(ix.columns[0])
    if len(data) == 0:
        ix.index_obj = None            # empty table: nothing to index yet
        ix.options["_row_gids"] = gids
        ix.dirty = False
        return
    nlist = int(ix.options.get("lists", 64))
    metric = ix.options.get("_metric", "l2")
    nlist = max(1, min(nlist, max(1, len(data))))
    if ix.algo == "ivfpq":
        d = data.shape[1] if data.ndim == 2 else 1
        m = int(ix.options.get("subspaces", 0)) or _pick_subspaces(d)
        if d % m != 0:
            raise ValueError(f"dim {d} must divide into n_subspaces={m}")
        ix.index_obj = ivf_pq.build(jnp.asarray(data), nlist=nlist,
                                    n_subspaces=m, metric=metric)
    else:
        ix.index_obj = ivf_flat.build(jnp.asarray(data), nlist=nlist,
                                      metric=metric)
    ix.options["_row_gids"] = gids
    ix.options.pop("_delta_vecs", None)
    ix.options.pop("_delta_gids", None)
    ix.dirty = False


def build_hnsw(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu.vectorindex import hnsw
    table = engine.get_table(ix.table)
    data, gids = table.read_column_f32(ix.columns[0])
    if len(data) == 0:
        ix.index_obj = None
        ix.options["_row_gids"] = gids
        ix.dirty = False
        return
    m = int(ix.options.get("m", 16))
    ef_c = int(ix.options.get("ef_construction", 64))
    metric = ix.options.get("_metric", "l2")
    ix.index_obj = hnsw.build(np.asarray(data), M=m, ef_construction=ef_c,
                              metric=metric)
    ix.options["_row_gids"] = gids
    ix.options.pop("_delta_vecs", None)
    ix.options.pop("_delta_gids", None)
    ix.dirty = False


def _pick_subspaces(d: int) -> int:
    """Largest divisor of d with subspace width >= 4, capped at d//4."""
    for m in (96, 64, 48, 32, 24, 16, 12, 8, 6, 4, 2, 1):
        if m <= max(d // 4, 1) and d % m == 0:
            return m
    return 1


def build_fulltext(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu import fulltext as FT
    table = engine.get_table(ix.table)
    texts = None
    gids = None
    for col in ix.columns:
        col_texts, col_gids = table.read_texts(col)
        if texts is None:
            texts, gids = col_texts, col_gids
        else:
            # multi-column index: concatenated document text (reference:
            # fulltext multi-column MATCH)
            texts = [" ".join(t for t in (a, b) if t) or None
                     for a, b in zip(texts, col_texts)]
    ix.index_obj = FT.build(texts or [])
    ix.options["_row_gids"] = gids if gids is not None \
        else np.zeros(0, np.int64)
    ix.options.pop("_delta_vecs", None)
    ix.options.pop("_delta_gids", None)
    ix.dirty = False


#: delta fraction beyond which a dirty refresh falls back to a full
#: recluster (reference: idxcron re-clustering policy)
RECLUSTER_FRACTION = 0.1


def refresh_if_dirty(engine: Engine, ix: IndexMeta) -> None:
    if not ix.dirty:
        return
    # under the commit lock: a concurrent commit must not set dirty=True
    # between our table read and the trailing dirty=False (lost update)
    with engine._commit_lock:
        if not ix.dirty:
            return
        if ix.algo in ("ivfflat", "ivfpq"):
            if not _try_incremental(engine, ix):
                build_ivfflat(engine, ix)
        elif ix.algo == "hnsw":
            build_hnsw(engine, ix)
        elif ix.algo == "fulltext":
            build_fulltext(engine, ix)
        _register_in_cache(engine, ix)


def _try_incremental(engine: Engine, ix: IndexMeta) -> bool:
    """Incremental refresh (reference: iscp IndexSync feed): rows INSERTED
    since the last build land in a brute-force delta segment the search
    path scans exactly; DELETEs need no index change (visible_gids filters
    dead candidates at search). Falls back to a full recluster when the
    delta outgrows RECLUSTER_FRACTION of the indexed rows (idxcron role)
    or when gids were rewritten (table merge)."""
    if ix.index_obj is None:
        return False
    table = engine.get_table(ix.table)
    data, gids = table.read_column_f32(ix.columns[0])
    base = np.asarray(ix.options.get("_row_gids", np.zeros(0, np.int64)))
    dgids = np.asarray(ix.options.get("_delta_gids",
                                      np.zeros(0, np.int64)))
    known = np.union1d(base, dgids)
    new_mask = ~np.isin(gids, known)
    n_new = int(new_mask.sum())
    if n_new == 0:
        ix.dirty = False
        return True
    if n_new + len(dgids) > RECLUSTER_FRACTION * max(len(base), 1):
        return False
    new_vecs = np.asarray(data)[new_mask]
    old = ix.options.get("_delta_vecs")
    ix.options["_delta_vecs"] = (new_vecs if old is None or not len(old)
                                 else np.concatenate([old, new_vecs]))
    ix.options["_delta_gids"] = np.concatenate([dgids, gids[new_mask]])
    ix.dirty = False
    return True


def fold_delta(engine: Engine, ix: IndexMeta) -> bool:
    """Full recluster folding the delta back in — the idxcron background
    job body (run via taskservice off the query path). Returns True when
    a rebuild happened."""
    with engine._commit_lock:
        has_delta = len(ix.options.get("_delta_gids", ())) > 0
        if not (ix.dirty or has_delta):
            return False
        if ix.algo in ("ivfflat", "ivfpq"):
            build_ivfflat(engine, ix)
        elif ix.algo == "hnsw":
            build_hnsw(engine, ix)
        elif ix.algo == "fulltext":
            build_fulltext(engine, ix)
        ix.options.pop("_delta_vecs", None)
        ix.options.pop("_delta_gids", None)
        _register_in_cache(engine, ix)
        return True


def register_recluster_task(engine: Engine, tasks, period_s: float = 60.0):
    """Schedule delta folding on the durable task service
    (reference: vectorindex/idxcron). Returns the task id."""
    def body(eng, arg):
        for ix in list(eng.indexes.values()):
            fold_delta(eng, ix)
    tasks.register("index_recluster", body)
    return tasks.submit("index_recluster", "index_recluster",
                        interval_s=period_s)


def register_in_cache(engine: Engine, ix: IndexMeta) -> None:
    cache = getattr(engine, "index_cache", None)
    if cache is not None and ix.index_obj is not None:
        cache.put(ix)


_register_in_cache = register_in_cache

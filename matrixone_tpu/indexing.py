"""Index build/refresh shared by DDL and the scan operators.

Reference analogue: the iscp IndexSync consumer + idxcron re-clustering
(`pkg/iscp`, `pkg/vectorindex/idxcron`): the reference maintains indexes
asynchronously off the logtail; here commits mark dependent indexes dirty
(engine.commit_txn) and the next index-accelerated query rebuilds lazily —
same freshness contract (eventually consistent), simpler machinery.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from matrixone_tpu.storage.engine import Engine, IndexMeta


def build_ivfflat(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu.vectorindex import ivf_flat, ivf_pq
    table = engine.get_table(ix.table)
    data, gids = table.read_column_f32(ix.columns[0])
    if len(data) == 0:
        ix.index_obj = None            # empty table: nothing to index yet
        ix.options["_row_gids"] = gids
        ix.dirty = False
        return
    nlist = int(ix.options.get("lists", 64))
    metric = ix.options.get("_metric", "l2")
    nlist = max(1, min(nlist, max(1, len(data))))
    if ix.algo == "ivfpq":
        d = data.shape[1] if data.ndim == 2 else 1
        m = int(ix.options.get("subspaces", 0)) or _pick_subspaces(d)
        if d % m != 0:
            raise ValueError(f"dim {d} must divide into n_subspaces={m}")
        ix.index_obj = ivf_pq.build(jnp.asarray(data), nlist=nlist,
                                    n_subspaces=m, metric=metric)
    else:
        ix.index_obj = ivf_flat.build(jnp.asarray(data), nlist=nlist,
                                      metric=metric)
    ix.options["_row_gids"] = gids
    ix.dirty = False


def build_hnsw(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu.vectorindex import hnsw
    table = engine.get_table(ix.table)
    data, gids = table.read_column_f32(ix.columns[0])
    if len(data) == 0:
        ix.index_obj = None
        ix.options["_row_gids"] = gids
        ix.dirty = False
        return
    m = int(ix.options.get("m", 16))
    ef_c = int(ix.options.get("ef_construction", 64))
    metric = ix.options.get("_metric", "l2")
    ix.index_obj = hnsw.build(np.asarray(data), M=m, ef_construction=ef_c,
                              metric=metric)
    ix.options["_row_gids"] = gids
    ix.dirty = False


def _pick_subspaces(d: int) -> int:
    """Largest divisor of d with subspace width >= 4, capped at d//4."""
    for m in (96, 64, 48, 32, 24, 16, 12, 8, 6, 4, 2, 1):
        if m <= max(d // 4, 1) and d % m == 0:
            return m
    return 1


def build_fulltext(engine: Engine, ix: IndexMeta) -> None:
    from matrixone_tpu import fulltext as FT
    table = engine.get_table(ix.table)
    texts = None
    gids = None
    for col in ix.columns:
        col_texts, col_gids = table.read_texts(col)
        if texts is None:
            texts, gids = col_texts, col_gids
        else:
            # multi-column index: concatenated document text (reference:
            # fulltext multi-column MATCH)
            texts = [" ".join(t for t in (a, b) if t) or None
                     for a, b in zip(texts, col_texts)]
    ix.index_obj = FT.build(texts or [])
    ix.options["_row_gids"] = gids if gids is not None \
        else np.zeros(0, np.int64)
    ix.dirty = False


def refresh_if_dirty(engine: Engine, ix: IndexMeta) -> None:
    if not ix.dirty:
        return
    # under the commit lock: a concurrent commit must not set dirty=True
    # between our table read and the trailing dirty=False (lost update)
    with engine._commit_lock:
        if not ix.dirty:
            return
        if ix.algo in ("ivfflat", "ivfpq"):
            build_ivfflat(engine, ix)
        elif ix.algo == "hnsw":
            build_hnsw(engine, ix)
        elif ix.algo == "fulltext":
            build_fulltext(engine, ix)

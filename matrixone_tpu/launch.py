"""Cluster launcher: one TOML file -> a whole running cluster.

Reference analogue: `cmd/mo-service -launch launch.toml`
(cmd/mo-service/launch.go:38 starts log -> TN -> CN in order from
per-role toml files; etc/launch/launch.toml). Redesign: one TOML
describes the deployment; the launcher spawns the log replicas, the TN
(journaling through the quorum WAL when replicas > 0), and N CN
processes (wired to each other's fragment endpoints for distributed
scopes), hosts the HAKeeper (+ optional standby) and the MySQL-aware
proxy in-process, points every service's heartbeats at the keepers, and
writes the port map to `<data_dir>/launch_ports.json` for tooling.

    [cluster]
    data_dir = "/var/lib/mo"      # shared storage for every role
    [log]
    replicas = 3                  # 0 = plain local WAL file
    [tn]
    port = 0                      # 0 = auto-assign
    [cn]
    count = 2
    insecure = true               # false = mo_user auth
    [keeper]
    enabled = true
    standby = true                # second keeper that takes over
    [proxy]
    enabled = true
    port = 0

Usage: `python -m matrixone_tpu.launch --launch cluster.toml` (stays in
the foreground like the reference binary; SIGTERM tears the tree down),
or programmatically: `Launcher(cfg_path).start() ... .stop()`.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
try:
    import tomllib                 # py311+
except ModuleNotFoundError:        # this image ships py310: use tomli
    import tomli as tomllib
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n: int) -> List[int]:
    """n distinct free ports: every probe socket stays open until all
    are allocated, or the kernel may hand a just-released port out
    twice."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class Launcher:
    def __init__(self, cfg_path: str):
        with open(cfg_path, "rb") as f:
            self.cfg = tomllib.load(f)
        self.data_dir = self.cfg["cluster"]["data_dir"]
        os.makedirs(self.data_dir, exist_ok=True)
        self.procs: List[subprocess.Popen] = []
        self.ports: Dict[str, object] = {}
        self.keepers = []          # in-process HAKeeper objects
        self.proxy = None

    # ------------------------------------------------------------ spawn
    def _launch(self, mod: str, args: List[str], role: str):
        """Start a child; stderr goes to a per-role log under data_dir
        (a child that dies pre-PORT must leave a diagnostic)."""
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        # [cluster] platform picks the backend for every role ("cpu" by
        # default; set "tpu"/"axon" for chip deployments). Forced, not
        # defaulted: the image's sitecustomize pre-seeds JAX_PLATFORMS
        # in the parent env and a wedged tunnel would hang children.
        platform = self.cfg["cluster"].get("platform", "cpu")
        env["JAX_PLATFORMS"] = platform
        if platform == "cpu":
            env["PALLAS_AXON_POOL_IPS"] = ""
        errlog = open(os.path.join(self.data_dir, f"{role}.stderr.log"),
                      "a")
        p = subprocess.Popen([sys.executable, "-m", mod] + args,
                             stdout=subprocess.PIPE, stderr=errlog,
                             env=env, text=True)
        errlog.close()               # the child holds its own fd now
        self.procs.append(p)
        return p

    @staticmethod
    def _collect_ports(p, mod: str, n_ports: int,
                       timeout_s: float = 180) -> List[int]:
        """Read the child's PORT lines under a REAL deadline: readline
        blocks, so it runs on a reaper thread joined with a timeout (a
        live-but-silent child must fail the launch, not hang it)."""
        got: List[int] = []

        def read():
            while len(got) < n_ports:
                line = p.stdout.readline()
                if not line:
                    return
                if line.startswith(("PORT ", "FRAGPORT ")):
                    got.append(int(line.split()[1]))
        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(timeout_s)
        if len(got) < n_ports:
            raise RuntimeError(f"{mod} did not report its ports "
                               f"(rc={p.poll()}; see its stderr log)")
        return got

    def _spawn(self, mod: str, args: List[str], role: str,
               n_ports: int = 1) -> List[int]:
        p = self._launch(mod, args, role)
        return self._collect_ports(p, mod, n_ports)

    def start(self) -> "Launcher":
        try:
            return self._start()
        except BaseException:       # noqa: BLE001 — incl.
            # KeyboardInterrupt mid-launch; re-raised after cleanup
            # a half-started cluster must not leak orphans holding the
            # ports and the data dir
            self.stop()
            raise

    def _start(self) -> "Launcher":
        # --- keepers first (services register as they come up)
        keeper_addrs = []
        if self.cfg.get("keeper", {}).get("enabled", False):
            from matrixone_tpu.hakeeper import HAKeeper
            state = os.path.join(self.data_dir, "keeper_state.json")

            def persist(snap, _p=state):
                # atomic: a crash mid-write must not corrupt membership
                # or the keeper-generation fencing state
                tmp = _p + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, _p)

            def restore(_p=state):
                if not os.path.exists(_p):
                    return None
                with open(_p) as f:
                    return json.load(f)
            primary = HAKeeper(persist=persist, restore=restore).start()
            self.keepers.append(primary)
            keeper_addrs.append(f"127.0.0.1:{primary.port}")
            if self.cfg["keeper"].get("standby", False):
                standby = HAKeeper(
                    persist=persist, restore=restore,
                    standby_of=("127.0.0.1", primary.port)).start()
                self.keepers.append(standby)
                keeper_addrs.append(f"127.0.0.1:{standby.port}")
            self.ports["keepers"] = [k.port for k in self.keepers]
        keeper_opt = (["--keeper", ",".join(keeper_addrs)]
                      if keeper_addrs else [])

        # --- log replicas (launch.go: log service first) — started in
        # parallel within the tier; ports collected afterwards so the
        # tier costs ~one child init, not the sum
        n_rep = int(self.cfg.get("log", {}).get("replicas", 0))
        rep_procs = [
            self._launch("matrixone_tpu.logservice.replicated",
                         ["--dir", os.path.join(self.data_dir, f"log{i}"),
                          "--port", "0"], f"log{i}")
            for i in range(n_rep)]
        log_addrs = [
            f"127.0.0.1:{self._collect_ports(p, 'log replica', 1)[0]}"
            for p in rep_procs]
        self.ports["log"] = log_addrs

        # --- TN
        tn_args = ["--dir", self.data_dir, "--port",
                   str(self.cfg.get("tn", {}).get("port", 0))]
        if log_addrs:
            tn_args += ["--log-replicas", ",".join(log_addrs)]
        (tn_port,) = self._spawn("matrixone_tpu.cluster.tn",
                                 tn_args + keeper_opt, "tn")
        self.ports["tn"] = tn_port

        # --- TN failover (VERDICT r4 Next #9; reference:
        # hakeeper/checkers/tnservice): when the keeper marks the TN
        # DOWN, its repair hook respawns a TN over the same storage ON
        # THE SAME PORT — CN RPC clients and logtail consumers
        # reconnect by themselves, so nothing needs repointing. With
        # log replicas, the successor acquires the quorum WAL via
        # ELECTION (--campaign): it only proceeds once the dead
        # writer's lease lapses, and the replay of the quorum log
        # guarantees no acked commit is lost.
        if self.keepers and self.cfg.get("tn", {}).get(
                "auto_restart", True):
            respawn_args = (["--dir", self.data_dir,
                             "--port", str(tn_port)]
                            + (["--log-replicas", ",".join(log_addrs),
                                "--campaign"] if log_addrs else [])
                            + keeper_opt)

            def _respawn(_args=respawn_args):
                try:
                    p_ = self._launch("matrixone_tpu.cluster.tn",
                                      _args, "tn-respawn")
                    self._collect_ports(p_, "tn respawn", 1)
                except Exception as e:     # noqa: BLE001 — repair is
                    import sys as _sys     # best-effort; keeper records
                    print(f"[launch] TN respawn failed: {e}",
                          file=_sys.stderr, flush=True)

            def tn_repair(rec):
                # detach: the hook runs on the keeper's tick thread —
                # a slow respawn (port contention, quiet child) must
                # not stall failure detection for every other service
                threading.Thread(target=_respawn, daemon=True).start()
            for k in self.keepers:
                k.on_down("tn", tn_repair)

        # --- CNs (fragment endpoints pre-allocated so every CN knows
        # the full peer set at spawn time; spawned in parallel)
        cn_cfg = self.cfg.get("cn", {})
        n_cn = int(cn_cfg.get("count", 1))
        insecure = "1" if cn_cfg.get("insecure", True) else "0"
        frag_ports = _free_ports(n_cn)
        peers = ",".join(f"127.0.0.1:{p}" for p in frag_ports)
        cn_procs = [
            self._launch(
                "matrixone_tpu.cluster.cn",
                ["--tn", f"127.0.0.1:{tn_port}", "--dir", self.data_dir,
                 "--port", "0", "--frag-port", str(frag_ports[i]),
                 "--peers", peers, "--insecure", insecure] + keeper_opt,
                f"cn{i}")
            for i in range(n_cn)]
        cn_ports = [self._collect_ports(p, "cn", 2)[0]
                    for p in cn_procs]
        self.ports["cn"] = cn_ports
        self.ports["frag"] = frag_ports

        # --- proxy over the CNs
        if self.cfg.get("proxy", {}).get("enabled", False):
            from matrixone_tpu.frontend.proxy import MOProxy
            self.proxy = MOProxy(
                [("127.0.0.1", p) for p in cn_ports],
                port=int(self.cfg["proxy"].get("port", 0))).start()
            self.ports["proxy"] = self.proxy.port

        with open(os.path.join(self.data_dir, "launch_ports.json"),
                  "w") as f:
            json.dump(self.ports, f)
        return self

    def stop(self) -> None:
        if self.proxy is not None:
            self.proxy.stop()
        for k in self.keepers:
            k.stop()
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()


def main() -> None:
    import argparse
    import signal
    ap = argparse.ArgumentParser(prog="matrixone_tpu.launch")
    ap.add_argument("--launch", required=True, help="cluster TOML file")
    args = ap.parse_args()
    launcher = Launcher(args.launch).start()
    print(json.dumps(launcher.ports), flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    launcher.stop()


if __name__ == "__main__":
    main()

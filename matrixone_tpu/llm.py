"""LLM SQL functions (reference: plan/function/func_builtin_llm.go +
pkg/monlp/llm): `llm_chat(prompt)` and `llm_embed(text)` call a
configured model endpoint from inside SQL.

Configuration (no endpoint -> a clear error, never a silent stub):
    SET llm_endpoint = 'http://host:port/path'   -- per session
    MO_LLM_ENDPOINT=...                          -- process default
    SET llm_embed_dim = 16                       -- embedding width

Protocol: one POST per distinct input with a JSON body
  {"op": "chat",  "prompt": "..."}  -> {"text": "..."}
  {"op": "embed", "text": "...", "dim": N} -> {"embedding": [floats]}
(An OpenAI-style gateway is a ~10-line adapter serving this shape.)

Evaluation cost model matches the other string functions: host work is
per DISTINCT dictionary entry, so `llm_chat(col)` over a million rows
with 50 distinct values makes 50 calls, and results gather on device.
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import List, Optional


class LLMError(RuntimeError):
    pass


def endpoint(variables: Optional[dict] = None) -> str:
    ep = None
    if variables:
        ep = variables.get("llm_endpoint")
    ep = ep or os.environ.get("MO_LLM_ENDPOINT")
    if not ep:
        raise LLMError(
            "no LLM endpoint configured: SET llm_endpoint = 'http://...'"
            " (or MO_LLM_ENDPOINT)")
    return str(ep)


def _post(ep: str, payload: dict, timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        ep, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception as e:               # noqa: BLE001
        raise LLMError(f"LLM endpoint {ep!r} failed: "
                       f"{type(e).__name__}: {e}") from None


#: process-level result cache: SQL evaluates string functions once per
#: DISTINCT dictionary entry, and the projection's dict derivation plus
#: the device eval both walk the dictionary — without a cache each
#: distinct prompt would hit the endpoint more than once per query
#: (and once more on every later query). Keyed by endpoint so a
#: reconfigured session never serves another model's answers.
_CACHE: dict = {}
_CACHE_MAX = 4096


def _cached(key, fn):
    if key in _CACHE:
        return _CACHE[key]
    val = fn()
    if len(_CACHE) >= _CACHE_MAX:
        _CACHE.pop(next(iter(_CACHE)))
    _CACHE[key] = val
    return val


def chat(prompt: str, variables: Optional[dict] = None) -> str:
    ep = endpoint(variables)

    def call():
        resp = _post(ep, {"op": "chat", "prompt": prompt})
        if "text" not in resp:
            raise LLMError(f"LLM endpoint returned no 'text': {resp}")
        return str(resp["text"])
    return _cached(("chat", ep, prompt), call)


def embed(text: str, dim: int,
          variables: Optional[dict] = None) -> List[float]:
    ep = endpoint(variables)

    def call():
        resp = _post(ep, {"op": "embed", "text": text, "dim": dim})
        vec = resp.get("embedding")
        if not isinstance(vec, list) or len(vec) != dim:
            raise LLMError(
                f"LLM endpoint returned a bad embedding (want {dim} "
                f"floats, got {type(vec).__name__}"
                f"{f' of {len(vec)}' if isinstance(vec, list) else ''})")
        return [float(x) for x in vec]
    return _cached(("embed", ep, dim, text), call)

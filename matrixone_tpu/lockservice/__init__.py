"""Pessimistic lock service with deadlock detection.

Reference analogue: `pkg/lockservice` (34k LoC — lock tables allocated per
table, row/range locks, distributed deadlock detection `deadlock.go`,
orphan GC), collapsed to the single-service form: an in-process lock table
keyed by (table, row), shared/exclusive modes, and a wait-for graph
checked for cycles before every block — the waiter whose edge completes a
cycle aborts (`DeadlockError`), matching the reference's kill-the-latecomer
policy. Each row lock keeps a FIFO waiter queue (reference: per-lock
queues in lockservice/lock.go): a new request must not barge past earlier
waiters, so an exclusive waiter cannot starve under sustained shared
traffic; wait-for edges include earlier queued waiters, keeping deadlock
detection sound under queue ordering.
"""

from __future__ import annotations

import threading

from matrixone_tpu.utils import san
import time
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

SHARED = "shared"
EXCLUSIVE = "exclusive"


class DeadlockError(RuntimeError):
    pass


class LockTimeoutError(RuntimeError):
    pass


class _RowLock:
    __slots__ = ("owners", "mode", "waiters")

    def __init__(self):
        self.owners: Set[int] = set()
        self.mode: Optional[str] = None
        self.waiters: List[Tuple[int, str]] = []   # FIFO arrival order


class LockService:
    def __init__(self):
        self._locks: Dict[Tuple[str, int], _RowLock] = {}
        self._held: Dict[int, Set[Tuple[str, int]]] = defaultdict(set)
        #: waiter txn -> (key, mode) it is currently blocked on; wait-for
        #: edges are DERIVED fresh at cycle-check time (stored edge sets go
        #: stale the moment an owner releases, producing false deadlocks)
        self._waiting_on: Dict[int, Tuple[Tuple[str, int], str]] = {}
        self._cond = san.condition("LockService._cond")

    # ------------------------------------------------------------- locking
    def lock(self, txn_id: int, table: str, rows, mode: str = EXCLUSIVE,
             timeout: float = 10.0) -> None:
        """Acquire locks on every row (all-or-block, row at a time in
        sorted order — ordered acquisition limits livelock)."""
        for row in sorted(int(r) for r in rows):
            self._lock_one(txn_id, (table, row), mode, timeout)

    def _compatible(self, lk: _RowLock, txn_id: int, mode: str) -> bool:
        if not lk.owners or lk.owners == {txn_id}:
            return True
        if mode == SHARED and lk.mode == SHARED:
            return True
        return False

    def _grantable(self, lk: _RowLock, txn_id: int, mode: str) -> bool:
        """Owner-compatible AND FIFO-fair: no barging past earlier waiters
        (two shared requests may be granted together)."""
        if lk.owners == {txn_id}:
            return True             # re-entrant / upgrade fast path
        if txn_id in lk.owners and mode == SHARED and lk.mode == SHARED:
            return True             # re-reading a shared hold must never
                                    # queue behind (or deadlock on) waiters
        if not self._compatible(lk, txn_id, mode):
            return False
        for t, m in lk.waiters:
            if t == txn_id:
                return True         # nothing ahead of us blocks
            if m == EXCLUSIVE or mode == EXCLUSIVE:
                return False        # would barge past an earlier waiter
        return True

    def _blockers(self, lk: _RowLock, txn_id: int, mode: str) -> Set[int]:
        out = set(lk.owners) - {txn_id}
        for t, m in lk.waiters:     # earlier waiters we queue behind
            if t == txn_id:
                break
            if m == EXCLUSIVE or mode == EXCLUSIVE:
                out.add(t)
        return out

    def _lock_one(self, txn_id: int, key, mode: str, timeout: float):
        deadline = time.monotonic() + timeout
        with self._cond:
            lk = self._locks.setdefault(key, _RowLock())
            ticket = (txn_id, mode)
            lk.waiters.append(ticket)
            try:
                while not self._grantable(lk, txn_id, mode):
                    self._waiting_on[txn_id] = (key, mode)
                    if self._creates_cycle(txn_id):
                        raise DeadlockError(
                            f"txn {txn_id} would deadlock on {key}")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(
                            timeout=remaining):
                        raise LockTimeoutError(
                            f"txn {txn_id} timed out on {key}")
                    # lk object identity is stable: our queued ticket keeps
                    # it alive in _locks (unlock_all only deletes entries
                    # with no owners AND no waiters)
            except BaseException:   # noqa: BLE001 — waiter-ticket
                # cleanup (incl. KeyboardInterrupt): a leaked ticket
                # deadlocks every later acquirer; always re-raised
                try:
                    lk.waiters.remove(ticket)
                except ValueError:
                    pass
                self._waiting_on.pop(txn_id, None)
                if not lk.owners and not lk.waiters:
                    self._locks.pop(key, None)
                self._cond.notify_all()   # our slot freed: re-evaluate
                raise
            lk.waiters.remove(ticket)
            self._waiting_on.pop(txn_id, None)
            lk.owners.add(txn_id)
            if mode == EXCLUSIVE or lk.mode is None:
                lk.mode = mode      # never downgrades an EXCLUSIVE hold
            self._held[txn_id].add(key)
            self._cond.notify_all()   # shared co-grants may now proceed

    def _edges(self, txn: int) -> Set[int]:
        """Current blockers of a waiting txn, derived from live lock
        state (owners + earlier queued waiters)."""
        w = self._waiting_on.get(txn)
        if w is None:
            return set()
        key, mode = w
        lk = self._locks.get(key)
        if lk is None:
            return set()
        return self._blockers(lk, txn, mode)

    def _creates_cycle(self, start: int) -> bool:
        """DFS over the DERIVED wait-for graph from start back to start."""
        seen = set()
        stack = list(self._edges(start))
        while stack:
            t = stack.pop()
            if t == start:
                return True
            if t in seen:
                continue
            seen.add(t)
            stack.extend(self._edges(t))
        return False

    # ------------------------------------------------------------ release
    def unlock_all(self, txn_id: int) -> None:
        with self._cond:
            for key in self._held.pop(txn_id, set()):
                lk = self._locks.get(key)
                if lk is None:
                    continue
                lk.owners.discard(txn_id)
                if not lk.owners and not lk.waiters:
                    del self._locks[key]
            self._waiting_on.pop(txn_id, None)
            self._cond.notify_all()

    # ------------------------------------------------------------- status
    def held_by(self, txn_id: int) -> Set[Tuple[str, int]]:
        with self._cond:
            return set(self._held.get(txn_id, ()))

    def n_locks(self) -> int:
        with self._cond:
            return len(self._locks)

"""Pessimistic lock service with deadlock detection.

Reference analogue: `pkg/lockservice` (34k LoC — lock tables allocated per
table, row/range locks, distributed deadlock detection `deadlock.go`,
orphan GC), collapsed to the single-service form: an in-process lock table
keyed by (table, row), shared/exclusive modes, and a wait-for graph
checked for cycles before every block — the waiter whose edge completes a
cycle aborts (`DeadlockError`), matching the reference's kill-the-latecomer
policy. Wakeups race on a shared condition (no fairness queue yet): an
exclusive waiter can starve under sustained shared traffic — the
reference's per-lock FIFO queue is the planned refinement.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

SHARED = "shared"
EXCLUSIVE = "exclusive"


class DeadlockError(RuntimeError):
    pass


class LockTimeoutError(RuntimeError):
    pass


class _RowLock:
    __slots__ = ("owners", "mode")

    def __init__(self):
        self.owners: Set[int] = set()
        self.mode: Optional[str] = None


class LockService:
    def __init__(self):
        self._locks: Dict[Tuple[str, int], _RowLock] = {}
        self._held: Dict[int, Set[Tuple[str, int]]] = defaultdict(set)
        #: waiter txn -> owner txns it is blocked on (wait-for graph)
        self._waits: Dict[int, Set[int]] = {}
        self._cond = threading.Condition()

    # ------------------------------------------------------------- locking
    def lock(self, txn_id: int, table: str, rows, mode: str = EXCLUSIVE,
             timeout: float = 10.0) -> None:
        """Acquire locks on every row (all-or-block, row at a time in
        sorted order — ordered acquisition limits livelock)."""
        for row in sorted(int(r) for r in rows):
            self._lock_one(txn_id, (table, row), mode, timeout)

    def _compatible(self, lk: _RowLock, txn_id: int, mode: str) -> bool:
        if not lk.owners or lk.owners == {txn_id}:
            return True
        if mode == SHARED and lk.mode == SHARED:
            return True
        return False

    def _lock_one(self, txn_id: int, key, mode: str, timeout: float):
        deadline = time.monotonic() + timeout
        with self._cond:
            lk = self._locks.setdefault(key, _RowLock())
            while not self._compatible(lk, txn_id, mode):
                blockers = lk.owners - {txn_id}
                self._waits[txn_id] = set(blockers)
                if self._creates_cycle(txn_id):
                    self._waits.pop(txn_id, None)
                    self._cond.notify_all()
                    raise DeadlockError(
                        f"txn {txn_id} would deadlock on {key}")
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    self._waits.pop(txn_id, None)
                    raise LockTimeoutError(f"txn {txn_id} timed out on {key}")
                lk = self._locks.setdefault(key, _RowLock())
            self._waits.pop(txn_id, None)
            lk.owners.add(txn_id)
            if mode == EXCLUSIVE or lk.mode is None:
                lk.mode = mode      # never downgrades an EXCLUSIVE hold
            self._held[txn_id].add(key)

    def _creates_cycle(self, start: int) -> bool:
        """DFS over the wait-for graph from start's blockers back to start."""
        seen = set()
        stack = list(self._waits.get(start, ()))
        while stack:
            t = stack.pop()
            if t == start:
                return True
            if t in seen:
                continue
            seen.add(t)
            stack.extend(self._waits.get(t, ()))
        return False

    # ------------------------------------------------------------ release
    def unlock_all(self, txn_id: int) -> None:
        with self._cond:
            for key in self._held.pop(txn_id, set()):
                lk = self._locks.get(key)
                if lk is None:
                    continue
                lk.owners.discard(txn_id)
                if not lk.owners:
                    del self._locks[key]
            self._waits.pop(txn_id, None)
            self._cond.notify_all()

    # ------------------------------------------------------------- status
    def held_by(self, txn_id: int) -> Set[Tuple[str, int]]:
        with self._cond:
            return set(self._held.get(txn_id, ()))

    def n_locks(self) -> int:
        with self._cond:
            return len(self._locks)

"""Replicated WAL: 3 log-replica processes + a quorum append client.

Reference analogue: `pkg/logservice` (dragonboat Raft WAL shards,
store.go:171) — re-designed to the minimum that gives the same durability
contract for this engine's single-writer TN role:

  * each replica is its own PROCESS owning an append-only frame file;
  * the engine (sole writer, like the reference TN) appends with a
    monotonically increasing (epoch, seq); an append is durable once a
    MAJORITY of replicas ack — losing any minority loses nothing;
  * writer restart: epoch := max(replica epochs) + 1 fences any stale
    writer (replicas reject appends from older epochs — the
    view-change half of viewstamped replication); recovery reads a
    majority and takes the seq-union, which must contain every
    majority-acked entry (any 2-of-3 overlap with every ack set);
    single-writer sequencing means union-dedupe is conflict-free, so no
    leader election or log repair pass is needed (the full Raft state
    machine collapses under the one-writer assumption).

Wire protocol (length-prefixed, JSON + raw blob):
    u32 header_len | header_json | u32 blob_len | blob
Ops: hello(epoch) | append(epoch, seq) | read | truncate(epoch, upto) |
elect(writer, epoch, lease_s) | renew(writer, epoch, lease_s) |
ping | stop.

Leader election (VERDICT r4 Missing #3 / Next #3 — reference:
dragonboat Raft leadership, store.go:171): replicas additionally grant a
WRITER LEASE. A candidate wins by quorum `elect` with a higher epoch,
which replicas refuse while another writer's lease is live — so a
standby cannot fence a healthy primary out mid-stream (the raw
`hello` takeover stays available for operator-forced recovery and
single-writer restarts). The elected writer renews its lease in the
background; when it dies, leases expire and the next `campaign()` wins.
Freshness is by construction: every new writer first reads a majority
and repairs (the VR view-change's log-merge), so the new view contains
every majority-acked entry.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading

from matrixone_tpu.utils import san
from matrixone_tpu.utils.lifecycle import ServiceThreads
from typing import Dict, Iterator, List, Optional, Tuple


def _send_msg(sock: socket.socket, header: dict, blob: bytes = b"") -> None:
    # mosan choke point: every fabric lane frames through here — a send
    # while holding the commit lock or a cache lock is a stall bug
    san.check_blocking("socket.send")
    hj = json.dumps(header).encode()
    sock.sendall(struct.pack("<I", len(hj)) + hj
                 + struct.pack("<I", len(blob)) + blob)


def _recv_n(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf += part
    return buf


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    san.check_blocking("socket.recv")
    (hlen,) = struct.unpack("<I", _recv_n(sock, 4))
    header = json.loads(_recv_n(sock, hlen).decode())
    (blen,) = struct.unpack("<I", _recv_n(sock, 4))
    return header, _recv_n(sock, blen) if blen else b""


_REC = struct.Struct("<QQI")       # epoch, seq, payload_len


class ReplicaCore:
    """One replica's durable state machine, decoupled from the socket
    service so the crash harness (tools/mocrash) can drive it over a
    RecordingFileService and reopen it from any materialized crash
    state.  All I/O rides a FileService: the append path is durable-on-
    return (fs.append fsyncs) and BOTH metadata writes — epoch/watermark
    and the truncation rewrite — are atomic replaces (the old in-place
    `replica.meta` write could tear, corrupting the epoch fence after a
    crash; mocrash write-path audit)."""

    LOG = "replica.log"
    META = "replica.meta"

    def __init__(self, fs):
        self.fs = fs
        self.epoch = 0
        #: low watermark: entries at or below this seq were truncated by
        #: a checkpoint — a rejoining laggard's stale copies of them must
        #: never resurrect (repair/replay honor max watermark)
        self.truncated_upto = 0
        self.entries: Dict[int, Tuple[int, bytes]] = {}  # seq -> (epoch, payload)
        self.torn_bytes = 0
        self._load()

    def _load(self) -> None:
        if self.fs.exists(self.META):
            parts = (self.fs.read(self.META).decode().strip()
                     or "0").split()
            self.epoch = int(parts[0])
            self.truncated_upto = int(parts[1]) if len(parts) > 1 else 0
        if not self.fs.exists(self.LOG):
            return
        blob = self.fs.read(self.LOG)
        off = 0
        while off + _REC.size <= len(blob):
            epoch, seq, plen = _REC.unpack_from(blob, off)
            if off + _REC.size + plen > len(blob):
                break                  # torn tail
            payload = blob[off + _REC.size:off + _REC.size + plen]
            self.entries[seq] = (epoch, payload)
            off += _REC.size + plen
        self.torn_bytes = len(blob) - off

    def persist_meta(self) -> None:
        self.fs.write(self.META,
                      f"{self.epoch} {self.truncated_upto}".encode())

    def append(self, epoch: int, seq: int, payload: bytes) -> dict:
        if epoch < self.epoch:
            return {"ok": False,
                    "err": f"stale epoch {epoch} < {self.epoch}"}
        if epoch > self.epoch:
            self.epoch = epoch
            self.persist_meta()
        self.entries[seq] = (epoch, payload)
        self.fs.append(self.LOG,
                       _REC.pack(epoch, seq, len(payload)) + payload)
        return {"ok": True}

    def truncate(self, epoch: int, upto: int) -> dict:
        if epoch < self.epoch:
            return {"ok": False, "err": "stale epoch"}
        self.entries = {s: v for s, v in self.entries.items()
                        if s > upto}
        self.truncated_upto = max(self.truncated_upto, upto)
        self.persist_meta()
        self.fs.write(self.LOG, b"".join(
            _REC.pack(e, s, len(p)) + p
            for s, (e, p) in sorted(self.entries.items())))
        return {"ok": True}

    def read_blob(self) -> bytes:
        return b"".join(
            _REC.pack(self.entries[s][0], s, len(self.entries[s][1]))
            + self.entries[s][1] for s in sorted(self.entries))


def merge_majority(reads: List[Tuple[int, Dict[int, bytes]]]
                   ) -> Tuple[int, Dict[int, bytes]]:
    """Union a set of replica reads past the highest truncation
    watermark — THE quorum recovery rule (single-writer sequencing
    makes the union conflict-free; any majority overlaps every ack
    set, so the union of any majority contains every acked entry;
    entries at or below a truncation watermark never resurrect).
    Shared by ReplicatedLog's repair/replay and the mocrash quorum
    scenario so the recovery contract cannot drift from the checker.
    `reads`: [(truncated_upto, {seq: payload})]."""
    upto = max((u for u, _e in reads), default=0)
    merged: Dict[int, bytes] = {}
    for _u, entries in reads:
        for s, payload in entries.items():
            if s > upto:
                merged[s] = payload
    return upto, merged


class LogReplica:
    """One log replica: append-only frame file + TCP service (the
    durable state machine lives in ReplicaCore)."""

    def __init__(self, data_dir: str, port: int = 0, fs=None):
        from matrixone_tpu.storage.fileservice import LocalFS
        os.makedirs(data_dir, exist_ok=True)
        self.core = ReplicaCore(fs if fs is not None
                                else LocalFS(data_dir))
        #: writer lease (election): volatile by design — a replica
        #: restart forgets the lease (grace only shrinks; epochs still
        #: fence), it never extends a dead writer's tenure
        self.writer_id: Optional[str] = None
        self.lease_expires = 0.0
        self._lock = san.lock("LogReplica._lock")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        self._stopping = threading.Event()
        self._svc = ServiceThreads("mo-log")

    # core views (the handler + tests read these)
    @property
    def epoch(self) -> int:
        return self.core.epoch

    @epoch.setter
    def epoch(self, v: int) -> None:
        self.core.epoch = v

    @property
    def truncated_upto(self) -> int:
        return self.core.truncated_upto

    @property
    def entries(self) -> Dict[int, Tuple[int, bytes]]:
        return self.core.entries

    def _persist_epoch(self) -> None:
        self.core.persist_meta()

    def _append(self, epoch: int, seq: int, payload: bytes) -> dict:
        with self._lock:
            return self.core.append(epoch, seq, payload)

    def _elect(self, writer: str, epoch: int, lease_s: float) -> dict:
        """VOTE for a candidate: grant iff the proposed epoch advances
        AND no OTHER writer holds a live lease. A vote only RESERVES the
        lease — it does NOT bump the persisted epoch. Epochs move when
        the quorum winner sends hello; this two-phase split (Raft
        prevote's purpose) means a minority campaign — e.g. one replica
        restarted and forgot the primary's lease — cannot fence that
        replica against the healthy primary's appends."""
        import time as _t
        with self._lock:
            now = _t.monotonic()
            if epoch <= self.epoch:
                return {"ok": False, "err": "stale epoch",
                        "epoch": self.epoch}
            if (self.writer_id not in (None, writer)
                    and now < self.lease_expires):
                return {"ok": False, "err": "lease held",
                        "holder": self.writer_id,
                        "expires_in": round(self.lease_expires - now, 3)}
            self.writer_id = writer
            self.lease_expires = now + lease_s
            return {"ok": True, "epoch": self.epoch}

    def _renew(self, writer: str, epoch: int, lease_s: float) -> dict:
        """Extend (or re-capture) the writer lease. An expired or vacant
        lease is adoptable by any writer at a current epoch — that is
        how a healthy primary re-captures a restarted replica that
        briefly voted for a losing candidate."""
        import time as _t
        with self._lock:
            now = _t.monotonic()
            if epoch < self.epoch:
                return {"ok": False, "err": "stale epoch"}
            if (self.writer_id not in (None, writer)
                    and now < self.lease_expires):
                return {"ok": False, "err": "not the lease holder"}
            self.writer_id = writer
            self.lease_expires = now + lease_s
            return {"ok": True}

    def _truncate(self, epoch: int, upto: int) -> dict:
        with self._lock:
            return self.core.truncate(epoch, upto)

    def serve_forever(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._svc.spawn_handler(self._handle, conn)

    def start(self) -> "LogReplica":
        self._svc.spawn_accept(self.serve_forever)
        return self

    def stop(self) -> None:
        self._stopping.set()
        # a stopped replica must look DEAD to connected writers, like a
        # killed process would: ServiceThreads shuts down the listener +
        # every tracked conn (interrupting blocked accept/recv) and
        # joins the accept loop + handlers with a deadline
        self._svc.shutdown(self._sock)

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                header, blob = _recv_msg(conn)
                op = header.get("op")
                if op == "append":
                    _send_msg(conn, self._append(header["epoch"],
                                                 header["seq"], blob))
                elif op == "read":
                    with self._lock:
                        out = self.core.read_blob()
                        n = len(self.core.entries)
                    _send_msg(conn, {"ok": True, "epoch": self.epoch,
                                     "upto": self.truncated_upto,
                                     "n": n}, out)
                elif op == "hello":
                    with self._lock:
                        if header["epoch"] > self.epoch:
                            self.epoch = header["epoch"]
                            self._persist_epoch()
                        _send_msg(conn, {"ok": True, "epoch": self.epoch})
                elif op == "truncate":
                    _send_msg(conn, self._truncate(header["epoch"],
                                                   header["upto"]))
                elif op == "elect":
                    _send_msg(conn, self._elect(header["writer"],
                                                header["epoch"],
                                                header.get("lease_s", 2.0)))
                elif op == "renew":
                    _send_msg(conn, self._renew(header["writer"],
                                                header["epoch"],
                                                header.get("lease_s", 2.0)))
                elif op == "ping":
                    _send_msg(conn, {"ok": True, "epoch": self.epoch})
                elif op == "stop":
                    _send_msg(conn, {"ok": True})
                    os._exit(0)        # hard-kill path for tests
                else:
                    _send_msg(conn, {"ok": False, "err": f"bad op {op}"})
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class NotLeader(ConnectionError):
    """Campaign lost: another writer's lease is still live."""


class ReplicatedLog:
    """Quorum append client — the engine's WAL when the log role runs as
    separate replica processes. Drop-in for storage.wal.WalWriter
    (append/truncate/replay).

    Two acquisition modes:
      * default (compat / operator-forced): unconditional takeover via
        hello(max_epoch + 1) — any new writer instantly fences the old;
      * campaign=True (election): quorum `elect` that replicas REFUSE
        while another writer's lease is live — a standby polling with
        campaign() only wins after the primary actually stops renewing
        (dragonboat leader-lease semantics). The winner renews in the
        background for its lifetime.
    """

    def __init__(self, addrs: List[Tuple[str, int]],
                 quorum: Optional[int] = None, timeout: float = 5.0,
                 writer_id: Optional[str] = None,
                 campaign: bool = False, lease_s: float = 2.0):
        import uuid
        self.addrs = list(addrs)
        self.quorum = quorum or (len(addrs) // 2 + 1)
        self.timeout = timeout
        self.writer_id = writer_id or f"w-{uuid.uuid4().hex[:8]}"
        self.lease_s = lease_s
        self._renew_stop = threading.Event()
        # the renew thread and the append/replay caller share the
        # per-replica sockets: without serialization their
        # request/response frames would cross and an append could read
        # a renew reply as its (non-)ack
        self._io_lock = san.lock("ReplicatedLog._io_lock")
        self._socks: Dict[int, Optional[socket.socket]] = {}
        self.seq = 0
        # fence any previous writer: adopt max(epochs) + 1
        epochs = []
        for i in range(len(self.addrs)):
            r = self._call(i, {"op": "ping"})
            if r is not None:
                epochs.append(r[0].get("epoch", 0))
        if len(epochs) < self.quorum:
            raise ConnectionError(
                f"only {len(epochs)}/{len(self.addrs)} log replicas "
                f"reachable; need {self.quorum}")
        self.epoch = max(epochs) + 1
        if campaign:
            # phase 1: gather votes (lease reservations; epochs untouched)
            grants, refusals = 0, []
            for i in range(len(self.addrs)):
                r = self._call(i, {"op": "elect", "writer": self.writer_id,
                                   "epoch": self.epoch,
                                   "lease_s": lease_s})
                if r is not None and r[0].get("ok"):
                    grants += 1
                elif r is not None:
                    refusals.append(r[0])
            if grants < self.quorum:
                raise NotLeader(
                    f"campaign lost: {grants} grants < quorum "
                    f"{self.quorum} ({refusals})")
            # phase 2: quorum won — NOW adopt the epoch everywhere
            # reachable (laggards adopt it on their first append)
            for i in range(len(self.addrs)):
                self._call(i, {"op": "hello", "epoch": self.epoch})
            self._renew_thread = threading.Thread(
                target=self._renew_loop, daemon=True,
                name="mo-log-renew")
            self._renew_thread.start()
        else:
            for i in range(len(self.addrs)):
                self._call(i, {"op": "hello", "epoch": self.epoch})
        # resume seq past anything already logged, and REPAIR divergent
        # replicas: a replica that missed appends while down rejoins by
        # receiving the union's missing entries under the new epoch (the
        # log-repair half of a Raft leader bringing a follower up to
        # date). The truncation watermark guards the other divergence
        # direction: a laggard that missed a checkpoint truncate must
        # have its stale pre-checkpoint entries dropped, never pushed
        # back onto healthy replicas.
        reads = self._read_majority()
        upto, merged = merge_majority(
            [(u, dict(entries)) for _i, u, entries in reads])
        self.seq = max(merged) if merged else upto
        for i, rep_upto, entries in reads:
            have = {s for s, _ in entries}
            for s in sorted(set(merged) - have):
                self._call(i, {"op": "append", "epoch": self.epoch,
                               "seq": s}, merged[s])
            if rep_upto < upto:
                # propagate the checkpoint truncation the laggard missed
                self._call(i, {"op": "truncate", "epoch": self.epoch,
                               "upto": upto})

    def _renew_loop(self) -> None:
        """Extend the writer lease at lease/3 cadence; stops on close().
        Losing renewals does NOT stop appends (epochs still protect
        correctness) — the lease only delays rival campaigns."""
        while not self._renew_stop.wait(self.lease_s / 3.0):
            for i in range(len(self.addrs)):
                self._call(i, {"op": "renew", "writer": self.writer_id,
                               "epoch": self.epoch,
                               "lease_s": self.lease_s})

    @classmethod
    def campaign_until_elected(cls, addrs, timeout: float = 30.0,
                               poll_s: float = 0.25, **kwargs
                               ) -> "ReplicatedLog":
        """Standby loop: poll-campaign until the primary's lease lapses
        (the automatic-successor half the VERDICT asked for)."""
        import time as _t

        from matrixone_tpu.cluster.rpc import backoff_delay
        deadline = _t.monotonic() + timeout
        last: Exception = NotLeader("never campaigned")
        attempt = 0
        while _t.monotonic() < deadline:
            try:
                return cls(addrs, campaign=True, **kwargs)
            except NotLeader as e:
                last = e
            except ConnectionError as e:
                last = e
            # jittered, growing poll: rival standbys campaigning in
            # lockstep re-collide on every lease check; never sleep
            # past the election deadline
            attempt += 1
            _t.sleep(max(0.0, min(max(poll_s, backoff_delay(attempt)),
                                  deadline - _t.monotonic())))
        raise last

    # ---- transport
    def _sock_for(self, i: int) -> Optional[socket.socket]:
        s = self._socks.get(i)
        if s is not None:
            return s
        try:
            s = socket.create_connection(self.addrs[i], timeout=self.timeout)
            s.settimeout(self.timeout)
            self._socks[i] = s
            return s
        except OSError:
            self._socks[i] = None
            return None

    def _call(self, i: int, header: dict, blob: bytes = b""):
        with self._io_lock:
            s = self._sock_for(i)
            if s is None:
                return None
            try:
                # an enclosing deadline (e.g. a TN handler re-entered
                # the CN's remaining budget) caps this replica's I/O:
                # nested calls never outlive the caller's deadline
                from matrixone_tpu.cluster.rpc import current_deadline
                dl = current_deadline()
                if dl is not None:
                    rem = dl.remaining()
                    if rem <= 0:
                        return None     # caller's budget is gone
                    s.settimeout(max(0.001, min(self.timeout, rem)))
                else:
                    s.settimeout(self.timeout)
                _send_msg(s, header, blob)
                return _recv_msg(s)
            except (OSError, ConnectionError):
                try:
                    s.close()
                except OSError:
                    pass
                self._socks[i] = None
                return None

    # ---- WalWriter interface
    def append(self, header: dict, arrow_blob: bytes = b"") -> None:
        from matrixone_tpu.utils.fault import INJECTOR
        if INJECTOR.trigger("wal.append") == "fail":
            raise ConnectionError("fault injected: wal.append failed")
        hj = json.dumps(header).encode()
        payload = struct.pack("<I", len(hj)) + hj + arrow_blob
        self.seq += 1
        acks = 0
        errs = []
        # WAL-then-apply under ONE commit critical section IS the commit
        # protocol (same exemption molint's lock-discipline makes by
        # omitting wal.append from its denylist); the quorum I/O is
        # bounded by the deadline conventions in _call
        with san.allow_blocking("wal.append quorum round is the commit "
                                "protocol under the commit lock"):
            for i in range(len(self.addrs)):
                r = self._call(i, {"op": "append", "epoch": self.epoch,
                                   "seq": self.seq}, payload)
                if r is not None and r[0].get("ok"):
                    acks += 1
                elif r is not None:
                    errs.append(r[0].get("err"))
        if acks < self.quorum:
            raise ConnectionError(
                f"WAL append seq={self.seq}: {acks} acks < quorum "
                f"{self.quorum} ({errs})")

    def truncate(self) -> None:
        for i in range(len(self.addrs)):
            self._call(i, {"op": "truncate", "epoch": self.epoch,
                           "upto": self.seq})

    def _read_majority(self):
        """[(replica_idx, truncated_upto, [(seq, payload)])] from >=
        quorum replicas."""
        out = []
        for i in range(len(self.addrs)):
            r = self._call(i, {"op": "read"})
            if r is None or not r[0].get("ok"):
                continue
            blob = r[1]
            entries, off = [], 0
            while off + _REC.size <= len(blob):
                _e, seq, plen = _REC.unpack_from(blob, off)
                entries.append((seq, blob[off + _REC.size:
                                          off + _REC.size + plen]))
                off += _REC.size + plen
            out.append((i, r[0].get("upto", 0), entries))
        if len(out) < self.quorum:
            raise ConnectionError(
                f"{len(out)} replicas readable < quorum {self.quorum}")
        return out

    def replay(self, stats: Optional[dict] = None
               ) -> Iterator[Tuple[dict, bytes]]:
        """Union of a majority's entries past the highest truncation
        watermark, seq-ordered (single-writer: union is conflict-free;
        contains every majority-acked entry; never resurrects
        checkpoint-truncated ones).  Per-replica torn tails are already
        dropped at ReplicaCore load; `stats` reports frames only."""
        reads = self._read_majority()
        _upto, merged = merge_majority(
            [(u, dict(entries)) for _i, u, entries in reads])
        if stats is not None:
            stats.update(frames=len(merged), torn_bytes=0,
                         bytes=sum(len(p) for p in merged.values()))
        for seq in sorted(merged):
            payload = merged[seq]
            (hlen,) = struct.unpack_from("<I", payload, 0)
            header = json.loads(payload[4:4 + hlen].decode())
            yield header, payload[4 + hlen:]

    def close(self) -> None:
        self._renew_stop.set()
        for s in self._socks.values():
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        t = getattr(self, "_renew_thread", None)
        if t is not None:
            # wakes from Event.wait within lease_s/3; join, don't abandon
            t.join(timeout=5)


def main() -> None:          # replica process entry
    import argparse
    import sys
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    rep = LogReplica(args.dir, args.port)
    print(f"PORT {rep.port}", flush=True)
    sys.stdout.flush()
    rep.serve_forever()


if __name__ == "__main__":
    main()

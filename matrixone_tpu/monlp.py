"""CJK word segmentation (reference: pkg/monlp/tokenizer/jieba.go:161 —
the cgo jieba tokenizer + dictionaries feeding fulltext indexing).

Redesign, not a port: a dictionary-driven bidirectional maximum-match
segmenter in pure host Python. Forward and backward maximum matching
both run; on disagreement the segmentation with fewer words (then fewer
single-character tokens) wins — the classic MM disambiguation rule,
which resolves the standard overlap ambiguities without jieba's HMM.
Unknown spans (not in the dictionary) stay as single characters for
`cut`, and become character bigrams in the fulltext tokenizer wrapper
(recall-preserving fallback, same as the pre-dictionary behavior).

The embedded lexicon covers frequent everyday + database-domain words;
`load_dict` extends it from a jieba-format file ("word[ freq]" lines).
"""

from __future__ import annotations

from typing import Iterable, List, Set

# frequent everyday words + database/tech domain vocabulary
_EMBEDDED = """
我们 你们 他们 她们 自己 大家 什么 怎么 为什么 哪里 这个 那个 这些 那些
今天 明天 昨天 现在 时间 时候 以后 以前 已经 马上 永远 刚才
可以 不能 应该 必须 需要 希望 喜欢 知道 认为 觉得 发现 开始 结束 继续
因为 所以 但是 如果 虽然 而且 或者 并且 然后 还是 不过 只要 只有
工作 学习 生活 问题 方法 办法 事情 东西 地方 世界 国家 社会 文化 历史
经济 政治 政府 公司 企业 市场 产品 服务 客户 用户 朋友 老师 学生 孩子
中国 美国 日本 德国 法国 英国 北京 上海 广州 深圳 香港 台湾
电话 手机 电脑 计算机 网络 互联网 网站 软件 硬件 程序 代码 开发 设计
测试 调试 发布 部署 运行 性能 优化 安全 加密 压缩
数据 数据库 数据表 查询 搜索 索引 向量 矩阵 张量 模型 训练 推理
分布式 存储 计算 内存 磁盘 文件 文件系统 日志 事务 提交 回滚 快照
分区 分片 集群 节点 副本 主节点 从节点 检查点 恢复 备份 容灾 高可用
吞吐 延迟 并发 一致性 隔离 锁 死锁 调度 队列 缓存 命中
天气 下雨 下雪 太阳 月亮 星星 地球 海洋 高山 河流 森林 动物 植物
吃饭 喝水 睡觉 起床 上班 下班 上学 放学 开会 出差 旅游 运动 跑步
飞机 火车 汽车 地铁 公交 自行车 司机 乘客 车站 机场
医院 医生 护士 病人 药品 健康 银行 超市 商店 餐厅 饭店 学校 大学
快乐 高兴 难过 生气 担心 害怕 奇怪 重要 容易 困难 简单 复杂 方便
非常 特别 比较 可能 一定 当然 其实 真的 大概 差不多
""".split()


class Segmenter:
    def __init__(self, words: Iterable[str] = ()):
        self.words: Set[str] = set(_EMBEDDED)
        self.words.update(w for w in words if w)
        self.max_len = max((len(w) for w in self.words), default=1)

    def add_words(self, words: Iterable[str]) -> None:
        for w in words:
            w = w.strip()
            if w:
                self.words.add(w)
                self.max_len = max(self.max_len, len(w))

    def load_dict(self, path: str) -> int:
        """jieba-format dictionary: one "word [freq [tag]]" per line."""
        n = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                w = line.split()[0] if line.split() else ""
                if w:
                    self.add_words([w])
                    n += 1
        return n

    # ------------------------------------------------------------- MM
    def _fmm(self, text: str) -> List[str]:
        out, i, n = [], 0, len(text)
        while i < n:
            for ln in range(min(self.max_len, n - i), 1, -1):
                if text[i:i + ln] in self.words:
                    out.append(text[i:i + ln])
                    i += ln
                    break
            else:
                out.append(text[i])
                i += 1
        return out

    def _bmm(self, text: str) -> List[str]:
        out, j = [], len(text)
        while j > 0:
            for ln in range(min(self.max_len, j), 1, -1):
                if text[j - ln:j] in self.words:
                    out.append(text[j - ln:j])
                    j -= ln
                    break
            else:
                out.append(text[j - 1])
                j -= 1
        out.reverse()
        return out

    def cut(self, text: str) -> List[str]:
        """Segment one CJK run: bidirectional maximum matching, fewer
        words wins, then fewer single-character tokens (the standard MM
        tie-break for overlap ambiguity)."""
        if not text:
            return []
        f = self._fmm(text)
        b = self._bmm(text)
        if f == b:
            return f
        if len(f) != len(b):
            return f if len(f) < len(b) else b
        fs = sum(1 for w in f if len(w) == 1)
        bs = sum(1 for w in b if len(w) == 1)
        return f if fs <= bs else b


#: process-wide default (the fulltext tokenizer consumes this; SQL-side
#: dictionaries extend it via add_words/load_dict)
DEFAULT = Segmenter()


def cut(text: str) -> List[str]:
    return DEFAULT.cut(text)


def tokenize_cjk_run(run: str) -> List[str]:
    """Fulltext tokens for one contiguous CJK run: dictionary words
    where the segmenter finds them; unknown spans fall back to character
    bigrams (and lone singles stay singles) so out-of-vocabulary text
    remains searchable."""
    toks: List[str] = []
    pending: List[str] = []        # consecutive unknown single chars

    def flush():
        if not pending:
            return
        if len(pending) == 1:
            toks.append(pending[0])
        else:
            toks.extend("".join(pending[i:i + 2])
                        for i in range(len(pending) - 1))
        pending.clear()

    for w in DEFAULT.cut(run):
        if len(w) == 1 and w not in DEFAULT.words:
            pending.append(w)
            continue
        flush()
        toks.append(w)
    flush()
    return toks

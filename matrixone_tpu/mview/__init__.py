"""Incremental materialized views (reference analogue: the dynamic-table
/ CDC surface of pkg/stream + pkg/cdc, maintained from commit deltas
instead of recomputed).

A materialized view is a real engine table (the *backing table*) whose
rows are the output of a stored SELECT.  Definitions persist as rows of
the `system_mview` catalog table, so durability, restart replay, tenant
scoping and CN replication all ride the existing commit+logtail funnels
(same design as matrixone_tpu/udf).

Two maintenance modes, chosen by `mview.planner.analyze`:

  * ``incremental`` — the maintainable shapes (single-table
    scan -> filter -> group-by with SUM/COUNT/AVG/MIN/MAX): per-commit
    deltas from the engine's version funnel (`apply_segment` /
    `apply_tombstones`, surfaced through the logtail subscriber + a
    post-commit hook) feed a partial-aggregate update; the hot path is
    ONE compiled XLA dispatch per delta (the PR-7 dense-agg step via
    the shared FragmentCompileCache).  Tombstones retract subtractable
    aggregates; MIN/MAX deletes fall back to a per-group recompute.
    State advances atomically to a per-view high-watermark ts and the
    changed groups land in the backing table as one ordinary commit, so
    reads are snapshot-consistent at that watermark.
  * ``full`` — everything else degrades to the dynamic-table full
    rematerialization (DELETE + INSERT ... SELECT), refreshed on demand
    (`REFRESH MATERIALIZED VIEW` / `mo_ctl('mview','refresh:<v>')`).

`SHOW MATERIALIZED VIEWS` and EXPLAIN mark which mode a view runs in.
"""

from matrixone_tpu.mview.catalog import (MVIEW_TABLE, MViewDef,
                                         ensure_table, is_mview_table,
                                         registry_for)
from matrixone_tpu.mview.planner import MaintainSpec, analyze
from matrixone_tpu.mview.maintain import MViewService, service_for

__all__ = ["MVIEW_TABLE", "MViewDef", "ensure_table", "is_mview_table",
           "registry_for", "MaintainSpec", "analyze", "MViewService",
           "service_for", "stats"]


def stats(catalog) -> dict:
    """mo_ctl('mview','status') payload: registry + per-view runtime."""
    reg = registry_for(catalog)
    host = getattr(catalog, "_inner", catalog)
    svc = getattr(host, "_mview_service", None)
    views = {}
    for name, d in sorted(reg.items()):
        entry = {"mode": d.mode, "watermark": None}
        if svc is not None:
            rt = svc.runtime(name)
            if rt is not None:
                entry["watermark"] = rt.watermark
                entry["groups"] = rt.n_groups()
        views[name] = entry
    out = {"views": views, "n_views": len(reg)}
    if svc is not None:
        out.update(svc.stats())
    return out

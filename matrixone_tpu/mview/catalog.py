"""Materialized-view catalog: the `system_mview` table and the registry
derived from it.

Same design as udf/catalog.py (the reference's mo_user_defined_function
pattern applied to views): definitions live in an ordinary MVCC table so
durability, restart replay, tenant scoping (ScopedCatalog prefixes the
name) and CN replication (logtail insert/delete records) all ride the
funnels that already exist.  The in-memory registry is a cache DERIVED
from the table, keyed by the table's version — any commit, local or
logtail-applied, invalidates it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional

import numpy as np

from matrixone_tpu.container import dtypes as dt

MVIEW_TABLE = "system_mview"

_SCHEMA = [
    ("name", dt.varchar(128)),
    ("sql", dt.TEXT),                  # the defining SELECT, verbatim
    ("mode", dt.varchar(16)),          # 'incremental' | 'full'
    ("source", dt.varchar(128)),       # single-table source ('' for full)
    ("created_ts", dt.INT64),
]


@dataclasses.dataclass
class MViewDef:
    name: str
    sql: str
    mode: str                          # 'incremental' | 'full'
    source: str                        # source table name ('' when full)
    created_ts: int = 0

    @property
    def def_hash(self) -> str:
        """Content key of the definition — the delta compile cache and
        runtime state key on it so OR-REPLACE-style churn (drop +
        recreate under the same name) can never serve stale programs."""
        return hashlib.sha1(
            f"{self.name}|{self.mode}|{self.sql}".encode()).hexdigest()


def table_meta():
    from matrixone_tpu.storage.engine import TableMeta
    return TableMeta(MVIEW_TABLE, list(_SCHEMA), ["name"])


def ensure_table(catalog) -> None:
    if MVIEW_TABLE not in catalog.tables:
        catalog.create_table(table_meta(), if_not_exists=True)


def is_mview_table(name: str) -> bool:
    """True for the sys table and every tenant-scoped `acct$system_mview`
    variant (the commit funnel uses this to bump ddl_gen)."""
    return name == MVIEW_TABLE or name.endswith("$" + MVIEW_TABLE)


# ------------------------------------------------------------- registry

def _table_version(t) -> tuple:
    return (t.last_commit_ts, len(t.segments), len(t.tombstones))


def _scan_rows(t) -> List[dict]:
    cols = [c for c, _ in _SCHEMA]
    rows: List[dict] = []
    for arrays, validity, dicts, n in t.iter_chunks(cols, 1 << 16):
        for i in range(n):
            row = {}
            for c, d in _SCHEMA:
                if not validity[c][i]:
                    row[c] = None
                elif d.is_varlen:
                    row[c] = dicts[c][int(arrays[c][i])]
                else:
                    row[c] = int(arrays[c][i])
            rows.append(row)
    return rows


def _has_mview_table(catalog) -> bool:
    scope = getattr(catalog, "_scope", None)
    if scope is not None:
        inner = getattr(catalog, "_inner", None)
        if inner is not None:
            return scope(MVIEW_TABLE) in inner.tables
    tables = getattr(catalog, "tables", None)
    return tables is not None and MVIEW_TABLE in tables


def registry_for(catalog) -> Dict[str, MViewDef]:
    """name -> MViewDef for every view visible through `catalog`.
    Cached on the underlying table object, invalidated by version."""
    if not _has_mview_table(catalog):
        return {}
    t = catalog.get_table(MVIEW_TABLE)
    t = getattr(t, "_t", t)          # unwrap the CN _TableProxy
    version = _table_version(t)
    cached = getattr(t, "_mview_registry", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    reg: Dict[str, MViewDef] = {}
    for row in _scan_rows(t):
        try:
            d = MViewDef(name=row["name"], sql=row["sql"] or "",
                         mode=row["mode"] or "full",
                         source=row["source"] or "",
                         created_ts=row["created_ts"] or 0)
        except (KeyError, TypeError):
            continue              # malformed row: never poison binds
        reg[d.name.lower()] = d
    t._mview_registry = (version, reg)
    return reg


def lookup(catalog, name: str) -> Optional[MViewDef]:
    return registry_for(catalog).get(name.lower())


def gids_for_name(catalog, name: str) -> np.ndarray:
    """Global row ids of the view's catalog row(s) (DROP path)."""
    from matrixone_tpu.storage.engine import ROWID
    t = catalog.get_table(MVIEW_TABLE)
    out = []
    for arrays, validity, dicts, n in t.iter_chunks([ROWID, "name"],
                                                    1 << 16):
        d = dicts["name"]
        for i in range(n):
            if validity["name"][i] and \
                    d[int(arrays["name"][i])].lower() == name.lower():
                out.append(int(arrays[ROWID][i]))
    return np.asarray(out, np.int64)


def row_batch(d: MViewDef, created_ts: int):
    """One-row host Batch for the insert side of CREATE MATERIALIZED
    VIEW."""
    from matrixone_tpu.container.batch import Batch
    vals = {"name": [d.name.lower()], "sql": [d.sql], "mode": [d.mode],
            "source": [d.source], "created_ts": [int(created_ts)]}
    return Batch.from_pydict(vals, dict(_SCHEMA))

"""Delta maintenance: materialized-view state updated from the commit
funnel instead of recomputed.

Wiring (one per engine, `service_for`):

  * a logtail subscriber captures per-commit deltas for tracked source
    tables — insert events keep their Segment (immutable), delete
    events materialize the doomed rows' columns IMMEDIATELY (still
    under the commit lock, before a concurrent merge could compact
    them away);
  * `Engine._notify_post_commit` drives `on_commit` on the committing
    thread AFTER the commit fully applied and the lock released: the
    queue drains in commit order, one thread applying at a time, and
    `on_commit` does not return until every event enqueued before it
    was applied — a writer's next statement always sees its own delta
    in the view (read-your-writes), and two concurrent writers
    serialize through the applying flag;
  * applying one commit's events updates the in-memory partial-agg
    state (the jitted dense tier is ONE compiled dispatch per delta —
    the PR-7 dense-agg step via the shared FragmentCompileCache) and
    lands the changed groups in the backing table as ONE ordinary
    commit, then advances the view watermark: reads are snapshot-
    consistent at that watermark because they are plain MVCC reads of
    the backing table.

Retraction: SUM/COUNT/AVG subtract exactly; a delete touching a group
with MIN/MAX falls back to a per-group recompute from the source at the
commit's snapshot.  A group whose live row count reaches zero leaves
the view (matching GROUP BY semantics).  Any error mid-apply poisons
that view's state (groups=None): the next commit re-initializes it from
a full recompute — self-healing over silently-wrong.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container import dtypes as dt, from_device
from matrixone_tpu.container.dtypes import TypeOid
from matrixone_tpu.container.device import DeviceBatch, DeviceColumn
from matrixone_tpu.ops import agg as A, filter as F
from matrixone_tpu.sql import plan as P
from matrixone_tpu.sql.expr import AggCall, BoundCast
from matrixone_tpu.utils import san
from matrixone_tpu.vm.exprs import ExecBatch, eval_expr

from matrixone_tpu.mview import catalog as mcat
from matrixone_tpu.mview.planner import MaintainSpec, analyze

#: aggregate functions the dense one-dispatch tier handles (the
#: additive subset; MIN/MAX ride the general tier)
_DENSE_FUNCS = frozenset({"count", "sum", "avg"})


def _maintain_fields(a: AggCall) -> List[str]:
    """Partial-state fields of one aggregate (host scalars per group)."""
    if a.func == "count":
        return ["count"]
    if a.func in ("sum", "avg"):
        return ["sum", "count"]
    return [a.func, "count"]        # min / max


def _partial_arg(a: AggCall):
    """The expression whose per-group SUM is this aggregate's additive
    partial — avg over floats casts to f64 first, mirroring the device
    step (_grouped_step) exactly."""
    if a.func == "avg" and a.arg.dtype.is_float:
        return BoundCast(a.arg, dt.FLOAT64)
    return a.arg


class ViewRuntime:
    """One maintained view: spec + partial-agg state + watermark.
    Mutated only under the owning service's maintenance lock."""

    def __init__(self, name: str, spec: MaintainSpec, def_hash: str):
        self.name = name
        self.spec = spec
        self.def_hash = def_hash
        #: key tuple -> {"rows": int, "parts": [per-agg field dict]}
        self.groups: Optional[Dict[tuple, dict]] = None
        self.watermark: Optional[int] = None

    def n_groups(self) -> Optional[int]:
        return None if self.groups is None else len(self.groups)

    def invalidate(self) -> None:
        """Poison the state: the next commit re-initializes from a full
        recompute.  The watermark retreat with it IS the invalidation —
        molint's cache-invalidation checker pins the pairing."""
        san.mutating(self)
        self.groups = None
        self.watermark = None

    def replace_state(self, groups: Dict[tuple, dict], ts: int) -> None:
        san.mutating(self)
        self.groups = groups
        self.watermark = ts

    def merge_delta(self, delta: Dict[tuple, dict], sign: int,
                    ts: int) -> set:
        """Fold one delta's per-group partials into the state with
        `sign` (+1 insert, -1 retract); advance the watermark to `ts`.
        Returns the touched key set (the backing rewrite set)."""
        san.mutating(self)
        spec = self.spec
        touched = set()
        for key, d in delta.items():
            touched.add(key)
            g = self.groups.get(key)
            if g is None:
                g = {"rows": 0,
                     "parts": [dict.fromkeys(_maintain_fields(a), None)
                               for a in spec.aggs]}
                self.groups[key] = g
            g["rows"] += sign * d["rows"]
            for a, part, dp in zip(spec.aggs, g["parts"], d["parts"]):
                for f in _maintain_fields(a):
                    v = dp.get(f)
                    if v is None:
                        continue
                    cur = part.get(f)
                    if f in ("sum", "count"):
                        part[f] = v * sign if cur is None \
                            else cur + sign * v
                    elif f == "min":
                        part[f] = v if cur is None else min(cur, v)
                    else:               # max
                        part[f] = v if cur is None else max(cur, v)
            if g["rows"] <= 0:
                del self.groups[key]
        self.watermark = max(self.watermark, ts)
        return touched


class MViewService:
    """Per-engine maintenance driver (see module docstring)."""

    def __init__(self, engine):
        self.engine = engine
        # queue lock: taken by the subscriber UNDER the commit lock —
        # must never acquire the commit lock itself
        self._qlock = san.lock("MViewService._qlock")
        self._qcv = san.condition(self._qlock)
        self._queue: List[tuple] = []
        self._applying = False
        # maintenance lock: serializes state mutation + backing commits
        self._lock = san.rlock("MViewService._lock")
        self._maint = threading.local()       # re-entrancy guard
        self._views: Dict[str, ViewRuntime] = {}      # event-driven
        self._dynamic: Dict[str, ViewRuntime] = {}    # refresh-driven
        self._sources: frozenset = frozenset()
        self._needed_cols: Dict[str, List[str]] = {}
        #: def_hashes whose init failed: not retried until the
        #: definition changes (drop/recreate) — a permanently broken
        #: view must not wedge every commit into a failing recompute
        self._failed: set = set()
        engine.subscribe(self._on_event)

    # ------------------------------------------------------- event intake
    def _on_event(self, commit_ts: int, table: str, kind: str,
                  payload) -> None:
        """Logtail subscriber — runs under the engine commit lock, so it
        only buffers.  Delete payloads are decoded HERE: the tombstoned
        rows' values are guaranteed still present at notify time."""
        if table not in self._sources:
            return
        if kind == "delete":
            gids = np.asarray(payload, np.int64)
            if len(gids) == 0:
                return
            t = self.engine.get_table(table)
            cols = self._needed_cols.get(table) \
                or [c for c, _ in t.meta.schema]
            arrays, validity = t.fetch_rows(gids, cols)
            payload = (arrays, validity, len(gids))
        with self._qlock:
            self._queue.append((commit_ts, table, kind, payload))

    # ---------------------------------------------------------- the hook
    def on_commit(self, commit_ts: int, touched: set) -> None:
        """Post-commit driver (Engine._notify_post_commit).  Returns
        only when every event enqueued before entry has been applied.
        The triggering commit is already durable — maintenance failures
        must never surface from it (per-view errors poison that view's
        state instead; see _apply_events / _init_pending)."""
        if getattr(self._maint, "active", False):
            return             # nested maintenance commit: outer drains
        self._maint.active = True
        try:
            self._sync_views()
            self._init_pending()
            self._drain_all()
        except Exception:   # noqa: BLE001 — a maintenance-driver crash
            # (registry unreadable mid-drop, source table racing away)
            # must not fail the writer's ALREADY-APPLIED commit; the
            # next commit retries, per-view state stays poisoned-safe
            from matrixone_tpu.utils import metrics as M
            M.mview_apply.inc(tier="error")
        finally:
            self._maint.active = False

    def runtime(self, name: str) -> Optional[ViewRuntime]:
        return self._views.get(name) or self._dynamic.get(name)

    def stats(self) -> dict:
        with self._qlock:
            queued = len(self._queue)
        return {"incremental": sorted(self._views),
                "queued_events": queued,
                "sources": sorted(self._sources)}

    # ------------------------------------------------------ registry sync
    def _sync_views(self) -> None:
        """Diff the system_mview registry (version-cached) against the
        attached runtimes; attach/detach and rebuild the source map."""
        reg = mcat.registry_for(self.engine)
        with self._lock:
            want = {n: d for n, d in reg.items()
                    if d.mode == "incremental"}
            # a dropped definition forgives its failure record, so a
            # drop + recreate (same SQL) retries a failed init
            self._failed &= {d.def_hash for d in reg.values()}
            for n in list(self._views):
                d = want.get(n)
                if d is None or d.def_hash != self._views[n].def_hash:
                    del self._views[n]
            for n, d in want.items():
                if n in self._views or d.def_hash in self._failed:
                    continue
                from matrixone_tpu.sql.parser import parse
                try:
                    sel = parse(d.sql)[0]
                    spec, _why = analyze(sel, self.engine)
                except Exception:       # noqa: BLE001 — a definition
                    spec = None         # that stopped binding (dropped
                    #                     source) simply detaches
                if spec is None:
                    continue
                rt = ViewRuntime(n, spec, d.def_hash)
                san.guard(rt, self._lock, name=f"MViewRuntime[{n}]")
                self._views[n] = rt
            self._rebuild_sources()

    def _rebuild_sources(self) -> None:
        srcs = {}
        for rt in self._views.values():
            srcs.setdefault(rt.spec.source, set()).update(
                rt.spec.scan_columns)
        self._needed_cols = {t: sorted(c) for t, c in srcs.items()}
        self._sources = frozenset(srcs)

    # ----------------------------------------------------- initialization
    def _init_pending(self) -> None:
        for rt in list(self._views.values()):
            if rt.groups is None:
                try:
                    self._init_view(rt)
                except Exception:   # noqa: BLE001 — an unbuildable view
                    # (source dropped mid-flight) must not wedge every
                    # later commit into a failing full recompute:
                    # detach + remember; a definition change
                    # (drop/recreate) re-attaches and retries
                    with self._lock:
                        self._failed.add(rt.def_hash)
                        self._views.pop(rt.name, None)
                        self._rebuild_sources()

    def _init_view(self, rt: ViewRuntime) -> None:
        """Full compute at the current frontier: state + one rewrite
        commit.  Events at or below the captured frontier are skipped by
        the watermark; later ones replay on top."""
        from matrixone_tpu.utils import metrics as M
        t0 = time.perf_counter()
        with self._lock:
            if rt.groups is not None:     # raced another initializer
                return
            ts0 = self.engine.committed_ts
            rt.replace_state(self._compute_groups(rt.spec, ts0), ts0)
            self._rewrite_backing(rt, set(rt.groups), full=True)
        M.mview_apply.inc(tier="init")
        M.mview_apply_seconds.inc(time.perf_counter() - t0, kind="full")

    # ----------------------------------------------------------- draining
    def _drain_all(self) -> None:
        """Apply every queued event; when another thread is applying,
        wait it out so the caller's read-your-writes holds."""
        while True:
            with self._qlock:
                if not self._queue:
                    if not self._applying:
                        return
                    self._qcv.wait(timeout=1.0)
                    continue
                if self._applying:
                    self._qcv.wait(timeout=1.0)
                    continue
                batch, self._queue = self._queue, []
                self._applying = True
            try:
                self._apply_events(batch)
            finally:
                with self._qlock:
                    self._applying = False
                    self._qcv.notify_all()

    def _apply_events(self, events: List[tuple]) -> None:
        """Apply one popped batch, grouped into per-commit runs so a
        view's watermark only advances over FULLY applied commits."""
        i = 0
        while i < len(events):
            ts = events[i][0]
            j = i
            while j < len(events) and events[j][0] == ts:
                j += 1
            run = events[i:j]
            i = j
            for rt in list(self._views.values()):
                mine = [e for e in run if e[1] == rt.spec.source]
                if not mine:
                    continue
                try:
                    with self._lock:
                        self._apply_run(rt, ts, mine)
                except Exception:   # noqa: BLE001 — a failed apply must
                    # never leave silently-wrong state: poison it and
                    # let the next commit re-initialize from scratch
                    with self._lock:
                        rt.invalidate()

    def _apply_run(self, rt: ViewRuntime, ts: int, run: List[tuple]
                   ) -> None:
        """One commit's events for one view (deletes precede inserts by
        funnel order): merge deltas, recompute MIN/MAX-retracted groups,
        rewrite the changed backing rows, advance the watermark."""
        from matrixone_tpu.utils import metrics as M
        from matrixone_tpu.utils import motrace
        if rt.groups is None or ts <= rt.watermark:
            return
        t0 = time.perf_counter()
        with motrace.span("mview.apply", view=rt.name,
                          events=len(run)):
            self._apply_run_traced(rt, ts, run, t0, M)

    def _apply_run_traced(self, rt: ViewRuntime, ts: int,
                          run: List[tuple], t0: float, M) -> None:
        touched: set = set()
        recompute: set = set()
        for _ts, _table, kind, payload in run:
            if kind == "insert":
                seg = payload
                delta = self._delta_partials(
                    rt, seg.arrays, seg.validity, seg.n_rows)
                touched |= rt.merge_delta(delta, +1, ts)
            else:
                arrays, validity, n = payload
                delta = self._delta_partials(rt, arrays, validity, n)
                if rt.spec.has_minmax:
                    recompute |= set(delta)
                touched |= rt.merge_delta(delta, -1, ts)
        if recompute:
            # retraction of an extremum is not subtractable: replace the
            # affected groups' state from the source at this snapshot
            live = recompute & set(rt.groups)
            fresh = self._compute_groups(rt.spec, ts, only_keys=live)
            san.mutating(rt)
            for key in live:
                g = fresh.get(key)
                if g is None:
                    rt.groups.pop(key, None)
                else:
                    rt.groups[key] = g
            rt.watermark = max(rt.watermark, ts)
            M.mview_apply.inc(tier="recompute")
        self._rewrite_backing(rt, touched | recompute)
        M.mview_apply_seconds.inc(time.perf_counter() - t0, kind="delta")

    # =================================================== delta evaluation
    def _delta_execbatch(self, spec: MaintainSpec, arrays, validity,
                         n: int) -> ExecBatch:
        from matrixone_tpu.vm.operators import chunk_to_execbatch
        t = self.engine.get_table(spec.source)
        return chunk_to_execbatch(arrays, validity, t.dicts, n,
                                  spec.scan_columns, spec.scan_schema)

    def _delta_partials(self, rt: ViewRuntime, arrays, validity, n: int
                        ) -> Dict[tuple, dict]:
        """Per-group partials of one delta (a segment's rows or the
        decoded rows behind a tombstone): filters + keys + aggregate
        arguments evaluated over a device batch, grouped host-side.
        The dense tier compiles the WHOLE evaluation into one cached
        XLA program (one dispatch per delta)."""
        from matrixone_tpu.utils import metrics as M
        spec = rt.spec
        ex = self._delta_execbatch(spec, arrays, validity, n)
        M.mview_rows.inc(n)
        dense = self._dense_delta(rt, ex)
        if dense is not None:
            M.mview_apply.inc(tier="dense")
            return dense
        M.mview_apply.inc(tier="general")
        return self._general_delta(spec, ex)

    # ---- general tier (host groupby; any maintainable shape)
    def _general_delta(self, spec: MaintainSpec, ex: ExecBatch
                       ) -> Dict[tuple, dict]:
        from matrixone_tpu.vm import operators as O
        for f in spec.filters:
            ex.mask = ex.mask & F.predicate_mask(eval_expr(f, ex),
                                                 ex.batch)
        mask = np.asarray(jax.device_get(ex.mask))
        keys_host = []
        for k in spec.group_keys:
            col = O._broadcast_full(eval_expr(k, ex), ex.padded_len)
            d = O._expr_dict(k, ex)
            keys_host.append((np.asarray(jax.device_get(col.data)),
                              np.asarray(jax.device_get(col.validity)),
                              k.dtype, d))
        vals_host = []
        for a in spec.aggs:
            if a.arg is None:
                vals_host.append(None)
                continue
            col = O._broadcast_full(eval_expr(_partial_arg(a), ex),
                                    ex.padded_len)
            vals_host.append(
                (np.asarray(jax.device_get(col.data)),
                 np.asarray(jax.device_get(col.validity))))
        out: Dict[tuple, dict] = {}
        for i in np.nonzero(mask)[0]:
            key = tuple(_norm_key(dref, int(i), data, valid, dtype)
                        for data, valid, dtype, dref in keys_host)
            g = out.get(key)
            if g is None:
                g = {"rows": 0,
                     "parts": [dict.fromkeys(_maintain_fields(a), None)
                               for a in spec.aggs]}
                out[key] = g
            g["rows"] += 1
            for a, part, vh in zip(spec.aggs, g["parts"], vals_host):
                if a.arg is None:               # count(*)
                    part["count"] = (part["count"] or 0) + 1
                    continue
                data, valid = vh
                if not valid[i]:
                    continue
                part["count"] = (part["count"] or 0) + 1
                v = data[i].item()
                if a.func in ("sum", "avg"):
                    part["sum"] = v if part["sum"] is None \
                        else part["sum"] + v
                elif a.func == "min":
                    part["min"] = v if part["min"] is None \
                        else min(part["min"], v)
                elif a.func == "max":
                    part["max"] = v if part["max"] is None \
                        else max(part["max"], v)
        return out

    # ---- dense tier (one compiled dispatch; the Q1 shape)
    def _dense_delta(self, rt: ViewRuntime, ex: ExecBatch
                     ) -> Optional[Dict[tuple, dict]]:
        from matrixone_tpu.vm import fusion
        from matrixone_tpu.vm import operators as O
        spec = rt.spec
        if any(a.func not in _DENSE_FUNCS for a in spec.aggs):
            return None
        sizes, key_dicts = [], []
        for k in spec.group_keys:
            d = fusion._static_dict(k, ex.dicts)
            if d is not None:
                sizes.append(max(len(d), 1))
                key_dicts.append(d)
            elif k.dtype.oid == TypeOid.BOOL:
                sizes.append(2)
                key_dicts.append(None)
            else:
                return None
        g = 1
        for s in sizes:
            g *= s + 1
        if g > 4096:
            return None               # unroll budget (delta shapes are
        sizes = tuple(sizes)          # dashboards: a handful of groups)
        # content-addressed compile key: view definition, batch shape/
        # dtypes, dense sizes, and the CONTENT of every dictionary an
        # expression may bake into a LUT (grown dict => retrace)
        t = self.engine.get_table(spec.source)
        colsig = tuple((nm, int(c.dtype.oid), tuple(c.data.shape))
                       for nm, c in ex.batch.columns.items())
        dict_keys = tuple(fusion._dict_key(t.dicts.get(c))
                          for c in spec.scan_columns
                          if c in t.dicts)
        key = ("mview", rt.def_hash, colsig, int(ex.mask.shape[0]),
               sizes, dict_keys)
        entry = fusion.CACHE.entry(key)
        from matrixone_tpu.utils import keys as keyaudit
        if keyaudit.armed():
            # full dictionary CONTENT recomputed independently of
            # fusion._dict_key: a length-only regression in the compile
            # key (the PR-7 class) mismatches here on the first
            # colliding hit instead of serving stale delta partials
            keyaudit.audit("mview/maintain.py:mview", key, {
                "scan_dict_content": tuple(
                    (c, tuple(str(s) for s in t.dicts[c]))
                    for c in spec.scan_columns if c in t.dicts),
                "env_dict_content": tuple(
                    sorted((nm, tuple(str(s) for s in d))
                           for nm, d in ex.dicts.items()
                           if d is not None)),
                "sizes": sizes,
                "shape": (len(spec.filters), len(spec.group_keys),
                          len(spec.aggs)),
            })
        fn = entry["fn"].get("step")
        if fn is None:
            trig = tuple((nm, c.dtype)
                         for nm, c in ex.batch.columns.items())
            fn, fieldmap = self._make_dense_step(spec, trig, sizes,
                                                 dict(ex.dicts))
            entry["fn"]["step"] = fn
            entry["fieldmap"] = fieldmap
        fieldmap = entry["fieldmap"]
        datas = tuple(c.data for c in ex.batch.columns.values())
        valids = tuple(c.validity for c in ex.batch.columns.values())
        args = (datas, valids, jnp.asarray(ex.batch.n_rows, jnp.int32),
                ex.mask)
        from matrixone_tpu.utils import metrics as M
        if not entry["failed"]:
            compiled = entry["compiled"].get("step")
            if compiled is None:
                t0 = time.perf_counter()
                try:
                    compiled = jax.jit(fn).lower(*args).compile()
                except Exception:   # noqa: BLE001 — tracer rejection:
                    entry["failed"] = True      # eager fallback below
                    M.fusion_compile.inc(outcome="trace_fail")
                else:
                    entry["compiled"]["step"] = compiled
                    entry["trace_s"] += time.perf_counter() - t0
            if not entry["failed"]:
                out = entry["compiled"]["step"](*args)
                M.fusion_dispatch.inc(kind="step")
                return self._dense_to_groups(spec, out, sizes,
                                             key_dicts, fieldmap)
        out = fn(*args)               # eager: identical math
        M.fusion_dispatch.inc(kind="eager")
        return self._dense_to_groups(spec, out, sizes, key_dicts,
                                     fieldmap)

    def _make_dense_step(self, spec: MaintainSpec, trig_schema, sizes,
                         env0):
        """Build the delta step: filters -> keys -> deduplicated partial
        lanes -> dense_lane_partials, all inside one traceable function
        (jit-compiled when possible, called eagerly otherwise — one
        implementation, so the two modes cannot diverge)."""
        from matrixone_tpu.vm import fusion
        from matrixone_tpu.vm import operators as O
        # static lane layout (mirrors AggOp._dense_step's dedup)
        lane_of: Dict[tuple, tuple] = {}
        int_specs: List[tuple] = []      # (agg_idx|None, field)
        float_specs: List[tuple] = []
        fieldmap: List[List[tuple]] = []  # per agg: (field, lane)
        for ai, a in enumerate(spec.aggs):
            fm = []
            for f in _maintain_fields(a):
                if f == "count":
                    lk = ("count", None if a.arg is None
                          else fusion._dedup_sig(a.arg))
                    cls = "int"
                else:
                    arg = _partial_arg(a)
                    cls = "float" if arg.dtype.is_float else "int"
                    lk = ("sum", cls, fusion._dedup_sig(arg))
                lane = lane_of.get(lk)
                if lane is None:
                    if cls == "int":
                        lane = ("int", len(int_specs))
                        int_specs.append((ai, f))
                    else:
                        lane = ("float", len(float_specs))
                        float_specs.append((ai, f))
                    lane_of[lk] = lane
                fm.append((f, lane))
            fieldmap.append(fm)

        def step(datas, valids, n_rows, mask):
            cols = {nm: DeviceColumn(d, v, t)
                    for (nm, t), d, v in zip(trig_schema, datas, valids)}
            ex = ExecBatch(batch=DeviceBatch(columns=cols,
                                             n_rows=n_rows),
                           dicts=env0, mask=mask)
            for f in spec.filters:
                ex.mask = ex.mask & F.predicate_mask(
                    eval_expr(f, ex), ex.batch)
            n = ex.padded_len
            kdata, kvalid = [], []
            for k in spec.group_keys:
                kc = O._broadcast_full(eval_expr(k, ex), n)
                kdata.append(kc.data)
                kvalid.append(kc.validity)
            val_cache: dict = {}

            def _val(arg):
                sig = fusion._dedup_sig(arg)
                got = val_cache.get(sig)
                if got is None:
                    got = O._broadcast_full(eval_expr(arg, ex), n)
                    val_cache[sig] = got
                return got

            int_vals, int_masks = [], []
            float_vals, float_masks = [], []
            for ai, f in int_specs:
                a = spec.aggs[ai]
                if f == "count":
                    if a.arg is None:
                        int_vals.append(None)
                        int_masks.append(None)
                    else:
                        v = _val(_partial_arg(a))
                        int_vals.append(None)
                        int_masks.append(v.validity)
                else:
                    v = _val(_partial_arg(a))
                    int_vals.append(v.data)
                    int_masks.append(v.validity)
            for ai, f in float_specs:
                v = _val(_partial_arg(spec.aggs[ai]))
                float_vals.append(v.data)
                float_masks.append(v.validity)
            return A.dense_lane_partials(
                tuple(kdata), tuple(kvalid), ex.mask,
                tuple(int_vals), tuple(int_masks),
                tuple(float_vals), tuple(float_masks),
                sizes=sizes, with_null=True)

        return step, fieldmap

    def _dense_to_groups(self, spec: MaintainSpec, out, sizes,
                         key_dicts, fieldmap) -> Dict[tuple, dict]:
        """Dense lanes -> {key tuple: partials}, decoding NULL-slotted
        mixed-radix slots back to key values."""
        ints, floats, rows = (np.asarray(jax.device_get(x))
                              for x in out)
        strides, _g = A.dense_slot_strides(sizes)    # NULL-slotted radix
        groups: Dict[tuple, dict] = {}
        for slot in np.nonzero(rows)[0]:
            key = []
            for k, s, st, d in zip(spec.group_keys, sizes, strides,
                                   key_dicts):
                code = (int(slot) // st) % (s + 1)
                if code >= s:
                    key.append(None)
                elif d is not None:
                    key.append(d[code])
                elif k.dtype.oid == TypeOid.BOOL:
                    key.append(bool(code))
                else:
                    key.append(int(code))
            key = tuple(key)
            parts = []
            for a, fm in zip(spec.aggs, fieldmap):
                part = dict.fromkeys(_maintain_fields(a), None)
                for f, lane in fm:
                    arr = ints if lane[0] == "int" else floats
                    v = arr[lane[1]][slot]
                    part[f] = float(v) if lane[0] == "float" else int(v)
                parts.append(part)
            groups[key] = {"rows": int(rows[slot]), "parts": parts}
        return groups

    # =============================================== full/partial compute
    def _partial_plan(self, spec: MaintainSpec):
        """The partial-aggregate plan: same scan/filters/keys as the
        view, aggregates rewritten to their additive partials so the
        result converts straight into maintenance state.  Runs through
        the ordinary compile_plan pipeline (dense path, fusion and all),
        so the init/recompute numbers are the engine's own."""
        from matrixone_tpu.sql.binder import _agg_result_type
        scan = P.Scan(spec.source, list(spec.scan_columns),
                      list(spec.scan_schema),
                      filters=list(spec.filters))
        paggs: List[AggCall] = [AggCall("count", None, False, dt.INT64,
                                        out_name="_rows")]
        layout: List[dict] = []          # per agg: field -> out index
        from matrixone_tpu.vm import fusion
        seen: Dict[tuple, int] = {}
        for a in spec.aggs:
            fmap = {}
            for f in _maintain_fields(a):
                if f == "count" and a.arg is None:
                    fmap[f] = 0           # count(*) IS the rows lane
                    continue
                arg = a.arg if f in ("count", "min", "max") \
                    else _partial_arg(a)
                func = {"count": "count", "sum": "sum", "min": "min",
                        "max": "max"}[f]
                sk = (func, fusion._dedup_sig(arg))
                idx = seen.get(sk)
                if idx is None:
                    out_t = _agg_result_type(func, arg.dtype)
                    idx = len(paggs)
                    paggs.append(AggCall(func, arg, False, out_t,
                                         out_name=f"_p{idx}"))
                    seen[sk] = idx
                fmap[f] = idx
            layout.append(fmap)
        schema = [(f"_g{i}", k.dtype)
                  for i, k in enumerate(spec.group_keys)] + \
            [(a.out_name, a.dtype) for a in paggs]
        return P.Aggregate(scan, list(spec.group_keys), paggs,
                           schema), layout

    def _compute_groups(self, spec: MaintainSpec, ts: int,
                        only_keys: Optional[set] = None
                        ) -> Dict[tuple, dict]:
        """Full (or key-restricted) partial compute at snapshot `ts`
        through the ordinary operator pipeline — the init / restart-
        rebuild / MIN-MAX-recompute path."""
        from matrixone_tpu.vm.compile import compile_plan
        from matrixone_tpu.vm.process import ExecContext
        node, layout = self._partial_plan(spec)
        ctx = ExecContext(catalog=self.engine, txn=None,
                          variables={"batch_rows": 1 << 20},
                          frozen_ts=ts)
        op = compile_plan(node, ctx)
        nk = len(spec.group_keys)
        groups: Dict[tuple, dict] = {}
        for ex in op.execute():
            db = F.compact(ex.batch, ex.mask, ex.padded_len)
            b = from_device(db, ex.dicts, schema=dict(node.schema))
            n = len(b)
            if n == 0:
                continue
            kcols = []
            for (name, dtype), k in zip(node.schema[:nk],
                                        spec.group_keys):
                vec = b.columns[name]
                if dtype.is_varlen:
                    kcols.append(("s", vec.to_pylist(), None))
                else:
                    kcols.append((dtype, vec.data, vec.valid_mask()))
            pcols = []
            for name, _d in node.schema[nk:]:
                vec = b.columns[name]
                pcols.append((vec.data, vec.valid_mask()))
            for i in range(n):
                key = []
                for ent in kcols:
                    if ent[0] == "s":
                        key.append(ent[1][i])
                    else:
                        dtype, data, valid = ent
                        key.append(_norm_key(None, i, data, valid,
                                             dtype))
                key = tuple(key)
                if only_keys is not None and key not in only_keys:
                    continue
                rows = int(pcols[0][0][i])
                parts = []
                for a, fmap in zip(spec.aggs, layout):
                    part = dict.fromkeys(_maintain_fields(a), None)
                    for f, idx in fmap.items():
                        data, valid = pcols[idx]
                        if not valid[i]:
                            continue
                        v = data[i].item()
                        part[f] = float(v) if isinstance(v, float) \
                            else int(v)
                    parts.append(part)
                groups[key] = {"rows": rows, "parts": parts}
        return groups

    # ------------------------------------------------- backing rewrites
    def _rewrite_backing(self, rt: ViewRuntime, keys: set,
                         full: bool = False) -> None:
        """Land the changed groups in the backing table as ONE commit:
        delete the keys' existing rows, insert their fresh values.
        `full` rewrites everything (init / restart rebuild)."""
        if not keys and not full:
            return
        from matrixone_tpu.storage.engine import ROWID
        spec = rt.spec
        t = self.engine.get_table(rt.name)
        names = [c for c, _ in t.meta.schema]
        # map backing columns back to (kind, idx) and locate key columns
        key_col_of = {}           # group_key idx -> backing column name
        for (kind, idx), name in zip(spec.out_cols, names):
            if kind == "key":
                key_col_of[idx] = name
        key_cols = [key_col_of[i] for i in range(len(spec.group_keys))]
        sd = dict(t.meta.schema)
        # existing rows for the touched keys (small: the view output)
        gids: List[int] = []
        for arrays, validity, dicts, n in t.iter_chunks(
                key_cols + [ROWID], 1 << 20):
            for i in range(n):
                key = []
                for c in key_cols:
                    if not validity[c][i]:
                        key.append(None)
                    elif sd[c].is_varlen:
                        key.append(dicts[c][int(arrays[c][i])])
                    elif sd[c].oid == TypeOid.BOOL:
                        key.append(bool(arrays[c][i]))
                    elif sd[c].is_float:
                        key.append(float(arrays[c][i]))
                    else:
                        key.append(int(arrays[c][i]))
                if full or tuple(key) in keys:
                    gids.append(int(arrays[ROWID][i]))
        live = [k for k in (rt.groups if full else keys)
                if k in rt.groups]
        inserts = {}
        if live:
            vals = {name: [] for name in names}
            valid = {name: [] for name in names}
            for key in live:
                g = rt.groups[key]
                for (kind, idx), name in zip(spec.out_cols, names):
                    if kind == "key":
                        v = key[idx]
                        vals[name].append(v)
                        valid[name].append(v is not None)
                    else:
                        v, ok = _final_value(spec.aggs[idx],
                                             g["parts"][idx])
                        vals[name].append(v)
                        valid[name].append(ok)
            arrays2, validity2 = {}, {}
            for name in names:
                d = sd[name]
                vv = np.asarray(valid[name], np.bool_)
                if d.is_varlen:
                    arrays2[name] = t.encode_strings_list(
                        name, [v if ok else None
                               for v, ok in zip(vals[name],
                                                valid[name])])
                else:
                    filled = [v if ok else 0
                              for v, ok in zip(vals[name],
                                               valid[name])]
                    arrays2[name] = np.asarray(filled, d.np_dtype)
                validity2[name] = vv
            inserts = {rt.name: [(arrays2, validity2)]}
        if not inserts and not gids:
            return
        self.engine.commit_txn(
            None, inserts,
            {rt.name: np.asarray(gids, np.int64)} if gids else {})

    # ------------------------------------------- dynamic-table upgrade
    def refresh_dynamic(self, name: str, sql: str) -> Optional[int]:
        """Delta refresh for a maintainable dynamic table (the silent
        upgrade from DELETE+INSERT): replay the shared commit-delta
        stream (cdc.delta_events) past the watermark.  Returns the view
        row count, or None when the shape is not maintainable (caller
        falls back to the full rematerialize)."""
        import hashlib
        from matrixone_tpu.cdc import delta_events
        from matrixone_tpu.utils import metrics as M
        dh = hashlib.sha1(sql.encode()).hexdigest()
        rt = self._dynamic.get(name)
        if rt is None or rt.def_hash != dh:
            from matrixone_tpu.sql.parser import parse
            try:
                stmts = parse(sql)
                spec, _why = analyze(stmts[0], self.engine)
            except Exception:   # noqa: BLE001 — unparseable/unbindable:
                return None     # the full-refresh path reports it
            if spec is None:
                return None
            rt = ViewRuntime(name, spec, dh)
            san.guard(rt, self._lock, name=f"MViewRuntime[{name}]")
            self._dynamic[name] = rt
            # pin this runtime's replay history: fence GC defers any
            # compaction fence of the source until the runtime's
            # watermark passes it (delta-aware GC), so refreshes across
            # a background merge stay incremental
            reg = getattr(self.engine, "register_watermark", None)
            if reg is not None:
                reg(f"dyn:{name}", rt.spec.source,
                    lambda rt=rt: rt.watermark if rt.groups is not None
                    else None)
        was = getattr(self._maint, "active", False)
        self._maint.active = True
        try:
            with self._lock:
                src = self.engine.get_table(rt.spec.source)
                floor = getattr(src, "delta_floor", 0)
                if rt.groups is None or rt.watermark < floor:
                    # DEGRADE RUNG: first delta refresh, or the merge
                    # fence below our watermark was GC'd (history gone)
                    # — rebuild from scratch.  A merge whose fence is
                    # still held replays incrementally below via
                    # delta_events' exactly-once fence windows.
                    ts0 = self.engine.committed_ts
                    rt.replace_state(
                        self._compute_groups(rt.spec, ts0), ts0)
                    self._rewrite_backing(rt, set(rt.groups), full=True)
                    M.mview_apply.inc(tier="init")
                    return len(rt.groups)
                events = delta_events(self.engine, rt.spec.source,
                                      rt.watermark + 1)
                i = 0
                while i < len(events):
                    ts = events[i][0]
                    run = []
                    while i < len(events) and events[i][0] == ts:
                        ets, kind, payload = events[i]
                        if kind == "delete":
                            gids = np.asarray(payload, np.int64)
                            arrays, validity = src.fetch_rows(
                                gids, rt.spec.scan_columns)
                            payload = (arrays, validity, len(gids))
                        run.append((ets, rt.spec.source, kind, payload))
                        i += 1
                    self._apply_run(rt, ts, run)
                return len(rt.groups)
        finally:
            self._maint.active = was


_SERVICE_LOCK = san.lock("matrixone_tpu.mview._SERVICE_LOCK")


def service_for(engine) -> MViewService:
    """One maintenance service per engine (the TN / embedded engine —
    CN replicas never maintain; their backing rows arrive from the TN
    through the logtail)."""
    host = getattr(engine, "_inner", engine)
    svc = getattr(host, "_mview_service", None)
    if svc is None:
        with _SERVICE_LOCK:
            svc = getattr(host, "_mview_service", None)
            if svc is None:
                svc = MViewService(host)
                host._mview_service = svc
    return svc


def _final_value(a: AggCall, part: dict) -> Tuple[object, bool]:
    """Finalize one group's partial into the backing-stored value —
    mirrors vm/operators._grouped_final exactly (decimal sums stay
    scaled ints; avg divides in float64)."""
    c = part.get("count") or 0
    if a.func == "count":
        return int(c), True
    if c <= 0:
        return None, False
    if a.func == "sum":
        return part["sum"], True
    if a.func == "avg":
        s = float(part["sum"])
        if a.arg.dtype.oid == TypeOid.DECIMAL64:
            s = s / (10.0 ** a.arg.dtype.scale)
        return s / max(c, 1), True
    return part[a.func], True


def _norm_key(dref, i: int, data, valid, dtype):
    """State key of one evaluated group-key cell, at STORED
    representation (varchar decoded so dictionary growth can't alias)."""
    if not valid[i]:
        return None
    if dref is not None:
        return dref[int(data[i])]
    if dtype.oid == TypeOid.BOOL:
        return bool(data[i])
    if dtype.is_float:
        return float(data[i])
    return int(data[i])

"""Maintainability analysis: which view definitions can be maintained
from commit deltas instead of recomputed.

The maintainable shapes are exactly the fused-fragment shapes (vm/
fusion.py): a single-table scan -> pushed/explicit filters -> GROUP BY
with SUM / COUNT / AVG / MIN / MAX over traceable argument expressions,
optionally re-projected (pure renames) and ordered.  Anything else —
joins, HAVING, DISTINCT, window functions, subqueries, LIMIT,
nondeterministic functions, scalar (no-GROUP-BY) aggregates — degrades
to the dynamic-table full rematerialization, and `SHOW MATERIALIZED
VIEWS` / EXPLAIN say so.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from matrixone_tpu.container.dtypes import DType
from matrixone_tpu.sql import ast, plan as P
from matrixone_tpu.sql.expr import AggCall, BoundCol, BoundExpr

#: aggregate functions the delta maintainer knows how to update;
#: MIN/MAX merge over inserts and fall back to per-group recompute on
#: deletes (retraction of an extremum is not subtractable)
MAINTAINABLE_AGGS = frozenset({"sum", "count", "avg", "min", "max"})


@dataclasses.dataclass
class MaintainSpec:
    """Everything the maintainer needs, captured from the BOUND plan so
    delta evaluation uses the exact expressions a full recompute would."""
    source: str                        # single source table
    scan_columns: List[str]            # raw table columns the scan reads
    scan_schema: List[Tuple[str, DType]]   # qualified names for eval
    filters: List[BoundExpr]           # scan-pushed + explicit WHERE
    group_keys: List[BoundExpr]
    aggs: List[AggCall]
    #: backing-table column order: ("key", i) | ("agg", i) per output
    out_cols: List[tuple]
    out_schema: List[Tuple[str, DType]]
    def_hash: str = ""

    @property
    def has_minmax(self) -> bool:
        return any(a.func in ("min", "max") for a in self.aggs)


def _reason(msg: str):
    return None, msg


def _ast_nondet(sel) -> Optional[str]:
    """Name of the first nondeterministic function call in the statement
    AST (checked PRE-bind: the binder folds now() to a literal, which
    would silently freeze time into the maintained state)."""
    import dataclasses as dc
    from matrixone_tpu.serving.plan_cache import NONDET_FUNCS

    def walk(node):
        if isinstance(node, ast.FuncCall) and \
                node.name.lower() in NONDET_FUNCS:
            yield node.name.lower()
        if dc.is_dataclass(node) and isinstance(node, ast.Node):
            for f in dc.fields(node):
                v = getattr(node, f.name)
                items = v if isinstance(v, list) else [v]
                for x in items:
                    if isinstance(x, ast.Node):
                        yield from walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, ast.Node):
                                yield from walk(y)
    for name in walk(sel):
        return name
    return None


def analyze(sel, catalog, binder=None):
    """-> (MaintainSpec | None, reason).  `sel` is the parsed SELECT of
    the view definition; a None spec means full-refresh mode, with the
    human-readable reason surfaced by SHOW MATERIALIZED VIEWS.  Bind
    errors propagate — a broken definition is the caller's problem."""
    from matrixone_tpu.sql.binder import Binder

    if not isinstance(sel, ast.Select):
        return _reason("UNION definitions are not maintainable")
    if sel.ctes or sel.having is not None or sel.distinct \
            or getattr(sel, "fill", None) is not None:
        return _reason("CTE/HAVING/DISTINCT/FILL are not maintainable")
    if sel.limit is not None or sel.offset:
        return _reason("LIMIT/OFFSET is not maintainable")
    nd = _ast_nondet(sel)
    if nd is not None:
        return _reason(f"nondeterministic function {nd}()")
    node = (binder or Binder(catalog)).bind_statement(sel)
    return analyze_plan(node)


def analyze_plan(node):
    """Shape-match a BOUND plan (see analyze); separated so the dynamic-
    table upgrade path can reuse it on an already-bound plan."""
    from matrixone_tpu.vm import fusion

    # an ORDER BY on the definition is ignored for maintenance: backing
    # table storage is unordered either way (full refresh inserts rows
    # through the same unordered table)
    while isinstance(node, P.Sort):
        node = node.child
    proj = None
    if isinstance(node, P.Project):
        proj = node
        node = node.child
    if not isinstance(node, P.Aggregate):
        return _reason("not a single group-by aggregate")
    agg = node
    if not agg.group_keys:
        return _reason("scalar aggregates (no GROUP BY) degrade to "
                       "full refresh")
    filters: List[BoundExpr] = []
    node = agg.child
    while isinstance(node, P.Filter):
        filters.append(node.pred)
        node = node.child
    if not isinstance(node, P.Scan):
        return _reason("source is not a single base-table scan")
    scan = node
    if scan.as_of_ts is not None:
        return _reason("AS OF scans are immutable; use full refresh")
    filters = list(scan.filters) + filters

    # every expression the maintainer evaluates over delta rows must be
    # in the traceable subset (the fused-fragment contract) — that is
    # both the jit guarantee and the "no host-state surprises" guard
    probe = fusion._ExprInfo()
    for f in filters:
        if not fusion._analyze_expr(f, probe):
            return _reason("filter expression is not maintainable")
    for k in agg.group_keys:
        if not fusion._analyze_expr(k, probe):
            return _reason("group key expression is not maintainable")
    for a in agg.aggs:
        if a.distinct:
            return _reason("DISTINCT aggregates are not maintainable")
        if a.func not in MAINTAINABLE_AGGS:
            return _reason(f"{a.func}() is not maintainable")
        if a.arg is not None:
            if a.func in ("min", "max") and a.arg.dtype.is_varlen:
                return _reason("string MIN/MAX is not maintainable")
            if not fusion._analyze_expr(a.arg, probe):
                return _reason("aggregate argument is not maintainable")

    # the projection above the aggregate must be a pure rename of the
    # aggregate's outputs, covering every group key (the maintainer
    # addresses backing rows by key values)
    nkeys = len(agg.group_keys)
    agg_names = [n for n, _ in agg.schema]
    out_cols: List[tuple] = []
    if proj is None:
        out_schema = list(agg.schema)
        out_cols = [("key", i) for i in range(nkeys)] + \
            [("agg", i) for i in range(len(agg.aggs))]
    else:
        out_schema = list(proj.schema)
        seen = set()
        for e in proj.exprs:
            if not isinstance(e, BoundCol) or e.name not in agg_names:
                return _reason("projection above the aggregate is not a "
                               "pure rename")
            idx = agg_names.index(e.name)
            if idx in seen:
                return _reason("projection repeats an aggregate output")
            seen.add(idx)
            out_cols.append(("key", idx) if idx < nkeys
                            else ("agg", idx - nkeys))
        if {i for i in seen if i < nkeys} != set(range(nkeys)):
            return _reason("projection must keep every group key")
    spec = MaintainSpec(
        source=scan.table, scan_columns=list(scan.columns),
        scan_schema=list(scan.schema), filters=filters,
        group_keys=list(agg.group_keys), aggs=list(agg.aggs),
        out_cols=out_cols, out_schema=out_schema)
    return spec, "incremental"

"""Native host kernels: ctypes bindings over native/mo_native.cpp.

Reference analogue: the cgo bridge (`cgo/lib.go` + `plan/function/
cxcall.go:65`) — here a lazily-compiled shared library (g++ at first use,
cached under native/build/) with numpy fallbacks when no toolchain exists.
Exposes: 64-bit hashing (host/device-consistent splitmix), bloom filters
(runtime join filters / PK dedup), dense bitsets (doc-id pushdown,
tombstone masks).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from matrixone_tpu.utils import san

import numpy as np

_here = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_here, "native", "mo_native.cpp")
_BUILD_DIR = os.path.join(_here, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libmo_native.so")

_lib = None
_lock = san.lock("matrixone_tpu.native._lock")
_tried = False


def _compile() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           _SRC, "-o", _SO + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        # compiler missing/failed/timed out: numpy fallback paths apply
        return False


def get_lib():
    """The loaded native library, or None (numpy fallback paths apply)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.mo_hash64_i64.argtypes = [i64p, ctypes.c_size_t, u64p]
        lib.mo_hash_bytes.restype = ctypes.c_uint64
        lib.mo_hash_bytes.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint64]
        lib.mo_bloom_add.argtypes = [u64p, ctypes.c_size_t, u8p,
                                     ctypes.c_uint64, ctypes.c_int]
        lib.mo_bloom_probe.argtypes = [u64p, ctypes.c_size_t, u8p,
                                       ctypes.c_uint64, ctypes.c_int, u8p]
        lib.mo_bitset_set.argtypes = [u8p, ctypes.c_uint64, i64p,
                                      ctypes.c_size_t]
        lib.mo_bitset_test.argtypes = [u8p, ctypes.c_uint64, i64p,
                                       ctypes.c_size_t, u8p]
        lib.mo_bitset_and.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.mo_bitset_or.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.mo_bitset_count.restype = ctypes.c_int64
        lib.mo_bitset_count.argtypes = [u8p, ctypes.c_size_t]
        lib.mo_sorted_contains.argtypes = [i64p, ctypes.c_size_t, i64p,
                                           ctypes.c_size_t, u8p]
        try:        # an older cached .so may predate the HNSW symbols
            f32p = ctypes.POINTER(ctypes.c_float)
            lib.mo_hnsw_build.restype = ctypes.c_void_p
            lib.mo_hnsw_build.argtypes = [f32p, ctypes.c_int64,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_uint64]
            lib.mo_hnsw_search.argtypes = [ctypes.c_void_p, f32p,
                                           ctypes.c_int64, ctypes.c_int,
                                           ctypes.c_int, i64p, f32p]
            lib.mo_hnsw_n.restype = ctypes.c_int64
            lib.mo_hnsw_n.argtypes = [ctypes.c_void_p]
            lib.mo_hnsw_free.argtypes = [ctypes.c_void_p]
            lib.mo_has_hnsw = True
        except AttributeError:
            lib.mo_has_hnsw = False
        try:        # roaring symbols (added round 4)
            lib.mo_rbm_create.restype = ctypes.c_void_p
            lib.mo_rbm_free.argtypes = [ctypes.c_void_p]
            lib.mo_rbm_add.argtypes = [ctypes.c_void_p, i64p,
                                       ctypes.c_size_t]
            lib.mo_rbm_test.argtypes = [ctypes.c_void_p, i64p,
                                        ctypes.c_size_t, u8p]
            lib.mo_rbm_test_range.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64,
                                              ctypes.c_int64, u8p]
            lib.mo_rbm_count.restype = ctypes.c_int64
            lib.mo_rbm_count.argtypes = [ctypes.c_void_p]
            lib.mo_rbm_bytes.restype = ctypes.c_int64
            lib.mo_rbm_bytes.argtypes = [ctypes.c_void_p]
            lib.mo_rbm_and.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.mo_rbm_or.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.mo_rbm_to_array.restype = ctypes.c_int64
            lib.mo_rbm_to_array.argtypes = [ctypes.c_void_p, i64p,
                                            ctypes.c_int64]
            lib.mo_has_rbm = True
        except AttributeError:
            lib.mo_has_rbm = False
        _lib = lib
        return _lib


def _p(arr, ct):
    return arr.ctypes.data_as(ctypes.POINTER(ct))


# ------------------------------------------------------------------ hashing

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _splitmix_np(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64) + _GOLDEN
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


def hash64(values: np.ndarray) -> np.ndarray:
    """splitmix64 over int64 values — bit-identical to device ops/hash.py."""
    values = np.ascontiguousarray(values, np.int64)
    lib = get_lib()
    out = np.empty(len(values), np.uint64)
    if lib is not None:
        lib.mo_hash64_i64(_p(values, ctypes.c_int64), len(values),
                          _p(out, ctypes.c_uint64))
        return out
    return _splitmix_np(values.view(np.uint64))


# ------------------------------------------------------------- bloom filter

class BloomFilter:
    """Runtime-filter bloom (reference: common/bloomfilter + the planner's
    runtime filter push, plan/query_builder.go:2781)."""

    def __init__(self, n_items: int, bits_per_item: int = 10, k: int = 4):
        nbits = max(64, n_items * bits_per_item)
        self.nbits = int(nbits)
        self.k = k
        self.bits = np.zeros((self.nbits + 7) // 8, np.uint8)

    def add_hashes(self, hashes: np.ndarray):
        hashes = np.ascontiguousarray(hashes, np.uint64)
        lib = get_lib()
        if lib is not None:
            lib.mo_bloom_add(_p(hashes, ctypes.c_uint64), len(hashes),
                             _p(self.bits, ctypes.c_uint8), self.nbits,
                             self.k)
            return
        h2 = _splitmix_np(hashes)
        for j in range(self.k):
            with np.errstate(over="ignore"):
                bit = (hashes + np.uint64(j) * h2) % np.uint64(self.nbits)
            np.bitwise_or.at(self.bits, (bit >> np.uint64(3)).astype(np.int64),
                             (np.uint8(1) << (bit & np.uint64(7))).astype(np.uint8))

    def probe_hashes(self, hashes: np.ndarray) -> np.ndarray:
        hashes = np.ascontiguousarray(hashes, np.uint64)
        lib = get_lib()
        out = np.empty(len(hashes), np.uint8)
        if lib is not None:
            lib.mo_bloom_probe(_p(hashes, ctypes.c_uint64), len(hashes),
                               _p(self.bits, ctypes.c_uint8), self.nbits,
                               self.k, _p(out, ctypes.c_uint8))
            return out.astype(bool)
        hit = np.ones(len(hashes), bool)
        h2 = _splitmix_np(hashes)
        for j in range(self.k):
            with np.errstate(over="ignore"):
                bit = (hashes + np.uint64(j) * h2) % np.uint64(self.nbits)
            hit &= (self.bits[(bit >> np.uint64(3)).astype(np.int64)]
                    >> (bit & np.uint64(7)).astype(np.uint8)) & 1 > 0
        return hit

    def add_int64(self, values: np.ndarray):
        self.add_hashes(hash64(values))

    def probe_int64(self, values: np.ndarray) -> np.ndarray:
        return self.probe_hashes(hash64(values))


# ----------------------------------------------------------------- bitsets

class Bitset:
    """Dense row-id bitset (reference: cgo/cbitmap.c, docfilter exact
    bitset used for index->scan doc-id pushdown)."""

    def __init__(self, nbits: int):
        self.nbits = int(nbits)
        self.bits = np.zeros((self.nbits + 7) // 8, np.uint8)

    def set_ids(self, ids: np.ndarray):
        ids = np.ascontiguousarray(ids, np.int64)
        lib = get_lib()
        if lib is not None:
            lib.mo_bitset_set(_p(self.bits, ctypes.c_uint8), self.nbits,
                              _p(ids, ctypes.c_int64), len(ids))
            return
        ok = ids[(ids >= 0) & (ids < self.nbits)]
        np.bitwise_or.at(self.bits, ok >> 3,
                         (np.uint8(1) << (ok & 7).astype(np.uint8)))

    def test_ids(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        lib = get_lib()
        if lib is not None:
            out = np.empty(len(ids), np.uint8)
            lib.mo_bitset_test(_p(self.bits, ctypes.c_uint8), self.nbits,
                               _p(ids, ctypes.c_int64), len(ids),
                               _p(out, ctypes.c_uint8))
            return out.astype(bool)
        out = np.zeros(len(ids), bool)
        ok = (ids >= 0) & (ids < self.nbits)
        idx = ids[ok]
        out[ok] = (self.bits[idx >> 3] >> (idx & 7).astype(np.uint8)) & 1 > 0
        return out

    def count(self) -> int:
        lib = get_lib()
        if lib is not None:
            return int(lib.mo_bitset_count(_p(self.bits, ctypes.c_uint8),
                                           len(self.bits)))
        return int(np.unpackbits(self.bits).sum())

    def and_(self, other: "Bitset"):
        lib = get_lib()
        if lib is not None:
            lib.mo_bitset_and(_p(self.bits, ctypes.c_uint8),
                              _p(other.bits, ctypes.c_uint8), len(self.bits))
        else:
            self.bits &= other.bits

    def or_(self, other: "Bitset"):
        lib = get_lib()
        if lib is not None:
            lib.mo_bitset_or(_p(self.bits, ctypes.c_uint8),
                             _p(other.bits, ctypes.c_uint8), len(self.bits))
        else:
            self.bits |= other.bits


def sorted_contains(haystack: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Membership of ids in a sorted haystack (tombstone filter hot path)."""
    haystack = np.ascontiguousarray(haystack, np.int64)
    ids = np.ascontiguousarray(ids, np.int64)
    lib = get_lib()
    if lib is not None:
        out = np.empty(len(ids), np.uint8)
        lib.mo_sorted_contains(_p(haystack, ctypes.c_int64), len(haystack),
                               _p(ids, ctypes.c_int64), len(ids),
                               _p(out, ctypes.c_uint8))
        return out.astype(bool)
    pos = np.searchsorted(haystack, ids)
    pos_c = np.clip(pos, 0, len(haystack) - 1)
    return (pos < len(haystack)) & (haystack[pos_c] == ids) \
        if len(haystack) else np.zeros(len(ids), bool)


# --------------------------------------------------------- roaring bitmap

class RoaringBitmap:
    """Compressed id set (reference: cgo/croaring.c + CRoaring —
    redesigned as 16-bit-bucketed array/bitmap containers in
    native/mo_native.cpp). The engine's sparse tombstone/doc-id filters:
    bit-identical answers to a dense bitset at a fraction of the memory
    when the live fraction is small. Falls back to a sorted numpy array
    (searchsorted membership) without the native library."""

    def __init__(self, ids=None):
        lib = get_lib()
        self._lib = lib if lib is not None and lib.mo_has_rbm else None
        if self._lib is not None:
            self._h = self._lib.mo_rbm_create()
        else:
            self._sorted = np.zeros(0, np.int64)
        if ids is not None and len(ids):
            self.add(ids)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            lib.mo_rbm_free(self._h)
            self._h = None

    def add(self, ids) -> None:
        ids = np.ascontiguousarray(ids, np.int64)
        if self._lib is not None:
            self._lib.mo_rbm_add(self._h, _p(ids, ctypes.c_int64),
                                 len(ids))
        else:
            self._sorted = np.union1d(self._sorted, ids[ids >= 0])

    def test(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.zeros(len(ids), np.uint8)
        if self._lib is not None:
            self._lib.mo_rbm_test(self._h, _p(ids, ctypes.c_int64),
                                  len(ids), _p(out, ctypes.c_uint8))
            return out.astype(np.bool_)
        return np.isin(ids, self._sorted)

    def test_range(self, lo: int, hi: int) -> np.ndarray:
        """Membership of every id in [lo, hi) — the scan-chunk tombstone
        path (a chunk's gids are contiguous)."""
        n = max(int(hi) - int(lo), 0)
        if self._lib is not None:
            out = np.zeros(n, np.uint8)
            self._lib.mo_rbm_test_range(self._h, int(lo), int(hi),
                                        _p(out, ctypes.c_uint8))
            return out.astype(np.bool_)
        i0, i1 = np.searchsorted(self._sorted, [lo, hi])
        out = np.zeros(n, np.bool_)
        out[self._sorted[i0:i1] - lo] = True
        return out

    def and_(self, other: "RoaringBitmap") -> None:
        if self._lib is not None and other._lib is not None:
            self._lib.mo_rbm_and(self._h, other._h)
        else:
            self._sorted = np.intersect1d(self.to_array(),
                                          other.to_array())
            if self._lib is not None:
                self._lib.mo_rbm_free(self._h)
                self._lib = None

    def or_(self, other: "RoaringBitmap") -> None:
        if self._lib is not None and other._lib is not None:
            self._lib.mo_rbm_or(self._h, other._h)
        else:
            merged = np.union1d(self.to_array(), other.to_array())
            if self._lib is not None:
                self._lib.mo_rbm_free(self._h)
                self._lib = None
            self._sorted = merged

    def count(self) -> int:
        if self._lib is not None:
            return int(self._lib.mo_rbm_count(self._h))
        return len(self._sorted)

    def nbytes(self) -> int:
        """Memory footprint (the compression claim)."""
        if self._lib is not None:
            return int(self._lib.mo_rbm_bytes(self._h))
        return int(self._sorted.nbytes)

    def to_array(self) -> np.ndarray:
        if self._lib is None:
            return self._sorted.copy()
        n = self.count()
        out = np.empty(n, np.int64)
        got = self._lib.mo_rbm_to_array(self._h, _p(out, ctypes.c_int64),
                                        n)
        return out[:got]

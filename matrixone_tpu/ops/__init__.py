from matrixone_tpu.ops import agg, distance, filter, hash, scalar, sort

__all__ = ["agg", "distance", "filter", "hash", "scalar", "sort"]

"""Group-by aggregation as sort/segment kernels — no hash tables.

TPU-native replacement for the reference's hash-table group-by
(`pkg/sql/colexec/group` + `pkg/container/hashtable` + `aggexec`). Pointer-
chasing hash maps don't map to a systolic/vector machine; instead:

    row hash (ops.hash) -> argsort -> boundary detect -> cumsum group ids
    -> jax.ops.segment_{sum,min,max} scatter reductions

which is sorts + scans + scatters, all native XLA ops. `max_groups` is a
static upper bound (compile-time); exceeding it is detected and the caller
re-runs with the next bucket — the analogue of the reference growing its
hash table, quantized to keep the jit cache small.

Sums over integers/decimals are exact (int64): bit-identical to the CPU
oracle regardless of reduction order — this is why Q1's money columns are
DECIMAL(scaled int64), matching the reference's decimal aggregators
(`colexec/aggexec/sum.go`).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from matrixone_tpu.ops import hash as mohash

import numpy as _np

_NULL_GROUP_SENTINEL = _np.uint64(0xFFFFFFFFFFFFFFFF)


class GroupIds(NamedTuple):
    gids: jnp.ndarray        # int32 [n]: group id per row (garbage for padding rows)
    num_groups: jnp.ndarray  # int32 scalar: number of distinct groups
    rep_rows: jnp.ndarray    # int32 [max_groups]: a representative row per group


def group_ids(key_columns: Sequence[jnp.ndarray],
              key_validities: Sequence[Optional[jnp.ndarray]],
              row_mask: jnp.ndarray,
              max_groups: int) -> GroupIds:
    """Assign dense group ids to rows by their key tuple.

    Grouping is by 64-bit row hash: with splitmix64-quality mixing the
    collision probability at 1M distinct keys is ~2^-44 per pair; the BVT
    harness cross-checks results against the numpy oracle. Padding rows
    (row_mask False) sort last and take no group id.
    """
    h = mohash.hash_columns(key_columns, key_validities)
    h = jnp.where(row_mask, h, _NULL_GROUP_SENTINEL)
    order = jnp.argsort(h).astype(jnp.int32)     # padding rows last
    sorted_h = h[order]
    sorted_mask = row_mask[order]
    first = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                             sorted_h[1:] != sorted_h[:-1]])
    first = first & sorted_mask
    gid_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    num_groups = jnp.where(jnp.any(sorted_mask), jnp.max(
        jnp.where(sorted_mask, gid_sorted, -1)) + 1, 0)
    # scatter group ids back to row order
    n = h.shape[0]
    gids = jnp.zeros((n,), jnp.int32).at[order].set(gid_sorted)
    # representative row for each group = first row (in sorted order)
    rep_target = jnp.where(first, gid_sorted, max_groups)
    rep_rows = jnp.zeros((max_groups + 1,), jnp.int32).at[rep_target].set(order)[:max_groups]
    return GroupIds(gids=gids, num_groups=num_groups.astype(jnp.int32),
                    rep_rows=rep_rows)


def _masked(values: jnp.ndarray, mask: jnp.ndarray, fill) -> jnp.ndarray:
    return jnp.where(mask, values, jnp.asarray(fill, values.dtype))


@partial(jax.jit, static_argnames=("max_groups", "use_pallas"))
def seg_sum(values, gids, mask, max_groups: int, use_pallas: bool = False):
    """Masked segment sum, through the hand-kernel dispatch seam
    (ops/kernels.py). use_pallas (session `SET use_pallas = 1` OR the
    MO_HAND_KERNELS policy, resolved in vm/compile and threaded through
    AggOp as a static arg) routes float32 sums to the hand-tiled
    one-hot-matmul kernel; exact int64/decimal and f64 sums always stay
    on the XLA scatter path (MXU accumulation is float)."""
    from matrixone_tpu.ops import kernels as HK
    return HK.grouped_scatter_add(values, gids, mask, max_groups,
                                  use_pallas=use_pallas)


@partial(jax.jit, static_argnames=("max_groups",))
def seg_count(gids, mask, max_groups: int):
    return jax.ops.segment_sum(mask.astype(jnp.int64), gids,
                               num_segments=max_groups)


def _reduce_fill(dtype, for_min: bool):
    """Identity element for min/max over dtype (BOOL included)."""
    if jnp.issubdtype(dtype, jnp.bool_):
        return True if for_min else False
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    info = jnp.iinfo(dtype)
    return info.max if for_min else info.min


@partial(jax.jit, static_argnames=("max_groups",))
def seg_min(values, gids, mask, max_groups: int):
    is_bool = jnp.issubdtype(values.dtype, jnp.bool_)
    v = _masked(values.astype(jnp.int32) if is_bool else values, mask,
                _reduce_fill(values.dtype, True))
    out = jax.ops.segment_min(v, gids, num_segments=max_groups)
    return out.astype(jnp.bool_) if is_bool else out


@partial(jax.jit, static_argnames=("max_groups",))
def seg_max(values, gids, mask, max_groups: int):
    is_bool = jnp.issubdtype(values.dtype, jnp.bool_)
    v = _masked(values.astype(jnp.int32) if is_bool else values, mask,
                _reduce_fill(values.dtype, False))
    out = jax.ops.segment_max(v, gids, num_segments=max_groups)
    return out.astype(jnp.bool_) if is_bool else out


def dense_slot_strides(sizes: Sequence[int], null_slots: bool = True
                       ) -> Tuple[Tuple[int, ...], int]:
    """Row-major strides over the dense key space. With null_slots each
    key contributes (size + 1) slots — the extra slot is its NULL group;
    without, exactly `size` slots (a chunk proven all-valid)."""
    strides = []
    acc = 1
    for s in reversed(sizes):
        strides.append(acc)
        acc *= s + (1 if null_slots else 0)
    return tuple(reversed(strides)), acc


@partial(jax.jit, static_argnames=("sizes", "with_null"))
def dense_lane_partials(codes, valids, row_mask, int_vals, int_masks,
                        float_vals, float_masks, *, sizes, with_null):
    """Small-key grouped partials without hash or sort.

    When every group key is a dictionary code (or bool) the group id is
    a mixed-radix digit expansion over the key space — no 64-bit hash,
    no argsort over the batch. Each (deduplicated) partial lane then
    reduces per group as a masked sum; XLA's multi-output fusion turns
    the G x L reduction family over shared inputs into a handful of
    passes, which profiles ~4x faster than a segment_sum scatter per
    field on CPU and avoids the scatter path on TPU entirely.

    Lanes: parallel (value, mask) tuples per dtype class. value None
    means "count the mask"; mask None means "row_mask only". Returns
    (int64 lanes [Li, G], float64 lanes [Lf, G], rows [G]) with G the
    compact (with_null=False) or NULL-slotted key space.
    """
    strides, G = dense_slot_strides(sizes, null_slots=with_null)
    n = row_mask.shape[0]
    gid = jnp.zeros((n,), jnp.int32)
    for c, v, s, st in zip(codes, valids, sizes, strides):
        slot = jnp.clip(c.astype(jnp.int32), 0, s - 1)
        if with_null:
            slot = jnp.where(v, slot, jnp.asarray(s, jnp.int32))
        gid = gid + slot * jnp.asarray(st, jnp.int32)

    def lane_sums(vals, masks, dtype):
        outs = []
        for g in range(G):
            sel = (gid == g) & row_mask
            row = []
            for v, m in zip(vals, masks):
                sm = sel if m is None else sel & m
                row.append(jnp.sum(sm) if v is None
                           else jnp.sum(jnp.where(sm, v.astype(dtype),
                                                  jnp.asarray(0, dtype))))
            outs.append(row)
        return jnp.asarray(outs, dtype).T           # (L, G)

    ints = lane_sums(int_vals, int_masks, jnp.int64)
    floats = lane_sums(float_vals, float_masks, jnp.float64)
    rows = jnp.asarray([jnp.sum((gid == g) & row_mask)
                        for g in range(G)], jnp.int64)
    return ints, floats, rows


def gather_keys(key_columns: Sequence[jnp.ndarray],
                key_validities: Sequence[Optional[jnp.ndarray]],
                rep_rows: jnp.ndarray) -> Tuple[list, list]:
    """Materialize one key value per group from representative rows."""
    out_vals, out_vals_valid = [], []
    for data, valid in zip(key_columns, key_validities):
        out_vals.append(data[rep_rows])
        if valid is None:
            out_vals_valid.append(jnp.ones(rep_rows.shape, jnp.bool_))
        else:
            out_vals_valid.append(valid[rep_rows])
    return out_vals, out_vals_valid


# scalar (no GROUP BY) aggregates ------------------------------------------

def scalar_sum(values, mask):
    return jnp.sum(_masked(values, mask, 0))


def scalar_count(mask):
    return jnp.sum(mask.astype(jnp.int64))


def scalar_min(values, mask):
    is_bool = jnp.issubdtype(values.dtype, jnp.bool_)
    v = values.astype(jnp.int32) if is_bool else values
    out = jnp.min(_masked(v, mask, _reduce_fill(values.dtype, True)))
    return out.astype(jnp.bool_) if is_bool else out


def scalar_max(values, mask):
    is_bool = jnp.issubdtype(values.dtype, jnp.bool_)
    v = values.astype(jnp.int32) if is_bool else values
    out = jnp.max(_masked(v, mask, _reduce_fill(values.dtype, False)))
    return out.astype(jnp.bool_) if is_bool else out

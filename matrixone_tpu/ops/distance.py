"""Vector distance kernels on the MXU.

TPU-native replacement for the reference's distance stack:
`pkg/vectorize/moarray/external.go:181 L2Distance / :201 CosineDistance`
(gonum CPU), `cgo/xcall.h:81 xcall_l2distance_f32/64` (SIMD C),
`cgo/cuda/mocl.cu` (CUDA), and cuVS brute-force (`cgo/cuvs/distance_c.cpp`).

Design: every pairwise distance is expressed as a matmul so the 128x128
systolic array does the FLOPs:

    ||x - q||^2 = ||x||^2 + ||q||^2 - 2 x.q      (one X @ Q^T)
    cosine(x,q) = 1 - x.q / (||x|| ||q||)        (one matmul on normalized)

Inputs may be bf16 (2x HBM bandwidth, 2x+ MXU rate) with f32 accumulation
via `preferred_element_type` — the same precision split cuVS uses for its
fp16 path (`cgo/cuvs/quantize.hpp`). Exact f32 paths exist for the
bit-identical oracle comparison required by BASELINE.json.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _matmul_xqT(x: jnp.ndarray, q: jnp.ndarray, compute_dtype) -> jnp.ndarray:
    """x [n,d] @ q[b,d]^T -> [n,b] with f32 accumulation.

    When no compute_dtype override is given, request HIGHEST precision:
    TPU matmuls otherwise run f32 inputs through bf16 passes (~1e-3 rel
    error — measured on v5e), which silently reorders near-tie top-k
    results. The fast path passes compute_dtype=bfloat16 explicitly.
    """
    precision = jax.lax.Precision.HIGHEST
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        q = q.astype(compute_dtype)
        precision = jax.lax.Precision.DEFAULT
    return jax.lax.dot_general(
        x, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)


@partial(jax.jit, static_argnames=("compute_dtype", "use_pallas"))
def l2_distance_sq(x: jnp.ndarray, q: jnp.ndarray,
                   compute_dtype=None, use_pallas=None) -> jnp.ndarray:
    """Squared L2 distances [n, b] between rows of x [n,d] and q [b,d].

    With use_pallas (session `SET use_pallas = 1`, or the MO_USE_PALLAS
    env default when the kwarg is None) and tile-aligned shapes, the
    exact-f32 path runs the hand-tiled Pallas kernel
    (ops/pallas_kernels.py) instead of the XLA default — same math,
    explicit VMEM staging."""
    from matrixone_tpu.ops import pallas_kernels as PK
    enabled = PK.use_pallas() if use_pallas is None else use_pallas
    if enabled and compute_dtype is None and x.shape[0] % 1024 == 0:
        return PK.l2_distance_sq_pallas(x, q, tile_m=1024)
    xq = _matmul_xqT(x, q, compute_dtype)
    x2 = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    q2 = jnp.sum(jnp.square(q.astype(jnp.float32)), axis=-1)
    return jnp.maximum(x2 + q2[None, :] - 2.0 * xq, 0.0)


@partial(jax.jit, static_argnames=("compute_dtype",))
def l2_distance(x: jnp.ndarray, q: jnp.ndarray, compute_dtype=None) -> jnp.ndarray:
    return jnp.sqrt(l2_distance_sq(x, q, compute_dtype=compute_dtype))


def _seq_sum_lastdim(x: jnp.ndarray) -> jnp.ndarray:
    """Sequential (left-fold) sum over the last dim — a *defined* reduction
    order, so results are bit-identical to a sequential CPU oracle. XLA's
    default reduce reassociates; the north star requires reproducible float
    reductions (SURVEY.md §7 'bit-identical float reductions')."""
    xt = jnp.moveaxis(x, -1, 0)
    return jax.lax.scan(lambda acc, v: (acc + v, None),
                        jnp.zeros(xt.shape[1:], x.dtype), xt)[0]


@jax.jit
def l2_distance_rowwise(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Row-to-row l2_distance(a[i], b[i]) — the SQL scalar function shape
    (`SELECT l2_distance(col, const)`), f64 accumulation in defined
    sequential order (reference CPU path: moarray/external.go:181)."""
    d = a.astype(jnp.float64) - b.astype(jnp.float64)
    return jnp.sqrt(_seq_sum_lastdim(d * d))


@jax.jit
def inner_product_rowwise(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _seq_sum_lastdim(a.astype(jnp.float64) * b.astype(jnp.float64))


@jax.jit
def cosine_distance_rowwise(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a64, b64 = a.astype(jnp.float64), b.astype(jnp.float64)
    num = _seq_sum_lastdim(a64 * b64)
    den = jnp.sqrt(_seq_sum_lastdim(a64 * a64) * _seq_sum_lastdim(b64 * b64))
    return 1.0 - num / den


@partial(jax.jit, static_argnames=("compute_dtype",))
def inner_product(x: jnp.ndarray, q: jnp.ndarray, compute_dtype=None) -> jnp.ndarray:
    """Pairwise inner products [n, b]."""
    return _matmul_xqT(x, q, compute_dtype)


def normalize(x: jnp.ndarray, eps: float = 1e-30) -> jnp.ndarray:
    """L2-normalize rows (host-side prep for cosine -> inner product)."""
    n = jnp.linalg.norm(x.astype(jnp.float32), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) / jnp.maximum(n, eps)).astype(x.dtype)


@partial(jax.jit, static_argnames=("compute_dtype",))
def cosine_distance(x: jnp.ndarray, q: jnp.ndarray,
                    compute_dtype=None) -> jnp.ndarray:
    """Pairwise cosine distance [n, b] = 1 - cos_similarity."""
    xq = _matmul_xqT(x, q, compute_dtype)
    xn = jnp.linalg.norm(x.astype(jnp.float32), axis=-1, keepdims=True)
    qn = jnp.linalg.norm(q.astype(jnp.float32), axis=-1)
    den = jnp.maximum(xn * qn[None, :], 1e-30)
    return 1.0 - xq / den

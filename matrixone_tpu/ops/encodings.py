"""Narrow column encodings: per-column storage/compute dtype selection.

The decoded-column working set is the scan path's bandwidth bill, and
most of it is wider than the data: dictionary codes for join/group keys
ship as int32 even when the dictionary holds 20 strings, and float
aggregate lanes ride f32/f64 through the fused program even though the
accumulator (not the element) carries the precision.  This module is
the one policy point for narrowing both, the way PR 3 measured
bf16-vs-f32 per backend for IVF — generalized to per-column choice:

  * **dict codes** (lossless, bit-identical): int8 when the dictionary
    fits 128 entries, int16 under 32768, int32 otherwise.  Codes hash,
    compare and gather identically at any width (ops/hash widens to
    int64 before mixing; jnp comparisons promote), so this is purely a
    memory/bandwidth choice.  Applied at the host->device boundary
    (vm/operators.chunk_to_execbatch); a dictionary that grows past a
    width boundary flips the code dtype, which the fragment compile key
    carries (vm/fusion._runtime_key includes the array dtype), so a
    widened dict re-traces instead of colliding.
  * **bf16 float-agg lanes** (lossy, documented tolerance): FLOAT32
    aggregate *input* lanes in the fused dense-agg terminal round to
    bfloat16 before the (always-f64) accumulation — elements lose
    mantissa, sums do not lose order.  The documented tolerance is
    bf16's 8 mantissa bits: ~2-3 significant decimal digits per
    element, so relative error of a sum of same-signed elements stays
    under ~0.4%.  FLOAT64 lanes are never narrowed (the SQL `double`
    contract), and the exact-decimal discipline is untouched: decimals
    and counts stay scaled int64 everywhere.  Predicates, group keys,
    join keys and projections always evaluate at full width — flipping
    a row across a filter is a wrong answer, not a tolerance.

The policy is chosen per backend: `MO_NARROW_ENCODINGS` is `auto` by
default (on for TPU, off for the CPU fallback, where narrow loads
de-vectorize instead of saving bandwidth), `1` forces it on (the moqa
`narrow-encodings` lockstep pair runs this against the f32/int64
baseline), `0` kills it.
"""

from __future__ import annotations

import os

import numpy as np


def _flag() -> str:
    return os.environ.get("MO_NARROW_ENCODINGS", "auto").lower()


def enabled() -> bool:
    """Resolve the policy for this process/backend.  Read on the host
    at batch-staging and trace time only — every consumer records the
    resolved value in its compile key (directly or via the narrowed
    array dtypes), so a flip re-traces instead of colliding."""
    v = _flag()
    if v in ("1", "on", "true"):
        return True
    if v in ("0", "off", "false", ""):
        return False
    import jax
    return jax.default_backend() == "tpu"


def signature() -> tuple:
    """Compile-key component: the resolved policy.  The narrowed input
    dtypes already distinguish most flips, but the bf16 lane choice is
    applied inside the trace (not visible in the input signature), so
    the key must carry it explicitly."""
    return ("narrow", enabled())


# ------------------------------------------------------------ dict codes

def code_np_dtype(dict_len: int) -> np.dtype:
    """Narrowest signed int dtype holding codes 0..dict_len-1."""
    if dict_len <= (1 << 7):
        return np.dtype(np.int8)
    if dict_len <= (1 << 15):
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def narrow_codes(arr, dict_len: int):
    """Cast a code array (numpy or jax) to its narrowest width.  A
    no-op when the policy is off or the array is already narrow."""
    if not enabled():
        return arr
    cdt = code_np_dtype(dict_len)
    if arr.dtype == cdt:
        return arr
    if np.dtype(arr.dtype).itemsize < cdt.itemsize:
        return arr                      # never widen here
    return arr.astype(cdt)


# --------------------------------------------------------- bf16 agg lanes

def narrow_lane(val):
    """Round one float aggregate-input lane to bf16 (FLOAT32 only;
    f64 and non-floats pass through).  Called inside the fused trace —
    the accumulation downstream stays f64, so only element precision
    narrows, never reduction order."""
    import jax.numpy as jnp
    if enabled() and val is not None and val.dtype == jnp.float32:
        return val.astype(jnp.bfloat16)
    return val

"""Selection / compaction kernels (reference: colexec/filter + Vector.Shrink).

Filters produce a *mask*, not a compacted batch — downstream kernels
(aggregate, join, top-k) consume masks directly so the filter fuses into
them and no data moves. `compact()` exists for when cardinality drops
enough that shipping the dense remainder is worth a scatter (the reference
always compacts because CPU SIMD wants dense rows; TPUs prefer masks).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from matrixone_tpu.container.device import DeviceBatch, DeviceColumn


def predicate_mask(pred: DeviceColumn, batch: DeviceBatch) -> jnp.ndarray:
    """bool mask of rows passing a predicate column (NULL -> excluded).

    Deliberately does NOT fold in batch.row_mask(): the pipeline's
    ExecBatch.mask tracks live rows (which are non-contiguous after joins);
    callers AND this mask into it."""
    data = pred.data
    valid = pred.validity
    if pred.is_const:
        n = batch.padded_len
        data = jnp.broadcast_to(data, (n,))
        valid = jnp.broadcast_to(valid, (n,))
    return data & valid


def compact(batch: DeviceBatch, mask: jnp.ndarray, capacity: int) -> DeviceBatch:
    """Gather masked rows to the front; result padded to `capacity` rows."""
    (idx,) = jnp.nonzero(mask, size=capacity, fill_value=0)
    n_out = jnp.sum(mask.astype(jnp.int32))
    out_cols = {}
    for name, col in batch.columns.items():
        if col.is_const:
            out_cols[name] = col
            continue
        keep = jnp.arange(capacity, dtype=jnp.int32) < n_out
        out_cols[name] = DeviceColumn(
            data=col.data[idx],
            validity=col.validity[idx] & keep,
            dtype=col.dtype)
    return DeviceBatch(columns=out_cols, n_rows=n_out)


def gather(batch: DeviceBatch, indices: jnp.ndarray,
           n_rows: jnp.ndarray) -> DeviceBatch:
    """Row gather (ORDER BY / top-k materialization)."""
    out_cols = {}
    keep = jnp.arange(indices.shape[0], dtype=jnp.int32) < n_rows
    for name, col in batch.columns.items():
        if col.is_const:
            out_cols[name] = col
            continue
        out_cols[name] = DeviceColumn(
            data=col.data[indices],
            validity=col.validity[indices] & keep,
            dtype=col.dtype)
    return DeviceBatch(columns=out_cols, n_rows=n_rows.astype(jnp.int32))

"""64-bit hashing on device.

Replaces the reference's hand-written amd64/arm64 assembly hashers
(`pkg/container/hashtable/hash_amd64.s`, xxHash in `thirdparties/`) with a
splitmix64-style finalizer expressed in jnp uint64 ops — XLA lowers these to
int32 pairs on TPU; throughput is fine because hashing always fuses into the
surrounding sort/aggregate pipeline instead of being a separate pass.
"""

from __future__ import annotations

import jax.numpy as jnp

# numpy scalars, NOT jnp: creating a jnp array at import time initializes
# the backend, which must stay lazy (a wedged device would hang imports)
import numpy as _np

_GOLDEN = _np.uint64(0x9E3779B97F4A7C15)
_MIX1 = _np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = _np.uint64(0x94D049BB133111EB)


def splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """Finalizer of splitmix64 (public-domain PRNG): uint64 -> uint64."""
    x = x.astype(jnp.uint64)
    x = (x + _GOLDEN)
    x = (x ^ (x >> jnp.uint64(30))) * _MIX1
    x = (x ^ (x >> jnp.uint64(27))) * _MIX2
    x = x ^ (x >> jnp.uint64(31))
    return x


def hash_column(data: jnp.ndarray) -> jnp.ndarray:
    """Hash one column's values to uint64 (floats hashed by bit pattern)."""
    if data.dtype == jnp.float64:
        bits = data.view(jnp.uint64)
    elif data.dtype == jnp.float32:
        bits = data.view(jnp.uint32).astype(jnp.uint64)
    elif data.dtype == jnp.bool_:
        bits = data.astype(jnp.uint64)
    else:
        bits = data.astype(jnp.int64).view(jnp.uint64)
    return splitmix64(bits)


def combine(h1: jnp.ndarray, h2: jnp.ndarray) -> jnp.ndarray:
    """Order-dependent hash combine (boost::hash_combine shape)."""
    return splitmix64(h1 ^ (h2 + _GOLDEN + (h1 << jnp.uint64(6)) + (h1 >> jnp.uint64(2))))


def hash_columns(columns, validities=None) -> jnp.ndarray:
    """Row hash over multiple key columns; NULLs hash to a fixed sentinel so
    `NULL` groups together (SQL GROUP BY treats NULLs as equal —
    reference: hashmap's hasNull handling)."""
    out = None
    for i, data in enumerate(columns):
        h = hash_column(data)
        if validities is not None and validities[i] is not None:
            h = jnp.where(validities[i], h, jnp.uint64(0xDEADBEEFCAFEF00D))
        out = h if out is None else combine(out, h)
    return out

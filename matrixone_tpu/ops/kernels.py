"""Hand-kernel dispatch seam: the ONE routing point between XLA's
fused programs and the hand-written Pallas kernels for the two inner
loops the profile says XLA loses on TPU — the hash-join probe's sorted
search and the grouped-agg group-scatter.

Paper L4 analogue: `cgo/xcall.c` — hand SIMD/CUDA kernels live NEXT TO
the codegen'd operators behind one dispatch table, so "use the hand
loop" is a routing decision, not a code fork.  Here likewise: callers
(vm/join, ops/agg) call through this module and never name Pallas
directly; the choice is

  * `MO_HAND_KERNELS=0` — kill switch: always the XLA path (the
    rollback story when a kernel misbehaves on new hardware);
  * `MO_HAND_KERNELS=1` — force on (tier-1 runs the Pallas kernels in
    interpret mode on cpu this way; the bit-identity drills and the
    moqa padding canary ride it);
  * unset / `auto` — on for the TPU backend, off for the cpu fallback
    (XLA:CPU's native scatter/searchsorted beat interpreted Pallas by
    orders of magnitude).

Identity contract: `sorted_lookup` is bit-identical to the XLA path on
EVERY backend by construction (integer count, no rounding, no order
sensitivity — tools/precheck --kernel-smoke enforces it).
`grouped_scatter_add` routes only float32 sums to the MXU one-hot
kernel (same rule the session `SET use_pallas` path always had);
exact int64/decimal/f64 sums stay on the XLA scatter unconditionally.
The resolved routing is baked into traced executables, so every fused
compile key carries `signature()` (vm/fusion, vm/fusion_join).
"""

from __future__ import annotations

import os


def _flag() -> str:
    return os.environ.get("MO_HAND_KERNELS", "auto").lower()


def enabled() -> bool:
    """Resolve the hand-kernel routing for this process/backend.  Read
    host-side at trace/compile time only; consumers record it in their
    compile keys so a flip re-traces instead of colliding."""
    v = _flag()
    if v in ("1", "on", "true"):
        return True
    if v in ("0", "off", "false"):
        return False
    import jax
    return jax.default_backend() == "tpu"


def signature() -> tuple:
    """Compile-key component: the resolved routing (the kernels are
    trace-time choices, invisible in input dtypes/shapes)."""
    return ("hand_kernels", enabled())


def sorted_lookup(sorted_vals, queries):
    """searchsorted-left over the sorted build-side hashes (uint64):
    the probe's per-row entry point into the hash run.  Pallas
    count-less-than kernel when enabled, jnp.searchsorted otherwise —
    bit-identical either way."""
    import jax.numpy as jnp
    if enabled():
        from matrixone_tpu.ops import pallas_kernels as PK
        return PK.sorted_search_pallas(sorted_vals, queries)
    return jnp.searchsorted(sorted_vals, queries).astype(jnp.int32)


def grouped_scatter_add(values, gids, mask, max_groups: int,
                        use_pallas: bool = False):
    """Masked segment sum — the grouped-agg group-scatter.  float32
    values ride the one-hot-matmul Pallas kernel when routing says so;
    every exact dtype (int64 counts/decimals, f64) stays on the XLA
    scatter.  `use_pallas` must be resolved OUTSIDE any jit (it picks
    the traced program): vm/compile ORs the session `SET use_pallas`
    with `enabled()` and threads it as a static jit arg, so the routing
    is part of the jit cache key — this function never reads the env."""
    import jax.numpy as jnp
    if (use_pallas and values.dtype == jnp.float32
            and max_groups <= 4096 and values.shape[0] > 0):
        from matrixone_tpu.ops import pallas_kernels as PK
        n = values.shape[0]
        tile = 512
        padded = ((n + tile - 1) // tile) * tile
        if padded != n:
            values = jnp.pad(values, (0, padded - n))
            gids = jnp.pad(gids, (0, padded - n))
            mask = jnp.pad(mask, (0, padded - n))   # pads False
        return PK.segment_sum_pallas(values, gids, mask,
                                     num_segments=max_groups,
                                     tile_n=tile)
    import jax
    v = jnp.where(mask, values, jnp.asarray(0, values.dtype))
    return jax.ops.segment_sum(v, gids, num_segments=max_groups)

"""Hand-tiled Pallas TPU kernels for the hottest inner loops.

Reference analogue: the hand-written SIMD/CUDA kernels (`cgo/arith.c`,
`cgo/cuda/mocl.cu`) — here Pallas grid kernels that keep the MXU fed from
VMEM explicitly instead of relying on XLA's default tiling.

`l2_distance_sq_pallas`: one grid step loads a [TM, D] tile of the
collection and the full query block [B, D] into VMEM, runs the
[TM, D] @ [D, B] matmul on the MXU, and fuses the ||x||^2 row-norm
computation + (x2 + q2 - 2xq) epilogue into the same kernel — the
epilogue never round-trips through HBM. Falls back to interpret mode off
TPU (tests run on the CPU mesh), and callers opt in via
MO_USE_PALLAS=1 (ops.distance keeps the XLA path as default until the
kernel is profiled on hardware).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, q_ref, q2_ref, out_ref):
    x = x_ref[:]                                   # [TM, D] f32
    q = q_ref[:]                                   # [B, D]  f32
    xq = jax.lax.dot_general(
        x, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # [TM, B] on the MXU
    x2 = jnp.sum(x * x, axis=1, keepdims=True)     # fused row norms (VPU)
    out_ref[:] = jnp.maximum(x2 + q2_ref[:] - 2.0 * xq, 0.0)


@functools.partial(jax.jit, static_argnames=("tile_m", "interpret"))
def l2_distance_sq_pallas(x: jnp.ndarray, q: jnp.ndarray,
                          tile_m: int = 1024,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Pairwise squared L2 [n, b]; n must be a multiple of tile_m."""
    n, d = x.shape
    b = q.shape[0]
    assert n % tile_m == 0, f"n={n} must be a multiple of tile_m={tile_m}"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    xf = x.astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q2 = jnp.sum(qf * qf, axis=1)[None, :]          # [1, b]
    grid = (n // tile_m,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, d), lambda i: (i, 0)),
            pl.BlockSpec((b, d), lambda i: (0, 0)),
            pl.BlockSpec((1, b), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_m, b), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, b), jnp.float32),
        interpret=interpret,
    )(xf, qf, q2)


def use_pallas() -> bool:
    return os.environ.get("MO_USE_PALLAS") == "1"

"""Hand-tiled Pallas TPU kernels for the hottest inner loops.

Reference analogue: the hand-written SIMD/CUDA kernels (`cgo/arith.c`,
`cgo/cuda/mocl.cu`, `cgo/cuvs/ivf_pq_c.cpp` ADC scoring) — here Pallas
grid kernels that keep the MXU fed from VMEM explicitly instead of
relying on XLA's default tiling.

Kernels:
  * `l2_distance_sq_pallas`     — tiled pairwise L2 with the norm
    epilogue fused (never round-trips through HBM);
  * `l2_distance_sq_masked_pallas` — same with a fused validity mask
    (masked rows score +inf), the filtered-search shape
    (`cgo/cuvs/filter.hpp` bitset prefilter analogue);
  * `segment_sum_pallas`        — one-hot-matmul GROUP BY segment sum:
    the hash-table-free TPU formulation of `colexec/group` partial
    aggregation, riding the MXU instead of scatter units;
  * `adc_score_pallas`          — IVF-PQ asymmetric-distance scoring
    sum_m LUT[g, m, code] as a one-hot matmul per candidate tile
    (`cgo/cuvs` ivf_pq ADC kernel analogue);
  * `sorted_search_pallas`      — the hash-join probe's searchsorted
    over the sorted build hashes as a count-less-than reduction
    (gather-free, VPU compares + integer sum), bit-identical to
    `jnp.searchsorted(side='left')` by construction.

All kernels fall back to interpret mode off TPU (tests run on the CPU
mesh) and are opt-in: sessions enable them with `SET use_pallas = 1`
(reference: `pkg/util/gpumode/gpu_mode.go:37 EffectiveGpuMode` — session
value wins, else the MO_USE_PALLAS env default), because until profiled
on real hardware the XLA default fusion is the trusted path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# --------------------------------------------------------------- gating
def use_pallas() -> bool:
    """Process default (env). Kept for back-compat; prefer
    effective_use_pallas(session_value)."""
    return os.environ.get("MO_USE_PALLAS") == "1"


def effective_use_pallas(session_value=None) -> bool:
    """gpu_mode.go:37 EffectiveGpuMode analogue: an explicit session
    `SET use_pallas = 0|1` wins; otherwise the MO_USE_PALLAS env var
    (the build-tag default of the reference)."""
    if session_value is not None:
        try:
            return bool(int(session_value))
        except (TypeError, ValueError):
            return False
    return use_pallas()


def _interpret(flag):
    if flag is None:
        return jax.default_backend() != "tpu"
    return flag


# ------------------------------------------------- pairwise L2 (fused)
def _l2_kernel(x_ref, q_ref, q2_ref, out_ref):
    x = x_ref[:]                                   # [TM, D] f32
    q = q_ref[:]                                   # [B, D]  f32
    xq = jax.lax.dot_general(
        x, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # [TM, B] on the MXU
    x2 = jnp.sum(x * x, axis=1, keepdims=True)     # fused row norms (VPU)
    out_ref[:] = jnp.maximum(x2 + q2_ref[:] - 2.0 * xq, 0.0)


@functools.partial(jax.jit, static_argnames=("tile_m", "interpret"))
def l2_distance_sq_pallas(x: jnp.ndarray, q: jnp.ndarray,
                          tile_m: int = 1024,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Pairwise squared L2 [n, b]; n must be a multiple of tile_m."""
    n, d = x.shape
    b = q.shape[0]
    assert n % tile_m == 0, f"n={n} must be a multiple of tile_m={tile_m}"
    interpret = _interpret(interpret)
    xf = x.astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q2 = jnp.sum(qf * qf, axis=1)[None, :]          # [1, b]
    grid = (n // tile_m,)
    return pl.pallas_call(
        _l2_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, d), lambda i: (i, 0)),
            pl.BlockSpec((b, d), lambda i: (0, 0)),
            pl.BlockSpec((1, b), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_m, b), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, b), jnp.float32),
        interpret=interpret,
    )(xf, qf, q2)


# -------------------------------------- pairwise L2 with fused prefilter
def _l2_masked_kernel(x_ref, q_ref, q2_ref, m_ref, out_ref):
    x = x_ref[:]                                   # [TM, D] f32
    q = q_ref[:]                                   # [B, D]  f32
    xq = jax.lax.dot_general(
        x, q, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    dist = jnp.maximum(x2 + q2_ref[:] - 2.0 * xq, 0.0)
    # fused doc-filter: excluded rows never leave the kernel as
    # candidates (top-k downstream sorts them last)
    keep = m_ref[:] > 0                            # [TM, 1] int32
    out_ref[:] = jnp.where(keep, dist, jnp.inf)


@functools.partial(jax.jit, static_argnames=("tile_m", "interpret"))
def l2_distance_sq_masked_pallas(x: jnp.ndarray, q: jnp.ndarray,
                                 mask: jnp.ndarray,
                                 tile_m: int = 1024,
                                 interpret: bool | None = None
                                 ) -> jnp.ndarray:
    """Filtered pairwise squared L2 [n, b]: rows with mask=False score
    +inf. The mask rides into the same VMEM tile as the vectors, so the
    filter costs no extra HBM pass (the reference pre-filters with a
    bitset handed to cuVS — cgo/cuvs/filter.hpp)."""
    n, d = x.shape
    b = q.shape[0]
    assert n % tile_m == 0, f"n={n} must be a multiple of tile_m={tile_m}"
    interpret = _interpret(interpret)
    xf = x.astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q2 = jnp.sum(qf * qf, axis=1)[None, :]
    m2 = mask.astype(jnp.int32)[:, None]            # [n, 1]
    return pl.pallas_call(
        _l2_masked_kernel,
        grid=(n // tile_m,),
        in_specs=[
            pl.BlockSpec((tile_m, d), lambda i: (i, 0)),
            pl.BlockSpec((b, d), lambda i: (0, 0)),
            pl.BlockSpec((1, b), lambda i: (0, 0)),
            pl.BlockSpec((tile_m, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile_m, b), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, b), jnp.float32),
        interpret=interpret,
    )(xf, qf, q2, m2)


# ------------------------------------------------ GROUP BY segment sum
def _segsum_kernel(v_ref, g_ref, out_ref):
    i = pl.program_id(0)
    v = v_ref[:]                                    # [1, TN] f32
    g = g_ref[:]                                    # [1, TN] int32
    num_segments = out_ref.shape[1]
    # one-hot [TN, G] on the fly in VMEM; the segment reduction becomes
    # a [1, TN] @ [TN, G] matmul on the MXU — no scatter, no hash table
    onehot = (g[0][:, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (1, num_segments), 1)
              ).astype(jnp.float32)
    partial = jax.lax.dot_general(
        v, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [1, G]

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += partial                           # grid is sequential


@functools.partial(jax.jit,
                   static_argnames=("num_segments", "tile_n", "interpret"))
def segment_sum_pallas(values: jnp.ndarray, gids: jnp.ndarray,
                       mask: jnp.ndarray, num_segments: int,
                       tile_n: int = 2048,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Masked float32 segment sum over [n] values into [num_segments].

    TPU formulation of `colexec/group` partial aggregation: instead of a
    hash-table scatter, each row tile builds its one-hot group matrix in
    VMEM and reduces with a single MXU matmul; the sequential TPU grid
    accumulates partials in the output block, which stays resident.
    n must be a multiple of tile_n (callers pad with mask=False);
    num_segments bounded by VMEM (tile_n * num_segments * 4B ≲ 8 MB).

    NOTE float32 only: exact int64/decimal sums must stay on the XLA
    `segment_sum` scatter path (MXU accumulation is float).
    """
    n = values.shape[0]
    assert n % tile_n == 0, f"n={n} not a multiple of tile_n={tile_n}"
    interpret = _interpret(interpret)
    v = jnp.where(mask, values.astype(jnp.float32), 0.0)[None, :]  # [1, n]
    # masked rows also get an out-of-range id so a gid collision with a
    # real group cannot resurrect them (id G sums into nothing: the iota
    # comparison never matches because iota < G)
    g = jnp.where(mask, gids.astype(jnp.int32), num_segments)[None, :]
    out = pl.pallas_call(
        _segsum_kernel,
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((1, tile_n), lambda i: (0, i)),
            pl.BlockSpec((1, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, num_segments), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, num_segments), jnp.float32),
        interpret=interpret,
    )(v, g)
    return out[0]


# --------------------------------------- hash-join probe sorted search
def _sorted_search_kernel(shi_ref, slo_ref, qhi_ref, qlo_ref, out_ref):
    j = pl.program_id(1)                            # sorted-tile index
    shi = shi_ref[:][0][:, None]                    # [TN, 1] int32
    slo = slo_ref[:][0][:, None]
    qhi = qhi_ref[:][0][None, :]                    # [1, TQ] int32
    qlo = qlo_ref[:][0][None, :]
    # lexicographic (hi, lo) compare == the uint64 compare: both halves
    # were pre-mapped to sign-flipped int32 so signed order == unsigned
    less = (shi < qhi) | ((shi == qhi) & (slo < qlo))   # [TN, TQ]

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # count-less-than accumulates across sorted tiles (the TPU grid is
    # sequential in its last dimension); the sum is order-free integer
    # arithmetic, so the result is exactly searchsorted-left
    out_ref[:] += jnp.sum(less.astype(jnp.int32), axis=0,
                          dtype=jnp.int32)[None, :]


def _sign_flip_halves(x64: jnp.ndarray):
    """uint64 [n] -> (hi, lo) sign-flipped int32 pairs whose signed
    lexicographic order equals the unsigned 64-bit order (TPU Pallas
    has no 64-bit integers in VMEM)."""
    hi = (x64 >> jnp.uint64(32)).astype(jnp.uint32)
    lo = x64.astype(jnp.uint32)                     # truncating mod 2^32
    flip = jnp.uint32(0x80000000)
    return ((hi ^ flip).astype(jnp.int32),
            (lo ^ flip).astype(jnp.int32))


@functools.partial(jax.jit,
                   static_argnames=("tile_q", "tile_n", "interpret"))
def sorted_search_pallas(sorted_vals: jnp.ndarray, queries: jnp.ndarray,
                         tile_q: int = 1024, tile_n: int = 1024,
                         interpret: bool | None = None) -> jnp.ndarray:
    """`jnp.searchsorted(sorted_vals, queries, side='left')` for uint64
    hashes, as a Pallas kernel: insertion-point-left(q) == #{s : s < q},
    so each (query-tile, sorted-tile) step is a dense VPU compare plus
    an integer reduction — no per-lane gather, no binary-search control
    flow, and bit-identical to the XLA path because an integer count has
    no rounding and no order sensitivity.

    Pads both inputs internally: sorted pads with UINT64_MAX (counted
    only for queries > MAX — impossible), queries pad with don't-cares
    sliced off the result.
    """
    (n,), (m,) = sorted_vals.shape, queries.shape
    interpret = _interpret(interpret)
    s64 = sorted_vals.astype(jnp.uint64)
    q64 = queries.astype(jnp.uint64)
    pad_n = (-n) % tile_n
    pad_m = (-m) % tile_q
    if pad_n:
        s64 = jnp.pad(s64, (0, pad_n),
                      constant_values=jnp.uint64(0xFFFFFFFFFFFFFFFF))
    if pad_m:
        q64 = jnp.pad(q64, (0, pad_m))
    shi, slo = _sign_flip_halves(s64)
    qhi, qlo = _sign_flip_halves(q64)
    out = pl.pallas_call(
        _sorted_search_kernel,
        grid=(q64.shape[0] // tile_q, s64.shape[0] // tile_n),
        in_specs=[
            pl.BlockSpec((1, tile_n), lambda qi, ni: (0, ni)),
            pl.BlockSpec((1, tile_n), lambda qi, ni: (0, ni)),
            pl.BlockSpec((1, tile_q), lambda qi, ni: (0, qi)),
            pl.BlockSpec((1, tile_q), lambda qi, ni: (0, qi)),
        ],
        out_specs=pl.BlockSpec((1, tile_q), lambda qi, ni: (0, qi)),
        out_shape=jax.ShapeDtypeStruct((1, q64.shape[0]), jnp.int32),
        interpret=interpret,
    )(shi[None, :], slo[None, :], qhi[None, :], qlo[None, :])
    return out[0][:m]


# ------------------------------------------------- IVF-PQ ADC scoring
def _adc_kernel(codes_ref, lut_ref, out_ref):
    codes = codes_ref[:][0]                         # [TC, M] int32
    lut = lut_ref[:][0]                             # [M, 256] f32
    tc, m = codes.shape
    # scores[c] = sum_m lut[m, codes[c, m]] — expressed as a one-hot
    # [TC, M*256] @ [M*256, 1] matmul so the gather runs on the MXU
    # (the reference's cuVS ADC kernel does warp-local LUT gathers;
    # TPUs have no per-lane gather, but the one-hot contraction is
    # exactly what the systolic array is good at)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, 256), 1)  # [1, 256]
    onehot = (codes[:, :, None] == iota[None, :, :]).astype(jnp.float32)
    onehot = onehot.reshape(tc, m * 256)
    lut_flat = lut.reshape(m * 256, 1)
    out_ref[:] = jax.lax.dot_general(
        onehot, lut_flat, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(1, tc)


@functools.partial(jax.jit, static_argnames=("tile_c", "interpret"))
def adc_score_pallas(codes: jnp.ndarray, lut: jnp.ndarray,
                     tile_c: int = 256,
                     interpret: bool | None = None) -> jnp.ndarray:
    """Batched ADC scoring: codes [G, P, M] uint8/int32 (G query-probe
    groups, P candidates each), lut [G, M, 256] f32 -> scores [G, P]
    with scores[g, p] = sum_m lut[g, m, codes[g, p, m]].

    P must be a multiple of tile_c. VMEM per step: the one-hot tile
    (tile_c * M * 256 * 4B — 4 MB at tile_c=256, M=16) plus one LUT.
    """
    g, p, m = codes.shape
    assert p % tile_c == 0, f"P={p} not a multiple of tile_c={tile_c}"
    assert lut.shape == (g, m, 256), lut.shape
    interpret = _interpret(interpret)
    c32 = codes.astype(jnp.int32)
    out = pl.pallas_call(
        _adc_kernel,
        grid=(g, p // tile_c),
        in_specs=[
            pl.BlockSpec((1, tile_c, m), lambda gi, ci: (gi, ci, 0)),
            pl.BlockSpec((1, m, 256), lambda gi, ci: (gi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile_c), lambda gi, ci: (gi, ci)),
        out_shape=jax.ShapeDtypeStruct((g, p), jnp.float32),
        interpret=interpret,
    )(c32, lut.astype(jnp.float32))
    return out

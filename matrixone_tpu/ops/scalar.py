"""Elementwise scalar kernels with SQL null semantics.

TPU-native replacement for the reference's per-type vectorized loops
(`pkg/vectorize/`, `cgo/arith.c`, `cgo/compare.c`, `cgo/logic.c`, and the
554-builtin registry `pkg/sql/plan/function/`). Design:

  * one generic jnp kernel per operation, not one per type — XLA specializes
    on dtype at trace time (the reference needs Go generics + cgo dispatch
    per type; XLA's compile cache is our dispatch table);
  * validity propagates as `a.valid & b.valid` (SQL ternary logic); AND/OR
    use Kleene logic exactly like MySQL;
  * const (length-1) columns broadcast for free via jnp broadcasting —
  * everything here fuses: a filter expression tree of 10 ops compiles to
    one XLA fusion over the batch, where the reference walks an expression
    executor per operator (`colexec/evalExpression.go`).

All kernels are pure functions DeviceColumn -> DeviceColumn and are safe to
call under jit/shard_map.
"""

from __future__ import annotations

import jax.numpy as jnp

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.container.device import DeviceColumn
from matrixone_tpu.container.dtypes import DType, TypeOid


def _broadcast2(a: DeviceColumn, b: DeviceColumn):
    """Broadcast const columns; return (da, db, validity)."""
    da, db = a.data, b.data
    va, vb = a.validity, b.validity
    n = max(da.shape[0], db.shape[0])
    if da.shape[0] != n:
        da = jnp.broadcast_to(da, (n,) + da.shape[1:])
        va = jnp.broadcast_to(va, (n,))
    if db.shape[0] != n:
        db = jnp.broadcast_to(db, (n,) + db.shape[1:])
        vb = jnp.broadcast_to(vb, (n,))
    return da, db, va & vb


def _decimal_rescale(a: DeviceColumn, b: DeviceColumn):
    """Align decimal scales for +,-,comparison (reference: decimal.go)."""
    sa = a.dtype.scale if a.dtype.oid == TypeOid.DECIMAL64 else 0
    sb = b.dtype.scale if b.dtype.oid == TypeOid.DECIMAL64 else 0
    s = max(sa, sb)
    da, db = a.data, b.data
    if sa < s:
        da = da * (10 ** (s - sa))
    if sb < s:
        db = db * (10 ** (s - sb))
    return da, db, s


def _result_type(a: DType, b: DType) -> DType:
    return dt.promote(a, b)


def _arith(a: DeviceColumn, b: DeviceColumn, fn, out_dtype: DType,
           null_mask=None) -> DeviceColumn:
    da, db, valid = _broadcast2(a, b)
    out = fn(da.astype(out_dtype.jnp_dtype), db.astype(out_dtype.jnp_dtype))
    if null_mask is not None:
        valid = valid & ~null_mask
    return DeviceColumn(data=out, validity=valid, dtype=out_dtype)


def _descale_for_float(a: DeviceColumn, b: DeviceColumn):
    """When decimal math lands in float (mixed operands), the decimal side
    must enter as its REAL value, not raw scaled ints (cents * 0.2 is off
    by 10^scale)."""
    def conv(c):
        if c.dtype.oid == TypeOid.DECIMAL64:
            return DeviceColumn(c.data.astype(jnp.float64)
                                / (10.0 ** c.dtype.scale),
                                c.validity, dt.FLOAT64)
        return c
    return conv(a), conv(b)


def add(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    if b.dtype.oid == TypeOid.DATE and a.dtype.is_integer:
        a, b = b, a
    if a.dtype.oid == TypeOid.DATE and b.dtype.is_integer:
        da, db, valid = _broadcast2(a, b)
        return DeviceColumn(da.astype(jnp.int32) + db.astype(jnp.int32),
                            valid, dt.DATE)
    out_t = _result_type(a.dtype, b.dtype)
    if out_t.oid == TypeOid.DECIMAL64:
        da, db, s = _decimal_rescale(a, b)
        _, _, valid = _broadcast2(a, b)
        out_t = dt.decimal64(scale=s)
        return DeviceColumn(jnp.broadcast_to(da, jnp.broadcast_shapes(da.shape, db.shape)) + db,
                            valid, out_t)
    if out_t.is_float:
        a, b = _descale_for_float(a, b)
    return _arith(a, b, jnp.add, out_t)


def sub(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    if a.dtype.oid == TypeOid.DATE and b.dtype.oid == TypeOid.DATE:
        da, db, valid = _broadcast2(a, b)
        return DeviceColumn((da.astype(jnp.int64) - db.astype(jnp.int64)),
                            valid, dt.INT64)
    if a.dtype.oid == TypeOid.DATE and b.dtype.is_integer:
        da, db, valid = _broadcast2(a, b)
        return DeviceColumn(da.astype(jnp.int32) - db.astype(jnp.int32),
                            valid, dt.DATE)
    out_t = _result_type(a.dtype, b.dtype)
    if out_t.oid == TypeOid.DECIMAL64:
        da, db, s = _decimal_rescale(a, b)
        _, _, valid = _broadcast2(a, b)
        return DeviceColumn(da - db, valid, dt.decimal64(scale=s))
    if out_t.is_float:
        a, b = _descale_for_float(a, b)
    return _arith(a, b, jnp.subtract, out_t)


def mul(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    out_t = _result_type(a.dtype, b.dtype)
    if out_t.oid == TypeOid.DECIMAL64:
        # scales add on multiply (reference: Decimal64Mul)
        sa = a.dtype.scale if a.dtype.oid == TypeOid.DECIMAL64 else 0
        sb = b.dtype.scale if b.dtype.oid == TypeOid.DECIMAL64 else 0
        da, db, valid = _broadcast2(a, b)
        return DeviceColumn(da * db, valid, dt.decimal64(scale=sa + sb))
    if out_t.is_float:
        a, b = _descale_for_float(a, b)
    return _arith(a, b, jnp.multiply, out_t)


def div(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    """SQL '/': true division; NULL on divide-by-zero (MySQL semantics)."""
    da, db, valid = _broadcast2(a, b)
    if a.dtype.oid == TypeOid.DECIMAL64 or b.dtype.oid == TypeOid.DECIMAL64:
        sa = a.dtype.scale if a.dtype.oid == TypeOid.DECIMAL64 else 0
        sb = b.dtype.scale if b.dtype.oid == TypeOid.DECIMAL64 else 0
        # widen to float64 for division; exactness only required for +,-,*
        fa = da.astype(jnp.float64) / (10.0 ** sa)
        fb = db.astype(jnp.float64) / (10.0 ** sb)
        zero = fb == 0
        out = fa / jnp.where(zero, 1.0, fb)
        return DeviceColumn(out, valid & ~zero, dt.FLOAT64)
    zero = db == 0
    fa = da.astype(jnp.float64)
    fb = jnp.where(zero, 1, db).astype(jnp.float64)
    return DeviceColumn(fa / fb, valid & ~zero, dt.FLOAT64)


def mod(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    out_t = _result_type(a.dtype, b.dtype)
    da, db, valid = _broadcast2(a, b)
    zero = db == 0
    safe = jnp.where(zero, 1, db)
    if out_t.is_float:
        out = jnp.fmod(da.astype(out_t.jnp_dtype), safe.astype(out_t.jnp_dtype))
    else:
        # MySQL % keeps dividend sign (C truncation), jnp.remainder is pythonic
        q = da.astype(out_t.jnp_dtype)
        s = safe.astype(out_t.jnp_dtype)
        out = jnp.sign(q) * (jnp.abs(q) % jnp.abs(s))
    return DeviceColumn(out, valid & ~zero, out_t)


def neg(a: DeviceColumn) -> DeviceColumn:
    return DeviceColumn(-a.data, a.validity, a.dtype)


def _cmp(a: DeviceColumn, b: DeviceColumn, fn) -> DeviceColumn:
    if TypeOid.DECIMAL64 in (a.dtype.oid, b.dtype.oid) \
            and a.dtype.is_numeric and b.dtype.is_numeric \
            and not (a.dtype.is_float or b.dtype.is_float):
        da, db, _ = _decimal_rescale(a, b)
        _, _, valid = _broadcast2(a, b)
        n = max(da.shape[0], db.shape[0])
        da = jnp.broadcast_to(da, (n,))
        db = jnp.broadcast_to(db, (n,))
        return DeviceColumn(fn(da, db), valid, dt.BOOL)
    da, db, valid = _broadcast2(a, b)
    if TypeOid.DECIMAL64 in (a.dtype.oid, b.dtype.oid) and \
            (a.dtype.is_float or b.dtype.is_float):
        # decimal vs float: descale the decimal to real units (cents
        # compared against a float threshold would be off by 10^scale)
        if a.dtype.oid == TypeOid.DECIMAL64:
            da = da.astype(jnp.float64) / (10 ** a.dtype.scale)
        if b.dtype.oid == TypeOid.DECIMAL64:
            db = db.astype(jnp.float64) / (10 ** b.dtype.scale)
        return DeviceColumn(fn(da.astype(jnp.float64),
                               db.astype(jnp.float64)), valid, dt.BOOL)
    if a.dtype.is_numeric and b.dtype.is_numeric and a.dtype.oid != b.dtype.oid:
        ct = dt.promote(a.dtype, b.dtype).jnp_dtype
        da, db = da.astype(ct), db.astype(ct)
    return DeviceColumn(fn(da, db), valid, dt.BOOL)


def eq(a, b): return _cmp(a, b, jnp.equal)
def ne(a, b): return _cmp(a, b, jnp.not_equal)
def lt(a, b): return _cmp(a, b, jnp.less)
def le(a, b): return _cmp(a, b, jnp.less_equal)
def gt(a, b): return _cmp(a, b, jnp.greater)
def ge(a, b): return _cmp(a, b, jnp.greater_equal)


def between(x: DeviceColumn, lo: DeviceColumn, hi: DeviceColumn) -> DeviceColumn:
    return logical_and(ge(x, lo), le(x, hi))


def isnull(a: DeviceColumn) -> DeviceColumn:
    v = a.validity
    return DeviceColumn(~v, jnp.ones_like(v), dt.BOOL)


def isnotnull(a: DeviceColumn) -> DeviceColumn:
    v = a.validity
    return DeviceColumn(v, jnp.ones_like(v), dt.BOOL)


def logical_and(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    """Kleene AND: FALSE dominates NULL."""
    da, db, _ = _broadcast2(a, b)
    va = jnp.broadcast_to(a.validity, da.shape)
    vb = jnp.broadcast_to(b.validity, db.shape)
    false_a = va & ~da
    false_b = vb & ~db
    valid = (va & vb) | false_a | false_b
    # treat NULL operands as TRUE for the value (masked by validity anyway)
    out = (da | ~va) & (db | ~vb)
    return DeviceColumn(out, valid, dt.BOOL)


def logical_or(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    """Kleene OR: TRUE dominates NULL."""
    da, db, _ = _broadcast2(a, b)
    va = jnp.broadcast_to(a.validity, da.shape)
    vb = jnp.broadcast_to(b.validity, db.shape)
    true_a = va & da
    true_b = vb & db
    out = true_a | true_b
    valid = (va & vb) | true_a | true_b
    return DeviceColumn(out, valid, dt.BOOL)


def logical_not(a: DeviceColumn) -> DeviceColumn:
    return DeviceColumn(~a.data, a.validity, dt.BOOL)


def in_list(a: DeviceColumn, values) -> DeviceColumn:
    """`x IN (v1, v2, ...)` with literal list (small, unrolled)."""
    hit = jnp.zeros(a.data.shape, jnp.bool_)
    for v in values:
        hit = hit | (a.data == v)
    return DeviceColumn(hit, a.validity, dt.BOOL)


def cast(a: DeviceColumn, to: DType) -> DeviceColumn:
    """Numeric/temporal cast (reference: function/func_cast.go)."""
    if a.dtype.oid == to.oid and a.dtype.scale == to.scale:
        return a
    src, d = a.dtype, a.data
    if src.oid == TypeOid.DECIMAL64 and to.is_float:
        out = d.astype(to.jnp_dtype) / (10.0 ** src.scale)
    elif src.oid == TypeOid.DECIMAL64 and to.oid == TypeOid.DECIMAL64:
        if to.scale >= src.scale:
            out = d * (10 ** (to.scale - src.scale))
        else:
            out = d // (10 ** (src.scale - to.scale))
    elif to.oid == TypeOid.DECIMAL64:
        if src.is_float:
            out = jnp.round(d.astype(jnp.float64) * (10.0 ** to.scale)).astype(jnp.int64)
        else:
            out = d.astype(jnp.int64) * (10 ** to.scale)
    else:
        out = d.astype(to.jnp_dtype)
    return DeviceColumn(out, a.validity, to)


def coalesce(*cols: DeviceColumn) -> DeviceColumn:
    out = cols[0]
    for c in cols[1:]:
        da, db, _ = _broadcast2(out, c)
        va = jnp.broadcast_to(out.validity, da.shape)
        vb = jnp.broadcast_to(c.validity, db.shape)
        data = jnp.where(va, da, db)
        valid = va | vb
        out = DeviceColumn(data, valid, out.dtype)
    return out


def case_when(cond: DeviceColumn, then: DeviceColumn, els: DeviceColumn) -> DeviceColumn:
    dc, dthen, _ = _broadcast2(cond, then)
    _, dels, _ = _broadcast2(cond, els)
    take_then = jnp.broadcast_to(cond.validity, dc.shape) & dc
    data = jnp.where(take_then, dthen, dels)
    valid = jnp.where(take_then,
                      jnp.broadcast_to(then.validity, dthen.shape),
                      jnp.broadcast_to(els.validity, dels.shape))
    out_t = then.dtype if then.dtype.is_numeric else els.dtype
    return DeviceColumn(data, valid, out_t)


# math builtins (reference: pkg/vectorize/momath)
def _unary_float(a: DeviceColumn, fn, out=dt.FLOAT64) -> DeviceColumn:
    d = a.data
    if a.dtype.oid == TypeOid.DECIMAL64:
        d = d.astype(jnp.float64) / (10.0 ** a.dtype.scale)
    return DeviceColumn(fn(d.astype(out.jnp_dtype)), a.validity, out)


def abs_(a):
    if a.dtype.is_numeric and not a.dtype.is_float:
        return DeviceColumn(jnp.abs(a.data), a.validity, a.dtype)
    return _unary_float(a, jnp.abs)


def floor(a): return _unary_float(a, jnp.floor)
def ceil(a): return _unary_float(a, jnp.ceil)
def sqrt(a): return _unary_float(a, jnp.sqrt)
def exp(a): return _unary_float(a, jnp.exp)
def ln(a): return _unary_float(a, jnp.log)
def sin(a): return _unary_float(a, jnp.sin)
def cos(a): return _unary_float(a, jnp.cos)


def power(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    da, db, valid = _broadcast2(a, b)
    out = jnp.power(da.astype(jnp.float64), db.astype(jnp.float64))
    return DeviceColumn(out, valid, dt.FLOAT64)


def round_(a: DeviceColumn, digits: int = 0) -> DeviceColumn:
    if a.dtype.oid == TypeOid.DECIMAL64:
        return cast(a, dt.decimal64(scale=digits))
    return _unary_float(a, lambda x: jnp.round(x, digits))


def tan(a): return _unary_float(a, jnp.tan)
def asin(a): return _unary_float(a, jnp.arcsin)
def acos(a): return _unary_float(a, jnp.arccos)
def atan(a): return _unary_float(a, jnp.arctan)
def cot(a): return _unary_float(a, lambda x: 1.0 / jnp.tan(x))
def degrees(a): return _unary_float(a, jnp.degrees)
def radians(a): return _unary_float(a, jnp.radians)
def log2(a): return _unary_float(a, jnp.log2)
def log10(a): return _unary_float(a, jnp.log10)


def atan2(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    da, db, valid = _broadcast2(a, b)
    out = jnp.arctan2(da.astype(jnp.float64), db.astype(jnp.float64))
    return DeviceColumn(out, valid, dt.FLOAT64)


def sign(a: DeviceColumn) -> DeviceColumn:
    # scale never changes the sign, so decimals need no rescale
    return DeviceColumn(jnp.sign(a.data).astype(jnp.int64), a.validity,
                        dt.INT64)


def truncate(a: DeviceColumn, digits: int = 0) -> DeviceColumn:
    """TRUNCATE(x, d): toward zero (ROUND's half-away sibling)."""
    if a.dtype.oid == TypeOid.DECIMAL64:
        diff = a.dtype.scale - digits
        if diff <= 0:
            return a
        f = 10 ** diff
        d = a.data
        # zero the truncated digits but KEEP the scale (the bound output
        # type is the input type)
        q = jnp.sign(d) * (jnp.abs(d) // f) * f
        return DeviceColumn(q.astype(d.dtype), a.validity, a.dtype)
    f = 10.0 ** digits
    return _unary_float(a, lambda x: jnp.trunc(x * f) / f)


def _pick2(a: DeviceColumn, b: DeviceColumn, fn) -> DeviceColumn:
    """GREATEST/LEAST pairwise step: NULL if either side is NULL
    (MySQL semantics); decimal scales align, and a decimal mixed with a
    float enters as its REAL value (descale), never as scaled ints."""
    if a.dtype.is_float or b.dtype.is_float:
        a, b = _descale_for_float(a, b)
    elif TypeOid.DECIMAL64 in (a.dtype.oid, b.dtype.oid):
        da_, db_, s_ = _decimal_rescale(a, b)
        a = DeviceColumn(da_, a.validity, dt.decimal64(scale=s_))
        b = DeviceColumn(db_, b.validity, dt.decimal64(scale=s_))
    da, db, valid = _broadcast2(a, b)
    if da.dtype != db.dtype:
        ct = jnp.promote_types(da.dtype, db.dtype)
        da, db = da.astype(ct), db.astype(ct)
    out_t = (a.dtype if a.dtype.oid == b.dtype.oid
             else _result_type(a.dtype, b.dtype))
    return DeviceColumn(fn(da, db), valid, out_t)


def greatest(*cols: DeviceColumn) -> DeviceColumn:
    out = cols[0]
    for c in cols[1:]:
        out = _pick2(out, c, jnp.maximum)
    return out


def least(*cols: DeviceColumn) -> DeviceColumn:
    out = cols[0]
    for c in cols[1:]:
        out = _pick2(out, c, jnp.minimum)
    return out

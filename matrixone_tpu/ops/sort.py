"""Sort and top-k kernels (reference: pkg/sort, colexec/{order,top}).

Multi-column ORDER BY is a sequence of stable argsorts applied from the
least-significant key to the most-significant (radix-style composition) —
XLA's sort is a stable bitonic/merge network on TPU. Top-k uses
`jax.lax.top_k`, the TPU-native primitive the reference approximates with a
heap per pipeline (`colexec/top`).

Integer/decimal keys are sorted and top-k'd **in their native integer
domain** (descending via bitwise-not, which is total and overflow-free);
casting int64 to float would corrupt ordering above 2^53 (and float32 above
2^24). NULL ordering follows MySQL: NULLs first on ASC, last on DESC; it is
applied as a separate stable class-key pass so no sentinel value can
collide with real data.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _is_int(dtype) -> bool:
    return (jnp.issubdtype(dtype, jnp.integer)
            or jnp.issubdtype(dtype, jnp.bool_))


def _value_key(data: jnp.ndarray, descending: bool) -> jnp.ndarray:
    """Order-preserving transform so ascending argsort realizes the order."""
    if _is_int(data.dtype):
        d = data.astype(jnp.int64) if data.dtype == jnp.bool_ else data
        return ~d if descending else d
    key = data.astype(jnp.float64) if data.dtype != jnp.float64 else data
    return -key if descending else key


def _class_key(validity: Optional[jnp.ndarray], descending: bool,
               row_mask: jnp.ndarray) -> jnp.ndarray:
    """0/1/2 class: nulls-first-or-last per MySQL, padding always last."""
    n = row_mask.shape[0]
    cls = jnp.ones((n,), jnp.int32)
    if validity is not None:
        null_cls = 2 if descending else 0   # DESC: nulls after values
        cls = jnp.where(validity, cls, null_cls)
    return jnp.where(row_mask, cls, 3)


def sort_indices(columns: Sequence[jnp.ndarray],
                 validities: Sequence[Optional[jnp.ndarray]],
                 descendings: Sequence[bool],
                 row_mask: jnp.ndarray) -> jnp.ndarray:
    """Row permutation realizing a multi-column ORDER BY (stable)."""
    n = row_mask.shape[0]
    order = jnp.arange(n, dtype=jnp.int32)
    # least-significant key first; stable sorts preserve prior order.
    # each key = value pass then null/padding class pass (both stable).
    for data, valid, desc in reversed(list(zip(columns, validities, descendings))):
        vkey = _value_key(data, desc)
        if valid is not None:
            # NULL lanes carry arbitrary underlying data; a constant key
            # keeps the value pass a no-op for them, so the prior
            # (less-significant) key's order survives into the NULL
            # class instead of being shuffled by garbage
            vkey = jnp.where(valid, vkey, jnp.zeros((), vkey.dtype))
        vkey = vkey[order]
        perm = jnp.argsort(vkey, stable=True)
        order = order[perm]
        ckey = _class_key(None if valid is None else valid, desc, row_mask)[order]
        perm = jnp.argsort(ckey, stable=True)
        order = order[perm]
    return order


def top_k_indices(key: jnp.ndarray, validity: Optional[jnp.ndarray],
                  descending: bool, row_mask: jnp.ndarray,
                  k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the first k rows under ORDER BY key [DESC] LIMIT k.

    Returns (indices [k], count) with count = min(k, n_real_rows).
    `lax.top_k` selects maxima, so the key is transformed so that "comes
    first" == "largest", staying in the integer domain for int keys.
    """
    if _is_int(key.dtype):
        d = key.astype(jnp.int64) if key.dtype == jnp.bool_ else key
        score = d if descending else ~d
        lo = jnp.iinfo(score.dtype).min
        if validity is not None:
            # ASC: nulls first -> top priority; DESC: nulls last but ahead
            # of padding
            null_score = jnp.iinfo(score.dtype).max if not descending else lo + 1
            score = jnp.where(validity, score, null_score)
        score = jnp.where(row_mask, score, lo)
    else:
        keyf = key.astype(jnp.float64) if key.dtype != jnp.float64 else key
        score = keyf if descending else -keyf
        if validity is not None:
            null_score = -jnp.finfo(jnp.float64).max if descending else jnp.inf
            score = jnp.where(validity, score, null_score)
        score = jnp.where(row_mask, score, -jnp.inf)
    _, idx = jax.lax.top_k(score, k)
    count = jnp.minimum(jnp.sum(row_mask.astype(jnp.int32)), k)
    return idx.astype(jnp.int32), count

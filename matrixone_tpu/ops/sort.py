"""Sort and top-k kernels (reference: pkg/sort, colexec/{order,top}).

Multi-column ORDER BY is a sequence of stable argsorts applied from the
least-significant key to the most-significant (radix-style composition) —
XLA's sort is a stable bitonic/merge network on TPU. Top-k uses
`jax.lax.top_k`, the TPU-native primitive the reference approximates with a
heap per pipeline (`colexec/top`).

NULL ordering follows MySQL: NULLs first on ASC, last on DESC.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp


def _sort_key(data: jnp.ndarray, validity: Optional[jnp.ndarray],
              descending: bool, row_mask: jnp.ndarray) -> jnp.ndarray:
    """Build a float64 key with MySQL null ordering; padding rows go last."""
    if jnp.issubdtype(data.dtype, jnp.bool_):
        key = data.astype(jnp.float64)
    else:
        key = data.astype(jnp.float64)
    if descending:
        key = -key
    if validity is not None:
        null_key = jnp.float64(jnp.inf) if descending else jnp.float64(-jnp.inf)
        key = jnp.where(validity, key, null_key)
    # padding rows always sort to the very end
    key = jnp.where(row_mask, key, jnp.inf)
    return key


def sort_indices(columns: Sequence[jnp.ndarray],
                 validities: Sequence[Optional[jnp.ndarray]],
                 descendings: Sequence[bool],
                 row_mask: jnp.ndarray) -> jnp.ndarray:
    """Row permutation realizing a multi-column ORDER BY (stable)."""
    n = row_mask.shape[0]
    order = jnp.arange(n, dtype=jnp.int32)
    # apply least-significant key first; stable sorts preserve prior order
    for data, valid, desc in reversed(list(zip(columns, validities, descendings))):
        key = _sort_key(data[order], None if valid is None else valid[order],
                        desc, row_mask[order])
        perm = jnp.argsort(key, stable=True)
        order = order[perm]
    return order


def top_k_indices(key: jnp.ndarray, validity: Optional[jnp.ndarray],
                  descending: bool, row_mask: jnp.ndarray,
                  k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the top/bottom k rows by a single numeric key.

    Returns (indices [k], count) where count = min(k, n_valid_rows).
    `lax.top_k` selects maxima, so ASC keys are negated.
    """
    keyf = key.astype(jnp.float32) if key.dtype != jnp.float64 else key
    score = keyf if descending else -keyf
    if validity is not None:
        # MySQL: NULLs first on ASC (selected ahead of values), last on DESC
        null_score = -jnp.inf if descending else jnp.inf
        score = jnp.where(validity, score, null_score)
    score = jnp.where(row_mask, score, -jnp.inf)
    import jax.lax as lax
    _, idx = lax.top_k(score, k)
    count = jnp.minimum(jnp.sum(row_mask.astype(jnp.int32)), k)
    return idx.astype(jnp.int32), count

from matrixone_tpu.parallel import dist_query, mesh
from matrixone_tpu.parallel.mesh import make_mesh, replicate, shard_rows

__all__ = ["dist_query", "mesh", "make_mesh", "replicate", "shard_rows"]

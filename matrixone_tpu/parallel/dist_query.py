"""Distributed query execution over a device mesh.

TPU-native re-architecture of the reference's multi-CN execution
(`compile/scope.go:504 ParallelRun`, `:423 RemoteRun`, `colexec/shuffle` +
`dispatch` + `merge*`): instead of serializing operator subtrees over morpc,
the whole plan is one `shard_map`-ed XLA program and the exchange operators
become collectives on the ICI:

  reference                      here
  ---------------------------    -----------------------------------
  ParallelRun DOP pipelines      rows sharded over mesh axis "shard"
  shuffle (hash repartition)     ppermute/all_to_all inside shard_map
  broadcast join / joinmap       all_gather of build side
  merge group (two-phase agg)    local segment agg + psum
  merge top-k                    local top_k + all_gather + global top_k

Three canonical steps live here:
  * sharded_group_aggregate — two-phase distributed GROUP BY
  * sharded_topk            — distributed vector search (cuvs "sharded
                              multi-GPU" mode, cgo/cuvs/README.md)
  * hash_shuffle            — all_to_all repartition by key hash
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map only exists as a top-level name on newer jax; this image
# ships 0.4.37 where it lives in jax.experimental and the replication
# check is spelled check_rep, not check_vma (the 5 test_parallel cases
# and the dryrun_multichip entry were failing on exactly this)
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    jax.shard_map = _compat_shard_map

from matrixone_tpu.ops import agg as A, distance as D, hash as H


# ---------------------------------------------------------------- group by

def sharded_group_aggregate(mesh: Mesh, keys: jnp.ndarray, values: jnp.ndarray,
                            row_mask: jnp.ndarray, max_groups: int,
                            axis: str = "shard"):
    """Distributed `SELECT key, sum(v), count(*) GROUP BY key`.

    Phase 1 (per shard): local dense-bucket segment aggregation.
    Phase 2: psum of the partial group tables across shards — the two-phase
    group/mergegroup pattern (`colexec/group` + `colexec/mergegroup`),
    with psum playing mergegroup.

    EXACT when keys are dense codes in [0, max_groups) — which is how the
    SQL layer calls it (group keys are dictionary codes / small ints). For
    large-domain keys use hash_shuffle + per-shard ops.agg.group_ids
    instead (co-locates equal keys, stays exact).

    Returns (group_keys [max_groups], sums, counts, present_mask) replicated.
    """
    def step(k_sh, v_sh, m_sh):
        bucket = jnp.clip(k_sh, 0, max_groups - 1).astype(jnp.int32)
        sums = jax.ops.segment_sum(jnp.where(m_sh, v_sh, 0), bucket,
                                   num_segments=max_groups)
        counts = jax.ops.segment_sum(m_sh.astype(jnp.int64), bucket,
                                     num_segments=max_groups)
        keys_tbl = jax.ops.segment_max(
            jnp.where(m_sh, k_sh, jnp.iinfo(k_sh.dtype).min), bucket,
            num_segments=max_groups)
        # merge partial tables across shards (mergegroup)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        keys_tbl = jax.lax.pmax(keys_tbl, axis)
        return keys_tbl, sums, counts, counts > 0

    fn = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()))
    return fn(keys, values, row_mask)


# ----------------------------------------------------------------- top-k

def sharded_topk(mesh: Mesh, vectors: jnp.ndarray, queries: jnp.ndarray,
                 k: int, axis: str = "shard") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed exact top-k: vectors row-sharded, queries replicated.

    Local matmul distances + local top_k, then all_gather(k per shard) and a
    global top_k — the cuvs sharded-mode consolidation
    (`pkg/cuvs/multi_index.go`) as two XLA collectives.
    """
    n_per, d = vectors.shape[0] // mesh.devices.size, vectors.shape[1]

    def step(v_sh, q):
        dist = D.l2_distance_sq(v_sh, q)                  # [n_sh, b]
        top_s, top_i = jax.lax.top_k(-dist.T, k)          # [b, k] local
        shard_no = jax.lax.axis_index(axis)
        gids = top_i + shard_no * n_per                   # global row ids
        all_s = jax.lax.all_gather(top_s, axis, axis=1).reshape(q.shape[0], -1)
        all_i = jax.lax.all_gather(gids, axis, axis=1).reshape(q.shape[0], -1)
        best_s, pos = jax.lax.top_k(all_s, k)
        best_i = jnp.take_along_axis(all_i, pos, axis=1)
        return -best_s, best_i

    fn = jax.shard_map(step, mesh=mesh, in_specs=(P(axis), P()),
                       out_specs=(P(), P()), check_vma=False)
    return fn(vectors, queries)


# ---------------------------------------------------------------- shuffle

class ShuffleOverflow(RuntimeError):
    """cap_per_dest was too small for the key skew; re-run with the
    reported capacity."""

    def __init__(self, needed: int):
        super().__init__(
            f"hash_shuffle bucket overflow: a destination needs capacity "
            f"{needed}; re-run with cap_per_dest >= {needed}")
        self.needed = needed


def hash_shuffle(mesh: Mesh, keys: jnp.ndarray, values: jnp.ndarray,
                 axis: str = "shard",
                 cap_per_dest: int | None = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """all_to_all hash repartition: row (k,v) moves to shard hash(k)%P.

    The reference's `colexec/shuffle` (hash mode, shuffle.go:200) + dispatch
    over morpc, as one ICI all_to_all. `cap_per_dest` is each destination
    bucket's capacity per source shard: default n_per_shard (lossless but
    output is n_dev x input rows per shard — all padding); size it to
    ~ (n_per_shard / n_dev) * skew_factor to bound memory. Undersized caps
    raise ShuffleOverflow with the needed capacity — rows are NEVER
    silently dropped (a shuffle that loses rows is a wrong-answer machine).

    Returns (keys', values') re-sharded so equal keys are co-located, with
    key == -1 marking padding slots.
    """
    n_dev = mesh.devices.size

    def step(k_sh, v_sh):
        n = k_sh.shape[0]
        cap = n if cap_per_dest is None else cap_per_dest
        dest = (H.hash_column(k_sh) % jnp.uint64(n_dev)).astype(jnp.int32)
        # stable order by destination, then slot within destination
        order = jnp.argsort(dest, stable=True)
        k_srt, v_srt, d_srt = k_sh[order], v_sh[order], dest[order]
        # position within destination bucket
        same = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                (d_srt[1:] == d_srt[:-1]).astype(jnp.int32)])
        # rank via cumsum segmented by destination
        idx = jnp.arange(n)
        seg_start = jnp.where(same == 0, idx, 0)
        start_of_dest = jax.lax.associative_scan(jnp.maximum, seg_start)
        rank = idx - start_of_dest
        # largest bucket demand (global): the overflow signal
        max_rank = jax.lax.pmax(jnp.max(rank) + 1, axis)
        slot_k = jnp.full((n_dev, cap), -1, k_sh.dtype)
        slot_v = jnp.zeros((n_dev, cap), v_sh.dtype)
        ok = rank < cap
        slot_k = slot_k.at[d_srt, jnp.where(ok, rank, cap - 1)].set(
            jnp.where(ok, k_srt, -1), mode="drop")
        slot_v = slot_v.at[d_srt, jnp.where(ok, rank, cap - 1)].set(
            jnp.where(ok, v_srt, 0), mode="drop")
        # exchange: bucket p goes to device p
        k_out = jax.lax.all_to_all(slot_k, axis, split_axis=0, concat_axis=0)
        v_out = jax.lax.all_to_all(slot_v, axis, split_axis=0, concat_axis=0)
        return k_out.reshape(-1), v_out.reshape(-1), max_rank

    fn = jax.shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                       out_specs=(P(axis), P(axis), P()))
    k_out, v_out, max_need = fn(keys, values)
    if cap_per_dest is not None:
        needed = int(jax.device_get(jnp.max(max_need)))
        if needed > cap_per_dest:
            raise ShuffleOverflow(needed)
    return k_out, v_out


# ----------------------------------------------------------- full Q1 step

def distributed_q1(mesh: Mesh, cols: dict, n_flags: int = 4,
                   n_status: int = 2, axis: str = "shard"):
    """TPC-H Q1 as ONE shard_map program over the mesh: per-shard masked
    segment aggregation into the dense (returnflag x linestatus) group
    table, merged with psum — the distributed form of the Session's Q1
    pipeline (scan rows are sharded across devices like ParallelRun DOP
    pipelines, mergegroup is a psum over ICI).

    cols: row-sharded device arrays {shipdate i32, flag i32 codes,
    status i32 codes, qty/price/disc/tax int64 scaled}, plus 'mask' bool.
    Returns replicated dense arrays keyed by group slot
    g = flag * n_status + status: sum_qty, sum_base, sum_disc, sum_charge,
    count, present.
    """
    n_groups = n_flags * n_status

    def step(flag, status, qty, price, disc, tax, mask):
        gid = (flag * n_status + status).astype(jnp.int32)
        m = mask
        disc_price = price * (100 - disc)              # scale 4
        charge = disc_price * (100 + tax)              # scale 6

        def seg(v):
            return jax.lax.psum(
                jax.ops.segment_sum(jnp.where(m, v, 0), gid,
                                    num_segments=n_groups), axis)
        out = {
            "sum_qty": seg(qty),
            "sum_base": seg(price),
            "sum_disc": seg(disc_price),
            "sum_charge": seg(charge),
            "count": jax.lax.psum(
                jax.ops.segment_sum(m.astype(jnp.int64), gid,
                                    num_segments=n_groups), axis),
        }
        out["present"] = out["count"] > 0
        return (out["sum_qty"], out["sum_base"], out["sum_disc"],
                out["sum_charge"], out["count"], out["present"])

    fn = jax.shard_map(
        step, mesh=mesh,
        in_specs=tuple([P(axis)] * 7),
        out_specs=tuple([P()] * 6))
    return fn(cols["flag"], cols["status"], cols["qty"], cols["price"],
              cols["disc"], cols["tax"], cols["mask"])

"""Distributed query execution over a device mesh.

TPU-native re-architecture of the reference's multi-CN execution
(`compile/scope.go:504 ParallelRun`, `:423 RemoteRun`, `colexec/shuffle` +
`dispatch` + `merge*`): instead of serializing operator subtrees over morpc,
the whole plan is one `shard_map`-ed XLA program and the exchange operators
become collectives on the ICI:

  reference                      here
  ---------------------------    -----------------------------------
  ParallelRun DOP pipelines      rows sharded over mesh axis "shard"
  shuffle (hash repartition)     ppermute/all_to_all inside shard_map
  broadcast join / joinmap       all_gather of build side
  merge group (two-phase agg)    local segment agg + psum
  merge top-k                    local top_k + all_gather + global top_k

Three canonical steps live here:
  * sharded_group_aggregate — two-phase distributed GROUP BY
  * sharded_topk            — distributed vector search (cuvs "sharded
                              multi-GPU" mode, cgo/cuvs/README.md)
  * hash_shuffle            — all_to_all repartition by key hash
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# mesh.py installs the jax.shard_map compat shim for jax 0.4.37 (where
# it lives in jax.experimental and the replication check is spelled
# check_rep, not check_vma) — import it before any shard_map call site.
from matrixone_tpu.parallel.mesh import make_mesh

from matrixone_tpu.ops import agg as A, distance as D, hash as H


# ---------------------------------------------------------------- group by

def sharded_group_aggregate(mesh: Mesh, keys: jnp.ndarray, values: jnp.ndarray,
                            row_mask: jnp.ndarray, max_groups: int,
                            axis: str = "shard"):
    """Distributed `SELECT key, sum(v), count(*) GROUP BY key`.

    Phase 1 (per shard): local dense-bucket segment aggregation.
    Phase 2: psum of the partial group tables across shards — the two-phase
    group/mergegroup pattern (`colexec/group` + `colexec/mergegroup`),
    with psum playing mergegroup.

    EXACT when keys are dense codes in [0, max_groups) — which is how the
    SQL layer calls it (group keys are dictionary codes / small ints). For
    large-domain keys use hash_shuffle + per-shard ops.agg.group_ids
    instead (co-locates equal keys, stays exact).

    Returns (group_keys [max_groups], sums, counts, present_mask) replicated.
    """
    def step(k_sh, v_sh, m_sh):
        bucket = jnp.clip(k_sh, 0, max_groups - 1).astype(jnp.int32)
        sums = jax.ops.segment_sum(jnp.where(m_sh, v_sh, 0), bucket,
                                   num_segments=max_groups)
        counts = jax.ops.segment_sum(m_sh.astype(jnp.int64), bucket,
                                     num_segments=max_groups)
        keys_tbl = jax.ops.segment_max(
            jnp.where(m_sh, k_sh, jnp.iinfo(k_sh.dtype).min), bucket,
            num_segments=max_groups)
        # merge partial tables across shards (mergegroup)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        keys_tbl = jax.lax.pmax(keys_tbl, axis)
        return keys_tbl, sums, counts, counts > 0

    fn = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()))
    return fn(keys, values, row_mask)


# ----------------------------------------------------------------- top-k

def sharded_topk(mesh: Mesh, vectors: jnp.ndarray, queries: jnp.ndarray,
                 k: int, axis: str = "shard") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed exact top-k: vectors row-sharded, queries replicated.

    Local matmul distances + local top_k, then all_gather(k per shard) and a
    global top_k — the cuvs sharded-mode consolidation
    (`pkg/cuvs/multi_index.go`) as two XLA collectives.
    """
    n_per, d = vectors.shape[0] // mesh.devices.size, vectors.shape[1]

    def step(v_sh, q):
        dist = D.l2_distance_sq(v_sh, q)                  # [n_sh, b]
        top_s, top_i = jax.lax.top_k(-dist.T, k)          # [b, k] local
        shard_no = jax.lax.axis_index(axis)
        gids = top_i + shard_no * n_per                   # global row ids
        all_s = jax.lax.all_gather(top_s, axis, axis=1).reshape(q.shape[0], -1)
        all_i = jax.lax.all_gather(gids, axis, axis=1).reshape(q.shape[0], -1)
        best_s, pos = jax.lax.top_k(all_s, k)
        best_i = jnp.take_along_axis(all_i, pos, axis=1)
        return -best_s, best_i

    fn = jax.shard_map(step, mesh=mesh, in_specs=(P(axis), P()),
                       out_specs=(P(), P()), check_vma=False)
    return fn(vectors, queries)


# ---------------------------------------------------------------- shuffle

class ShuffleOverflow(RuntimeError):
    """cap_per_dest was too small for the key skew; re-run with the
    reported capacity."""

    def __init__(self, needed: int):
        super().__init__(
            f"hash_shuffle bucket overflow: a destination needs capacity "
            f"{needed}; re-run with cap_per_dest >= {needed}")
        self.needed = needed


def hash_shuffle(mesh: Mesh, keys: jnp.ndarray, values: jnp.ndarray,
                 axis: str = "shard",
                 cap_per_dest: int | None = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """all_to_all hash repartition: row (k,v) moves to shard hash(k)%P.

    The reference's `colexec/shuffle` (hash mode, shuffle.go:200) + dispatch
    over morpc, as one ICI all_to_all. `cap_per_dest` is each destination
    bucket's capacity per source shard: default n_per_shard (lossless but
    output is n_dev x input rows per shard — all padding); size it to
    ~ (n_per_shard / n_dev) * skew_factor to bound memory. Undersized caps
    raise ShuffleOverflow with the needed capacity — rows are NEVER
    silently dropped (a shuffle that loses rows is a wrong-answer machine).

    Returns (keys', values') re-sharded so equal keys are co-located, with
    key == -1 marking padding slots.
    """
    n_dev = mesh.devices.size

    def step(k_sh, v_sh):
        n = k_sh.shape[0]
        cap = n if cap_per_dest is None else cap_per_dest
        dest = (H.hash_column(k_sh) % jnp.uint64(n_dev)).astype(jnp.int32)
        # stable order by destination, then slot within destination
        order = jnp.argsort(dest, stable=True)
        k_srt, v_srt, d_srt = k_sh[order], v_sh[order], dest[order]
        # position within destination bucket
        same = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                (d_srt[1:] == d_srt[:-1]).astype(jnp.int32)])
        # rank via cumsum segmented by destination
        idx = jnp.arange(n)
        seg_start = jnp.where(same == 0, idx, 0)
        start_of_dest = jax.lax.associative_scan(jnp.maximum, seg_start)
        rank = idx - start_of_dest
        # largest bucket demand (global): the overflow signal
        max_rank = jax.lax.pmax(jnp.max(rank) + 1, axis)
        slot_k = jnp.full((n_dev, cap), -1, k_sh.dtype)
        slot_v = jnp.zeros((n_dev, cap), v_sh.dtype)
        ok = rank < cap
        slot_k = slot_k.at[d_srt, jnp.where(ok, rank, cap - 1)].set(
            jnp.where(ok, k_srt, -1), mode="drop")
        slot_v = slot_v.at[d_srt, jnp.where(ok, rank, cap - 1)].set(
            jnp.where(ok, v_srt, 0), mode="drop")
        # exchange: bucket p goes to device p
        k_out = jax.lax.all_to_all(slot_k, axis, split_axis=0, concat_axis=0)
        v_out = jax.lax.all_to_all(slot_v, axis, split_axis=0, concat_axis=0)
        return k_out.reshape(-1), v_out.reshape(-1), max_rank

    fn = jax.shard_map(step, mesh=mesh, in_specs=(P(axis), P(axis)),
                       out_specs=(P(axis), P(axis), P()))
    k_out, v_out, max_need = fn(keys, values)
    if cap_per_dest is not None:
        needed = int(jax.device_get(jnp.max(max_need)))
        if needed > cap_per_dest:
            raise ShuffleOverflow(needed)
    return k_out, v_out


# ----------------------------------------------------------- full Q1 step

def distributed_q1(mesh: Mesh, cols: dict, n_flags: int = 4,
                   n_status: int = 2, axis: str = "shard"):
    """TPC-H Q1 as ONE shard_map program over the mesh: per-shard masked
    segment aggregation into the dense (returnflag x linestatus) group
    table, merged with psum — the distributed form of the Session's Q1
    pipeline (scan rows are sharded across devices like ParallelRun DOP
    pipelines, mergegroup is a psum over ICI).

    cols: row-sharded device arrays {shipdate i32, flag i32 codes,
    status i32 codes, qty/price/disc/tax int64 scaled}, plus 'mask' bool.
    Returns replicated dense arrays keyed by group slot
    g = flag * n_status + status: sum_qty, sum_base, sum_disc, sum_charge,
    count, present.
    """
    n_groups = n_flags * n_status

    def step(flag, status, qty, price, disc, tax, mask):
        gid = (flag * n_status + status).astype(jnp.int32)
        m = mask
        disc_price = price * (100 - disc)              # scale 4
        charge = disc_price * (100 + tax)              # scale 6

        def seg(v):
            return jax.lax.psum(
                jax.ops.segment_sum(jnp.where(m, v, 0), gid,
                                    num_segments=n_groups), axis)
        out = {
            "sum_qty": seg(qty),
            "sum_base": seg(price),
            "sum_disc": seg(disc_price),
            "sum_charge": seg(charge),
            "count": jax.lax.psum(
                jax.ops.segment_sum(m.astype(jnp.int64), gid,
                                    num_segments=n_groups), axis),
        }
        out["present"] = out["count"] > 0
        return (out["sum_qty"], out["sum_base"], out["sum_disc"],
                out["sum_charge"], out["count"], out["present"])

    fn = jax.shard_map(
        step, mesh=mesh,
        in_specs=tuple([P(axis)] * 7),
        out_specs=tuple([P()] * 6))
    return fn(cols["flag"], cols["status"], cols["qty"], cols["price"],
              cols["disc"], cols["tax"], cols["mask"])


# =====================================================================
# SQL shard executor: parallel/fragments.py's coordinator retargeted
# from host peers (morpc) to the device mesh.  plan_split decides the
# fragment exactly as for remote CNs; instead of shipping plan JSON to
# peers, each shard's fragment is compiled locally (PR-13 fusion intact)
# against a shard-routed scan and dispatched under that shard's device;
# the partial results merge in ONE traced program (psum over the mesh
# for dense group tables, a single jitted mergegroup otherwise).
# =====================================================================

import dataclasses
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from matrixone_tpu.sql import plan as PL
from matrixone_tpu.utils import motrace

# the mergegroup kernels + their audited compile cache (re-exported:
# tests and tools reach the cache/site/counter through this module)
from matrixone_tpu.parallel.merge_exec import (      # noqa: F401
    SITE_MERGE, _MERGE_CACHE, _MERGE_CALLS, ShardDegrade,
    _dense_merge, _general_merge, _merge_key_dicts, _merge_trackers,
    _scalar_combine)


def _shuffle_min_build() -> int:
    return int(os.environ.get("MO_SHUFFLE_BUILD_ROWS", "65536") or 65536)


@dataclasses.dataclass
class _JoinX:
    """One spine join's exchange decision."""
    prefix: tuple                  # attr path from the fragment root
    node: object                   # the ORIGINAL join node (read-only)
    mode: str                      # broadcast | shuffle | local
    lcol: Optional[str] = None     # probe-scan raw hash column (shuffle)
    rpath: Optional[tuple] = None  # path inside node.right to its scan
    rcol: Optional[str] = None     # build-scan raw hash column (shuffle)


@dataclasses.dataclass
class _XPlan:
    joins: List[_JoinX]
    probe_mode: str                # "rr" (chunk round-robin) | "hash"
    probe_col: Optional[str]
    modes_by_id: dict              # id(original node) -> mode (EXPLAIN)


def _node_at(root, path):
    cur = root
    for attr in path:
        cur = getattr(cur, attr)
    return cur


def _spine_joins(root, scan_path):
    """[(prefix, join_node)] for every join on the probe spine, top
    first, plus the probe scan node itself."""
    out = []
    cur = root
    for i, step in enumerate(scan_path):
        if step == "left":
            out.append((tuple(scan_path[:i]), cur))
        cur = getattr(cur, step)
    return out, cur


def _filter_only_scan(node):
    """(path, scan) walking Filter nodes ONLY — the join key's name maps
    1:1 onto the scan schema (a Project rename would break it)."""
    path = []
    cur = node
    while True:
        if isinstance(cur, PL.Scan):
            return tuple(path), cur
        if isinstance(cur, PL.Filter):
            path.append("child")
            cur = cur.child
            continue
        return None


def _qcol_to_raw(scan, qname: str) -> Optional[str]:
    """Qualified column name -> the scan's raw storage column, when the
    column is int-backed (hash routing domain)."""
    for (qn, d), raw in zip(scan.schema, scan.columns):
        if qn == qname:
            if d.is_varlen or not np.issubdtype(np.dtype(d.np_dtype),
                                                np.integer):
                return None
            return raw
    return None


def _partition_spec(catalog, table: str):
    try:
        return catalog.get_table(table).meta.partition
    except Exception:           # noqa: BLE001
        return None


def _co_partitioned(catalog, table: str, col: str, n_shards: int) -> bool:
    spec = _partition_spec(catalog, table)
    return (spec is not None and spec.kind == "hash"
            and spec.column == col and spec.n_parts == n_shards)


def _partition_sig(catalog, table: str):
    spec = _partition_spec(catalog, table)
    return None if spec is None else tuple(sorted(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in spec.to_json().items()))


def _shuffle_choice(j, catalog, n_shards: int):
    """Shuffle-vs-broadcast for the bottom spine join (CBO: build-side
    cardinality + the PR-13 runtime-filter key ranges).  Returns
    (mode, probe_raw_col, right_path, right_raw_col) or None ->
    broadcast."""
    from matrixone_tpu.sql import cbo
    from matrixone_tpu.sql.expr import BoundCol
    from matrixone_tpu.sql.stats import provider_for
    if j.kind != "inner" or not j.left_keys or j.residual is not None:
        return None
    lk, rk = j.left_keys[0], j.right_keys[0]
    if not (isinstance(lk, BoundCol) and isinstance(rk, BoundCol)):
        return None
    lwalk = _filter_only_scan(j.left)
    rwalk = _filter_only_scan(j.right)
    if lwalk is None or rwalk is None:
        return None
    (_lpath, lscan), (rpath, rscan) = lwalk, rwalk
    lraw = _qcol_to_raw(lscan, lk.name)
    rraw = _qcol_to_raw(rscan, rk.name)
    if lraw is None or rraw is None:
        return None
    sp = provider_for(catalog)
    est_r = cbo.estimate(j.right, sp)
    if est_r.rows < _shuffle_min_build():
        return None            # small build: replicate it, keep rr scans
    # runtime-filter bias: a build whose key range is much narrower than
    # the probe's already prunes most probe rows shard-locally through
    # the runtime filter — broadcast keeps that pruning movement-free
    est_l = cbo.estimate(j.left, sp)
    br = est_r.cols.get(rk.name)
    pr = est_l.cols.get(lk.name)
    if br and pr and None not in (br[1], br[2], pr[1], pr[2]):
        bw, pw = br[2] - br[1], pr[2] - pr[1]
        if pw > 0 and bw < pw / 4:
            return None
    mode = "local" if (_co_partitioned(catalog, lscan.table, lraw,
                                       n_shards)
                       and _co_partitioned(catalog, rscan.table, rraw,
                                           n_shards)) else "shuffle"
    return mode, lraw, rpath, rraw


def _plan_exchanges(split, catalog, n_shards: int) -> _XPlan:
    """Classify every exchange in the fragment: each spine join gets
    broadcast/shuffle/local; the probe scan gets rr or hash routing."""
    modes: dict = {}
    if split.kind == "join":
        j = split.split
        ch = _shuffle_choice(j, catalog, n_shards)
        if ch is None:
            jx = _JoinX((), j, "broadcast")
            probe_mode, probe_col = "rr", None
        else:
            mode, lraw, rpath, rraw = ch
            jx = _JoinX((), j, mode, lcol=lraw, rpath=rpath, rcol=rraw)
            probe_mode, probe_col = "hash", lraw
        modes[id(j)] = jx.mode
        lscan = _node_at(j.left, split.scan_path)
        modes[id(lscan)] = "local" if jx.mode in ("broadcast", "local") \
            else "shuffle"
        return _XPlan([jx], probe_mode, probe_col, modes)
    root = split.split.child
    joins, scan = _spine_joins(root, split.scan_path)
    xj: List[_JoinX] = []
    probe_mode, probe_col = "rr", None
    for i, (prefix, j) in enumerate(joins):
        mode, lraw, rpath, rraw = "broadcast", None, None, None
        if i == len(joins) - 1:
            ch = _shuffle_choice(j, catalog, n_shards)
            if ch is not None:
                mode, lraw, rpath, rraw = ch
                probe_mode, probe_col = "hash", lraw
        xj.append(_JoinX(prefix, j, mode, lcol=lraw, rpath=rpath,
                         rcol=rraw))
        modes[id(j)] = mode
    if probe_mode == "rr":
        modes[id(scan)] = "local"
    else:
        modes[id(scan)] = "local" if xj[-1].mode == "local" else "shuffle"
    return _XPlan(xj, probe_mode, probe_col, modes)


# ------------------------------------------------------- materialization

def _materialize(op, schema) -> PL.Materialized:
    from matrixone_tpu.parallel import fragments as FR
    arrays, valid, n = FR._collect_arrays(op, schema)
    if n == 0:
        arrays = {nm: ([] if d.is_varlen else np.zeros(0, d.np_dtype))
                  for nm, d in schema}
        valid = {nm: np.zeros(0, np.bool_) for nm, _ in schema}
    return PL.Materialized(arrays, valid, schema)


def _mat_nbytes(mat: PL.Materialized) -> int:
    total = 0
    for nm, _d in mat.schema:
        a = mat.arrays[nm]
        if isinstance(a, np.ndarray):
            total += a.nbytes
        else:
            total += len(a) + sum(len(s) for s in a if s is not None)
        v = mat.validity.get(nm)
        if isinstance(v, np.ndarray):
            total += v.nbytes
    return total


def _concat_materialized(parts, vparts, n_total, schema) -> PL.Materialized:
    if not n_total:
        arrays = {nm: ([] if d.is_varlen else np.zeros(0, d.np_dtype))
                  for nm, d in schema}
        valid = {nm: np.zeros(0, np.bool_) for nm, _ in schema}
        return PL.Materialized(arrays, valid, schema)
    arrays, valid = {}, {}
    for nm, d in schema:
        if d.is_varlen:
            merged: list = []
            for p in parts:
                merged.extend(p[nm])
            arrays[nm] = merged
        else:
            arrays[nm] = np.concatenate([p[nm] for p in parts])
        valid[nm] = np.concatenate([v[nm] for v in vparts])
    return PL.Materialized(arrays, valid, schema)


def _ex_to_materialized(ex, schema) -> PL.Materialized:
    """Finalized merge ExecBatch -> host Materialized (varlen columns
    carried as codes + their dictionary, like the peer coordinator)."""
    pres = np.asarray(jax.device_get(ex.mask)).astype(bool)
    arrays, valid, dicts = {}, {}, {}
    for name, dtype in schema:
        col = ex.batch.columns[name]
        data = np.asarray(jax.device_get(col.data))[pres]
        vm = np.asarray(jax.device_get(col.validity))[pres]
        if dtype.is_varlen:
            d = ex.dicts.get(name)
            if d is None:
                raise ShardDegrade(
                    f"varlen column {name!r} finalized without a "
                    f"dictionary")
            arrays[name] = np.clip(data.astype(np.int64), 0,
                                   max(len(d) - 1, 0)).astype(np.int32)
            dicts[name] = list(d)
        else:
            arrays[name] = data
        valid[name] = vm
    return PL.Materialized(arrays, valid, schema, dicts=dicts)


# ------------------------------------------------------------- execution

def _broadcast_builds(xp: _XPlan, ctx, n_shards: int) -> dict:
    """Materialize every broadcast join's build side ONCE; the shared
    Materialized node substitutes into all shard plans (bytes counted
    once per non-owning shard)."""
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.vm.compile import compile_plan
    out = {}
    for jx in xp.joins:
        if jx.mode != "broadcast":
            continue
        with motrace.span("shard.broadcast"):
            op = compile_plan(jx.node.right, ctx)
            mat = _materialize(op, jx.node.right.schema)
        M.exchange_broadcast_bytes.inc(_mat_nbytes(mat) * (n_shards - 1))
        out[jx.prefix] = mat
    return out


def _apply_exchanges(root, xp: _XPlan, bc: dict, s: int, n_shards: int,
                     scan_path):
    for jx in xp.joins:
        j = _node_at(root, jx.prefix)
        if jx.mode == "broadcast":
            j.right = bc[jx.prefix]
        else:
            rscan = _node_at(j.right, jx.rpath)
            rscan.hash_shard = (jx.rcol, s, n_shards)
    sc = _node_at(root, scan_path)
    if xp.probe_mode == "hash":
        sc.hash_shard = (xp.probe_col, s, n_shards)
    else:
        sc.shard = (s, n_shards)


def _exec_agg(split, xp, catalog, ctx, n_shards: int):
    from matrixone_tpu.sql.serde import plan_from_json, plan_to_json
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.vm.compile import compile_plan
    from matrixone_tpu.vm.operators import AggOp
    agg = split.split
    child_json = plan_to_json(agg.child)
    bc = _broadcast_builds(xp, ctx, n_shards)
    psig = _partition_sig(catalog, split.scan_table)
    devs = jax.devices()[:n_shards]
    parts = []
    for s in range(n_shards):
        plan_s = plan_from_json(child_json)
        _apply_exchanges(plan_s, xp, bc, s, n_shards, split.scan_path)
        with jax.default_device(devs[s]), \
                motrace.span("shard.partial", shard=s):
            child_op = compile_plan(plan_s, ctx)
            helper = AggOp(PL.Aggregate(plan_s, agg.group_keys, agg.aggs,
                                        agg.schema), child_op)
            if agg.group_keys:
                parts.append(helper.partial_state())
            else:
                parts.append(helper.partial_scalar_state())
    merger = AggOp(PL.Aggregate(agg.child, agg.group_keys, agg.aggs,
                                agg.schema), None)
    if not agg.group_keys:
        tracker = _merge_trackers([p[1] for p in parts], agg.aggs)
        merged = [None] * len(agg.aggs)
        with motrace.span("shard.merge", kind="scalar"):
            for states, _tr in parts:
                for j, a in enumerate(agg.aggs):
                    if states[j] is None:
                        continue
                    merged[j] = states[j] if merged[j] is None else \
                        _scalar_combine(a, merged[j], states[j])
            ex = merger._scalar_result(merged, tracker)
        M.exchange_partial_merge.inc(1, kind="scalar")
        return _ex_to_materialized(ex, agg.schema)
    key_dicts = _merge_key_dicts([p[2] for p in parts],
                                 len(agg.group_keys))
    tracker = _merge_trackers([p[3] for p in parts], agg.aggs)
    denses = [p[1] for p in parts if p[0] == "dense"]
    states = [p[1] for p in parts if p[0] == "general"]
    if denses and not states \
            and len({d["sizes"] for d in denses}) == 1 and len(denses) > 1:
        with motrace.span("shard.merge", kind="dense"):
            state = _dense_merge(merger, denses, psig)
        mkind = "dense"
    else:
        states = states + [merger._dense_to_state(d) for d in denses]
        if not states:
            state = merger._empty_state()
            mkind = "empty"
        elif len(states) == 1:
            state = states[0]
            mkind = "single"
        else:
            with motrace.span("shard.merge", kind="general"):
                state = _general_merge(states, agg.aggs, psig)
            mkind = "general"
    M.exchange_partial_merge.inc(1, kind=mkind)
    merger._agg_tracker = tracker
    ex = merger._finalize(state, key_dicts)
    return _ex_to_materialized(ex, agg.schema)


def _exec_topk(split, xp, catalog, ctx, n_shards: int):
    from matrixone_tpu.parallel import fragments as FR
    from matrixone_tpu.sql.serde import plan_from_json, plan_to_json
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.vm.compile import compile_plan
    tk = split.split
    tk_json = plan_to_json(tk)
    bc = _broadcast_builds(xp, ctx, n_shards)
    devs = jax.devices()[:n_shards]
    parts, vparts, n_total = [], [], 0
    for s in range(n_shards):
        loc = plan_from_json(tk_json)
        loc = dataclasses.replace(loc, k=tk.k + tk.offset, offset=0)
        _apply_exchanges(loc.child, xp, bc, s, n_shards, split.scan_path)
        with jax.default_device(devs[s]), \
                motrace.span("shard.partial", shard=s):
            op = compile_plan(loc, ctx)
            arrays, valid, n = FR._collect_arrays(op, tk.schema)
        if n:
            parts.append(arrays)
            vparts.append(valid)
            n_total += n
    mat = _concat_materialized(parts, vparts, n_total, tk.schema)
    M.exchange_partial_merge.inc(1, kind="topk")
    # the ORIGINAL TopK re-runs over the union: every global top-k row
    # is inside its shard's local top-(k+offset)
    return dataclasses.replace(tk, child=mat)


def _exec_join(split, xp, catalog, ctx, n_shards: int):
    from matrixone_tpu.parallel import fragments as FR
    from matrixone_tpu.sql.serde import plan_from_json, plan_to_json
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.vm.compile import compile_plan
    j = split.split
    jx = xp.joins[0]
    j_json = plan_to_json(j)
    bc = _broadcast_builds(xp, ctx, n_shards)
    devs = jax.devices()[:n_shards]
    parts, vparts, n_total = [], [], 0
    for s in range(n_shards):
        loc = plan_from_json(j_json)
        lscan = _node_at(loc.left, split.scan_path)
        if jx.mode == "broadcast":
            loc.right = bc[jx.prefix]
            lscan.shard = (s, n_shards)
        else:
            lscan.hash_shard = (jx.lcol, s, n_shards)
            rscan = _node_at(loc.right, jx.rpath)
            rscan.hash_shard = (jx.rcol, s, n_shards)
        with jax.default_device(devs[s]), \
                motrace.span("shard.partial", shard=s):
            op = compile_plan(loc, ctx)
            arrays, valid, n = FR._collect_arrays(op, j.schema)
        if n:
            parts.append(arrays)
            vparts.append(valid)
            n_total += n
    M.exchange_partial_merge.inc(1, kind="join")
    return _concat_materialized(parts, vparts, n_total, j.schema)


# -------------------------------------------------------------- entrypoint

def try_shard(node, catalog, ctx, n_shards: int,
              min_rows: int = 100_000):
    """Execute `node`'s distributable fragment across n_shards device
    shards and return the rewritten plan (uppers over a Materialized
    merge result), or None to run single-device.  The degrade ladder:
    mesh absent, small inputs, non-shardable operators, or any
    shard-side failure -> None (never a wrong answer)."""
    from matrixone_tpu.parallel import fragments as FR
    if n_shards < 2 or len(jax.devices()) < n_shards:
        return None
    split = FR.plan_split(node, catalog, min_rows=min_rows)
    if split is None:
        return None
    try:
        xp = _plan_exchanges(split, catalog, n_shards)
        with motrace.span("shard.exec", kind=split.kind,
                          shards=n_shards):
            if split.kind == "agg":
                leaf = _exec_agg(split, xp, catalog, ctx, n_shards)
            elif split.kind == "topk":
                leaf = _exec_topk(split, xp, catalog, ctx, n_shards)
            else:
                leaf = _exec_join(split, xp, catalog, ctx, n_shards)
    except Exception as e:      # noqa: BLE001 — degrade, never fail
        print(f"[shard] degrading to single-device execution: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None
    return FR._rebuild_uppers(split.uppers, leaf)


def explain_exchanges(node, catalog, n_shards: int,
                      min_rows: int = 100_000) -> dict:
    """id(plan node) -> exchange mode for EXPLAIN annotation; empty when
    the plan would not shard."""
    from matrixone_tpu.parallel import fragments as FR
    if n_shards < 2 or len(jax.devices()) < n_shards:
        return {}
    split = FR.plan_split(node, catalog, min_rows=min_rows)
    if split is None:
        return {}
    try:
        return _plan_exchanges(split, catalog, n_shards).modes_by_id
    except Exception:           # noqa: BLE001
        return {}

"""General distributed executor: plan fragments shipped to peer CNs.

Reference analogue: `pkg/sql/compile/remoterun.go:86 encodeScope` +
`proto/pipeline.proto:529` — the reference serializes arbitrary operator
subtrees (scans, joins, partial aggregation, top-k) and ships them to
peer CNs over morpc; each peer executes the subtree against its OWN
disttae state and the coordinator merges.

Redesign for the CN/TN split here: every CN holds a full logtail-replayed
replica, so a fragment ships as a JSON plan (sql/serde.plan_to_json) with
ONE scan marked `shard=(i, n)` — peer i reads every n-th chunk of that
scan's deterministic chunk sequence; all other scans (join build sides)
are evaluated from the peer's replica, which IS the broadcast-build: the
build data is already resident on every peer, no wire transfer needed.

Two fragment kinds (both exact):
  * partial_agg — peer runs the subtree below an Aggregate and ships raw
    partial group states (rep keys + decomposable fields); the
    coordinator re-groups them with the same mergegroup kernel AggOp
    uses, so a distributed GROUP BY over joins is bit-identical to local
    for the decomposable aggregates (sum/count/min/max int-exact, avg as
    sum+count).
  * collect — peer runs the subtree (typically ending in a local TopK)
    and ships the resulting rows; the coordinator concatenates and
    re-runs the final TopK: the global top-k of a union of per-shard
    top-(k+offset)s is exact.

Merge safety: the coordinator registers a txn lease for the duration of
the query (Engine.txn_opened), so a background merge cannot rewrite gids
under the peers' pinned snapshot.
"""

from __future__ import annotations

import dataclasses
import itertools
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container import from_device
from matrixone_tpu.ops import agg as A
from matrixone_tpu.sql import plan as P
from matrixone_tpu.sql.serde import (agg_from_json, agg_to_json,
                                     expr_to_json, plan_from_json,
                                     plan_to_json)
from matrixone_tpu.storage import arrowio
from matrixone_tpu.vm.process import ExecContext

from matrixone_tpu.sql.parser import BASIC_AGGS, STDDEV_AGGS

# the second-moment family distributes too: its sum/sumsq/count fields
# merge by addition, same as the classic five's fields
_ALLOWED_AGGS = BASIC_AGGS | STDDEV_AGGS
_dist_ids = itertools.count(1 << 40)


# =====================================================================
# peer side: execute one fragment against the local replica
# =====================================================================

def execute_fragment(catalog, header: dict) -> Tuple[dict, bytes]:
    """Run a fragment header against `catalog` (a CN's RemoteCatalog or a
    plain Engine). Returns (resp_header, arrow_blob)."""
    from matrixone_tpu.vm.compile import compile_plan
    kind = header["kind"]
    snapshot_ts = header.get("snapshot_ts")
    consumer = getattr(catalog, "consumer", None)
    if consumer is not None and snapshot_ts is not None:
        consumer.wait_ts(snapshot_ts)   # peer must reach the snapshot
    if header.get("account"):
        # tenant fragment: resolve names in the tenant's namespace
        from matrixone_tpu.frontend.auth import ScopedCatalog
        catalog = ScopedCatalog(catalog, header["account"])
    ctx = ExecContext(catalog=catalog, frozen_ts=snapshot_ts,
                      variables={"batch_rows":
                                 int(header.get("batch_rows", 1 << 16)),
                                 **header.get("session_vars", {})})
    plan = plan_from_json(header["plan"])
    child_op = compile_plan(plan, ctx)
    sig = (table_signature(catalog, header["shard_table"], snapshot_ts)
           if header.get("shard_table") else None)
    if kind == "collect":
        resp, blob = _run_collect(child_op, plan.schema)
    elif kind == "partial_agg":
        from matrixone_tpu.sql.serde import expr_from_json
        gk = [expr_from_json(k) for k in header["group_keys"]]
        aggs = [agg_from_json(a) for a in header["aggs"]]
        if gk:
            resp, blob = _run_partial_grouped(child_op, plan, gk, aggs)
        else:
            resp, blob = _run_partial_scalar(child_op, aggs)
    else:
        raise ValueError(f"unknown fragment kind {kind!r}")
    if sig is not None:
        # the layout must not have changed UNDER the scan either (a
        # merge resync swapping segment lists mid-fragment)
        after = table_signature(catalog, header["shard_table"],
                                snapshot_ts)
        if after != sig:
            raise RuntimeError("table layout changed during fragment "
                               "execution (merge resync)")
        resp["table_sig"] = sig
    return resp, blob


def _collect_arrays(op, schema):
    """Materialize the fragment's output rows as HOST arrays (strings
    decoded through each batch's dictionary — peer dicts never leave).
    -> (arrays, valid, n_total); empty dicts when no rows."""
    parts: List[dict] = []
    vparts: List[dict] = []
    n_total = 0
    for ex in op.execute():
        host = _to_host(ex, schema)
        n = len(host)
        if n == 0:
            continue
        n_total += n
        arrays, valid = {}, {}
        for name, dtype in schema:
            vec = host.columns[name]
            if dtype.is_varlen:
                arrays[name] = vec.strings.to_pylist()
            else:
                arrays[name] = np.asarray(vec.data)
            valid[name] = np.asarray(vec.valid_mask())
        parts.append(arrays)
        vparts.append(valid)
    if not parts:
        return {}, {}, 0
    arrays = {}
    valid = {}
    for name, dtype in schema:
        if dtype.is_varlen:
            merged: List[Optional[str]] = []
            for p in parts:
                merged.extend(p[name])
            arrays[name] = merged
        else:
            arrays[name] = np.concatenate([p[name] for p in parts])
        valid[name] = np.concatenate([v[name] for v in vparts])
    return arrays, valid, n_total


def _run_collect(op, schema) -> Tuple[dict, bytes]:
    arrays, valid, n_total = _collect_arrays(op, schema)
    if n_total == 0:
        return {"ok": True, "n": 0}, b""
    return ({"ok": True, "n": n_total},
            arrowio.arrays_to_ipc(arrays, valid))


def _to_host(ex, schema):
    from matrixone_tpu.ops import filter as F
    db = F.compact(ex.batch, ex.mask, ex.padded_len)
    return from_device(db, ex.dicts, schema=dict(schema))


def _run_partial_grouped(child_op, child_plan, group_keys, aggs
                         ) -> Tuple[dict, bytes]:
    """AggOp's accumulation loop, stopped BEFORE finalization: the raw
    partial state (rep keys + decomposable fields) ships to the
    coordinator, exactly like colexec/group's partial results flowing to
    mergegroup."""
    from matrixone_tpu.vm.operators import (AggOp, _agg_value,
                                            _AggDictTracker,
                                            _broadcast_full, _expr_dict)
    nkeys = len(group_keys)
    agg_node = P.Aggregate(child_plan, group_keys, aggs,
                           [("k%d" % i, k.dtype)
                            for i, k in enumerate(group_keys)]
                           + [(a.out_name, a.dtype) for a in aggs])
    helper = AggOp(agg_node, child_op)
    key_dicts: List[Optional[list]] = [None] * nkeys
    tracker = _AggDictTracker(aggs)
    state = None
    for ex in child_op.execute():
        tracker.observe(ex)
        from matrixone_tpu.vm.exprs import eval_expr
        keys = [eval_expr(k, ex) for k in group_keys]
        for i, (k_ast, k) in enumerate(zip(group_keys, keys)):
            d = _expr_dict(k_ast, ex)
            if d is not None:
                key_dicts[i] = d
        kdata = [_broadcast_full(k, ex.padded_len).data for k in keys]
        kvalid = [_broadcast_full(k, ex.padded_len).validity for k in keys]
        values = [None if (a.func == "count" and a.arg is None)
                  else _agg_value(a, ex) for a in aggs]
        part = helper._partial_vals(kdata, kvalid, ex.mask, values,
                                    allow_spill=False)
        state = part if state is None else helper._merge(state, part)
    if state is None:
        return {"ok": True, "n_groups": 0}, b""
    ng = int(jax.device_get(state["n"]))
    arrays, valid = {}, {}
    for i, k in enumerate(group_keys):
        kd = np.asarray(jax.device_get(state["keys"][i]))[:ng]
        kv = np.asarray(jax.device_get(state["kvalid"][i]))[:ng]
        if k.dtype.is_varlen:
            d = key_dicts[i] or []
            arrays[f"_g{i}"] = arrowio.to_dict_encoded(d, kd, kv)
        else:
            arrays[f"_g{i}"] = kd
        valid[f"_g{i}"] = kv
        arrays[f"_gv{i}"] = kv
        valid[f"_gv{i}"] = np.ones(ng, np.bool_)
    for j, part in enumerate(state["partials"]):
        for field, arr in part.items():
            a = np.asarray(jax.device_get(arr))[:ng]
            arrays[f"_a{j}_{field}"] = a
            valid[f"_a{j}_{field}"] = np.ones(ng, np.bool_)
    return ({"ok": True, "n_groups": ng},
            arrowio.arrays_to_ipc(arrays, valid))


def _run_partial_scalar(child_op, aggs) -> Tuple[dict, bytes]:
    from matrixone_tpu.vm.operators import _scalar_step
    states = [None] * len(aggs)
    for ex in child_op.execute():
        for i, a in enumerate(aggs):
            states[i] = _scalar_step(a, ex, states[i])
    arrays, valid = {}, {}
    have = False
    for j, (a, st) in enumerate(zip(aggs, states)):
        if st is None:
            continue
        have = True
        if a.func == "count":
            fields = {"count": st}
        elif a.func in STDDEV_AGGS:
            fields = {"sum": st[0], "sumsq": st[1], "count": st[2]}
        elif a.func in ("sum", "avg"):
            fields = {"sum": st[0], "count": st[1]}
        else:
            fields = {a.func: st[0], "count": st[1]}
        for f, v in fields.items():
            arr = np.asarray(jax.device_get(v)).reshape(1)
            arrays[f"_a{j}_{f}"] = arr
            valid[f"_a{j}_{f}"] = np.ones(1, np.bool_)
    if not have:
        return {"ok": True, "n_groups": 0}, b""
    return ({"ok": True, "n_groups": 1},
            arrowio.arrays_to_ipc(arrays, valid))


# =====================================================================
# coordinator side: split, ship, merge
# =====================================================================

_UPPER = (P.Project, P.TopK, P.Sort, P.Limit, P.Filter, P.Distinct)


@dataclasses.dataclass
class _Split:
    kind: str                    # "agg" | "topk" | "join"
    uppers: List[P.PlanNode]     # nodes above the split, root first
    split: P.PlanNode            # the Aggregate / TopK / Join at the split
    scan_path: List[str]         # attr path from fragment child to scan
    scan_table: str
    # shuffle join only: the build (right) side's own sharded scan
    right_path: Optional[List[str]] = None
    right_table: Optional[str] = None


def _find_scan_path(node) -> Optional[Tuple[List[str], str]]:
    """Path of child attrs from `node` down to a scan that is on the
    probe (left) side of every join on the way — the side whose row
    partition partitions the join output."""
    path: List[str] = []
    cur = node
    while True:
        if isinstance(cur, P.Scan):
            return path, cur.table
        if isinstance(cur, (P.Filter, P.Project)):
            path.append("child")
            cur = cur.child
            continue
        if isinstance(cur, P.Join):
            if cur.kind == "full":
                return None      # build-side unmatched rows aren't
            path.append("left")  # partitionable by probe shard
            cur = cur.left
            continue
        return None


def _has_full_join(node) -> bool:
    if isinstance(node, P.Join):
        if node.kind == "full":
            return True
        return _has_full_join(node.left) or _has_full_join(node.right)
    for attr in ("child",):
        c = getattr(node, attr, None)
        if c is not None:
            return _has_full_join(c)
    return False


def plan_split(node, catalog, min_rows: int = 0) -> Optional[_Split]:
    """Decide whether/where to distribute `node` (the compiler's Magic:
    Remote decision, compile/types.go:162). Returns None -> run local."""
    uppers: List[P.PlanNode] = []
    cur = node
    topk_at: Optional[int] = None
    while isinstance(cur, _UPPER):
        if isinstance(cur, P.TopK) and topk_at is None:
            topk_at = len(uppers)
        uppers.append(cur)
        cur = cur.child
    if isinstance(cur, P.Aggregate):
        aggs = cur.aggs
        if any(a.distinct for a in aggs):
            return None
        if any(a.func not in _ALLOWED_AGGS for a in aggs):
            return None
        if any(a.arg is not None and (a.arg.dtype.is_varlen
                                      or a.arg.dtype.is_vector)
               for a in aggs):
            return None
        if _has_full_join(cur.child):
            return None
        found = _find_scan_path(cur.child)
        if found is None:
            return None
        path, table = found
        if not _table_big_enough(catalog, table, min_rows):
            return None
        try:
            plan_to_json(cur.child)
        except TypeError:
            return None
        return _Split("agg", uppers, cur, path, table)
    if topk_at is not None:
        tk = uppers[topk_at]
        if any(k.dtype.is_varlen for k in tk.keys):
            return None
        if _has_full_join(tk.child):
            return None
        found = _find_scan_path(tk.child)
        if found is None:
            return None
        path, table = found
        if not _table_big_enough(catalog, table, min_rows):
            return None
        try:
            plan_to_json(tk)
        except TypeError:
            return None
        return _Split("topk", uppers[:topk_at], tk, path, table)
    # shuffle join (reference: plan/shuffle.go + colexec/shuffle): BOTH
    # sides big — a broadcast/replica-resident build would be the wrong
    # shape, so hash-repartition both sides across the peers by join key
    # and join each bucket locally
    if isinstance(cur, P.Join) and cur.kind == "inner" \
            and cur.left_keys and not cur.residual:
        from matrixone_tpu.sql.expr import BoundCol
        if not all(isinstance(k, BoundCol)
                   for k in cur.left_keys + cur.right_keys):
            return None
        lf = _scan_only_path(cur.left)
        rf = _scan_only_path(cur.right)
        if lf is None or rf is None:
            return None
        (lpath, ltab), (rpath, rtab) = lf, rf
        if not (_table_big_enough(catalog, ltab, min_rows)
                and _table_big_enough(catalog, rtab, min_rows)):
            return None
        try:
            plan_to_json(cur.left)
            plan_to_json(cur.right)
        except TypeError:
            return None
        return _Split("join", uppers, cur, lpath, ltab,
                      right_path=rpath, right_table=rtab)
    return None


def _scan_only_path(node) -> Optional[Tuple[List[str], str]]:
    """Scan path through Filter/Project ONLY (no joins below): each
    shuffle side must be a single sharded table scan subtree."""
    path: List[str] = []
    cur = node
    while True:
        if isinstance(cur, P.Scan):
            return path, cur.table
        if isinstance(cur, (P.Filter, P.Project)):
            path.append("child")
            cur = cur.child
            continue
        return None


def _table_big_enough(catalog, table: str, min_rows: int) -> bool:
    try:
        t = catalog.get_table(table)
        return t.n_rows >= min_rows
    except Exception:          # noqa: BLE001  (e.g. external table)
        return False


def shard_of_peer(addrs, table: str) -> Dict[int, int]:
    """Stable shard ownership (reference: pkg/shardservice
    types.go:67 — table shards placed on CN subsets, reads routed to
    owners). The peer membership comes from the keeper (launch.py wires
    --peers from registered CNs); on top of it, each table's shards map
    to peers by a deterministic hash permutation — so the SAME peer
    always scans the SAME shard of a table across queries and
    coordinators, keeping that shard's blocks warm in exactly one CN's
    block cache (cache-sharded data placement: storage holds one copy
    in the object store; ownership shards the CACHE, not the truth)."""
    import hashlib
    n = len(addrs)
    perm = sorted(range(n), key=lambda i: hashlib.sha1(
        f"{addrs[i]}|{table}".encode()).digest())
    # perm[s] = peer owning shard s  ->  invert to peer -> shard
    return {perm[s]: s for s in range(n)}


def _set_shard(plan_json: dict, path: List[str], i: int, n: int) -> dict:
    import copy
    out = copy.deepcopy(plan_json)
    cur = out
    for attr in path:
        cur = cur[attr]
    cur["shard"] = [i, n]
    return out


def _rebuild_uppers(uppers: List[P.PlanNode], leaf: P.PlanNode):
    node = leaf
    for up in reversed(uppers):
        node = dataclasses.replace(up, child=node)
    return node


import threading

from matrixone_tpu.utils import san

_pool_guard = san.lock("matrixone_tpu.parallel.fragments._pool_guard")


def pool_for(catalog) -> "FragmentPeers":
    """The catalog's shared FragmentPeers pool (double-checked creation:
    concurrent first queries must not each build and leak a pool)."""
    pool = getattr(catalog, "_frag_pool", None)
    if pool is None:
        with _pool_guard:
            pool = getattr(catalog, "_frag_pool", None)
            if pool is None:
                pool = FragmentPeers(catalog.dist_peers)
                catalog._frag_pool = pool
    return pool


class FragmentPeers:
    """Connection pool over the peer CNs' fragment endpoints (pooled
    RpcClient per peer, LANES warm sockets each — shuffle L/R overlap).
    The default timeout is generous: a cold peer jit-compiles every
    fragment shape on its first query, and a premature timeout silently
    downgrades the cluster to local execution. `MO_FRAG_TIMEOUT`
    overrides it (the chaos drills shrink it so a dead peer trips the
    breaker in seconds, after which queries degrade to local execution
    instantly instead of hanging)."""

    LANES = 2     # concurrent fragments per peer (shuffle L/R overlap)

    def __init__(self, addrs, timeout: Optional[float] = None):
        from matrixone_tpu.cluster.rpc import RpcClient, _env_float
        if timeout is None:
            timeout = _env_float("MO_FRAG_TIMEOUT", 180.0)
        self.timeout = timeout
        self.addrs = list(addrs)
        self.clients = [RpcClient(a, timeout=timeout,
                                  pool_size=self.LANES)
                        for a in self.addrs]

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def run(self, headers: List[dict]) -> List[Tuple[dict, bytes]]:
        from matrixone_tpu.cluster.rpc import deadline_scope
        n = len(self.addrs)

        def one(i):
            c = self.clients[i % n]
            # fragments are read-only: transport retries are safe, and
            # a peer whose breaker is open fails the batch instantly
            # (BreakerOpen) -> try_distribute falls back to local
            with deadline_scope(self.timeout):
                resp, blob = c.call({"op": "run_fragment", **headers[i]},
                                    retryable=True)
            if not resp.get("ok"):
                raise RuntimeError(
                    f"fragment on {self.addrs[i % n]}: "
                    f"{resp.get('err')}")
            return resp, blob
        with futures.ThreadPoolExecutor(
                max_workers=max(2, len(headers))) as pool:
            return list(pool.map(one, range(len(headers))))


def table_signature(catalog, table: str, snap: Optional[int]) -> str:
    """Fingerprint of the chunk-sequence-determining layout visible at
    `snap`: every peer must report the same one, or the shard strides do
    not partition the table (an in-flight merge resync)."""
    import hashlib
    import json as _json
    t = catalog.get_table(table)
    segs = [(s.seg_id, s.base_gid, s.n_rows) for s in t.segments
            if snap is None or s.commit_ts <= snap]
    return hashlib.sha1(_json.dumps(segs).encode()).hexdigest()


def try_distribute(node, catalog, ctx, peers: FragmentPeers,
                   min_rows: int = 0, batch_rows: int = 1 << 16):
    """If the plan qualifies, execute its lower fragment across `peers`
    and return a rebuilt plan whose split subtree is a Materialized node;
    None -> caller runs the original plan locally. Any failure —
    including the merge lease RPC — falls back to local (never wrong,
    possibly slower)."""
    if ctx.txn is not None:
        return None       # peers cannot see an open txn's workspace
    split = plan_split(node, catalog, min_rows)
    if split is None:
        return None
    did = next(_dist_ids)
    opened = False
    try:
        # lease FIRST, snapshot second: a merge committing between the
        # two would rewrite chunk sequences under the peers; with the
        # lease held no new merge can start, and the signature check in
        # _dist_* catches one already in flight
        catalog.txn_opened(did)
        opened = True
        consumer = getattr(catalog, "consumer", None)
        if consumer is not None:
            # coordinator is a CN replica: its committed_ts includes
            # LOCAL-only commits (statement tracing writes into the
            # replica's system tables) that never ride the logtail — a
            # peer can never reach that ts. The replicated frontier is
            # the consumer's applied position; everything the
            # coordinator has seen of the SHARED tables is <= it.
            snap = consumer.applied_ts or None
        else:
            snap = max(ctx.snapshot_ts or 0,
                       getattr(catalog, "committed_ts", 0)) or None
        # forward session execution knobs so SET use_pallas behaves the
        # same distributed as local (no silent local/remote divergence)
        sess_vars = {k: v for k, v in (ctx.variables or {}).items()
                     if k in ("use_pallas",)}
        if split.kind == "agg":
            mat = _dist_aggregate(split, catalog, snap, peers, batch_rows,
                                  sess_vars)
        elif split.kind == "join":
            mat = _dist_shuffle_join(split, catalog, snap, peers,
                                     batch_rows, sess_vars)
        else:
            mat = _dist_topk(split, catalog, snap, peers, batch_rows,
                             sess_vars)
    except Exception as e:     # noqa: BLE001 — fall back to local
        import sys
        print(f"[dist] fragment execution failed, running locally: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        return None
    finally:
        if opened:
            try:
                catalog.txn_closed(did)
            except Exception:  # noqa: BLE001 — lease expires on its own
                pass
    return _rebuild_uppers(split.uppers, mat)


def _check_sigs(results, addrs) -> None:
    sigs = {r[0].get("table_sig") for r in results}
    if len(sigs) > 1:
        raise RuntimeError(
            f"peers disagree on the sharded table's layout ({sigs}) — "
            f"a merge resync is in flight; falling back to local")


def _dist_aggregate(split: _Split, catalog, snap, peers: FragmentPeers,
                    batch_rows: int, sess_vars=None) -> P.Materialized:
    agg: P.Aggregate = split.split
    n = len(peers.addrs)
    child_json = plan_to_json(agg.child)
    owners = shard_of_peer(peers.addrs, split.scan_table)
    headers = []
    for i in range(n):
        headers.append({
            "kind": "partial_agg",
            "plan": _set_shard(child_json, split.scan_path,
                               owners[i], n),
            "group_keys": [expr_to_json(k) for k in agg.group_keys],
            "aggs": [agg_to_json(a) for a in agg.aggs],
            "snapshot_ts": snap,
            "batch_rows": batch_rows,
            "session_vars": sess_vars or {},
            "shard_table": split.scan_table,
            "account": getattr(catalog, "_acct", None),
        })
    results = peers.run(headers)
    _check_sigs(results, peers.addrs)
    if agg.group_keys:
        return _merge_grouped(agg, results)
    return _merge_scalar(agg, results)


def _merge_grouped(agg: P.Aggregate, results) -> P.Materialized:
    """mergegroup at the coordinator: re-encode varlen keys into a
    coordinator dictionary, concatenate all peers' partial rows, re-group
    once, finalize with the same kernels the local AggOp uses."""
    from matrixone_tpu.vm.operators import _grouped_final
    nkeys, naggs = len(agg.group_keys), len(agg.aggs)
    live = []
    for resp, blob in results:
        if resp.get("n_groups", 0) > 0:
            arrays, _valid = arrowio.ipc_to_arrays(blob)
            live.append((resp["n_groups"], arrays))
    if not live:
        arrays = {n_: [] if d_.is_varlen else np.zeros(0, d_.np_dtype)
                  for n_, d_ in agg.schema}
        return P.Materialized(arrays, {n_: np.zeros(0, np.bool_)
                                       for n_, _ in agg.schema},
                              agg.schema)
    coord_dicts: List[Optional[list]] = [None] * nkeys
    keys, kvalid = [], []
    for i, k in enumerate(agg.group_keys):
        parts = []
        if k.dtype.is_varlen:
            d: list = []
            lut: Dict[str, int] = {}
            coord_dicts[i] = d
            for ng, arrays in live:
                de = arrays[f"_g{i}"]
                enc = np.empty(len(de.cats), np.int32)
                for ci, s in enumerate(de.cats):
                    code = lut.get(s)
                    if code is None:
                        code = len(d)
                        lut[s] = code
                        d.append(s)
                    enc[ci] = code
                parts.append(enc[np.asarray(de.codes, np.int64)][:ng]
                             if len(de.cats)
                             else np.zeros(ng, np.int32))
        else:
            for ng, arrays in live:
                parts.append(np.asarray(arrays[f"_g{i}"])[:ng])
        keys.append(np.concatenate(parts))
        kvalid.append(np.concatenate(
            [np.asarray(arrays[f"_gv{i}"], bool)[:ng]
             for ng, arrays in live]))
    fields: List[Dict[str, np.ndarray]] = []
    for j in range(naggs):
        fs: Dict[str, np.ndarray] = {}
        names = {k.split("_", 2)[2] for _, arrays in live
                 for k in arrays if k.startswith(f"_a{j}_")}
        for f in names:
            fs[f] = np.concatenate(
                [np.asarray(arrays[f"_a{j}_{f}"])[:ng]
                 for ng, arrays in live])
        fields.append(fs)
    # one mergegroup pass over the concatenated partial rows
    total = len(keys[0])
    mg = 1 << max(total - 1, 1).bit_length()
    kd = [jnp.asarray(k) for k in keys]
    kv = [jnp.asarray(v) for v in kvalid]
    mask = jnp.ones((total,), jnp.bool_)
    gi = A.group_ids(kd, kv, mask, mg)
    ng = int(jax.device_get(gi.num_groups))
    if ng > mg:
        raise RuntimeError(f"merged group count {ng} > bucket {mg}")
    rep_k, rep_v = A.gather_keys(kd, kv, gi.rep_rows)
    out_arrays: Dict[str, object] = {}
    out_valid: Dict[str, np.ndarray] = {}
    out_dicts: Dict[str, list] = {}
    for i, (name, dtype) in enumerate(agg.schema[:nkeys]):
        codes = np.asarray(jax.device_get(rep_k[i]))[:ng]
        vmask = np.asarray(jax.device_get(rep_v[i]))[:ng]
        if dtype.is_varlen:
            # carry codes + the coordinator dictionary straight through
            # (MaterializedOp consumes them without per-row decode)
            out_arrays[name] = np.clip(codes, 0, None).astype(np.int32)
            out_dicts[name] = coord_dicts[i] or [""]
        else:
            out_arrays[name] = codes.astype(dtype.np_dtype)
        out_valid[name] = vmask
    for j, ((name, dtype), a) in enumerate(zip(agg.schema[nkeys:],
                                               agg.aggs)):
        merged: Dict[str, jnp.ndarray] = {}
        for f, vals in fields[j].items():
            v = jnp.asarray(vals)
            if f in ("sum", "count", "sumsq"):
                merged[f] = A.seg_sum(v, gi.gids, mask, mg)
            elif f == "min":
                merged[f] = A.seg_min(v, gi.gids, mask, mg)
            elif f == "max":
                merged[f] = A.seg_max(v, gi.gids, mask, mg)
        col = _grouped_final(a, merged, dtype)
        out_arrays[name] = np.asarray(jax.device_get(col.data))[:ng]
        out_valid[name] = np.asarray(jax.device_get(col.validity))[:ng]
    return P.Materialized(out_arrays, out_valid, agg.schema,
                          dicts=out_dicts)


def _merge_scalar(agg: P.Aggregate, results) -> P.Materialized:
    from matrixone_tpu.vm.operators import _scalar_final
    live = []
    for resp, blob in results:
        if resp.get("n_groups", 0) > 0:
            arrays, _ = arrowio.ipc_to_arrays(blob)
            live.append(arrays)
    out_arrays: Dict[str, object] = {}
    out_valid: Dict[str, np.ndarray] = {}
    for j, ((name, dtype), a) in enumerate(zip(agg.schema, agg.aggs)):
        fields: Dict[str, list] = {}
        for arrays in live:
            for k, v in arrays.items():
                if k.startswith(f"_a{j}_"):
                    fields.setdefault(k.split("_", 2)[2], []).append(
                        np.asarray(v)[0])
        if not fields:
            state = None
        elif a.func == "count":
            state = jnp.asarray(np.sum(fields["count"]))
        elif "sumsq" in fields:       # stddev/variance family
            state = (jnp.asarray(np.sum(fields["sum"], axis=0)),
                     jnp.asarray(np.sum(fields["sumsq"], axis=0)),
                     jnp.asarray(np.sum(fields["count"])))
        else:
            cnt = jnp.asarray(np.sum(fields["count"]))
            if a.func in ("sum", "avg"):
                val = jnp.asarray(np.sum(np.asarray(fields["sum"],
                                                    dtype=None), axis=0))
            elif a.func == "min":
                val = jnp.asarray(np.min(fields["min"]))
            else:
                val = jnp.asarray(np.max(fields["max"]))
            state = (val, cnt)
        col = _scalar_final(a, state, dtype)
        out_arrays[name] = np.asarray(jax.device_get(col.data))
        out_valid[name] = np.asarray(jax.device_get(col.validity))
    return P.Materialized(out_arrays, out_valid, agg.schema)


def _dist_topk(split: _Split, catalog, snap, peers: FragmentPeers,
               batch_rows: int, sess_vars=None) -> P.PlanNode:
    """Per-peer local top-(k+offset) over its shard, concatenated; the
    ORIGINAL TopK re-runs at the coordinator over the union (exact: every
    global top-k row is in its shard's local top-(k+offset))."""
    tk: P.TopK = split.split
    local = dataclasses.replace(tk, k=tk.k + tk.offset, offset=0)
    n = len(peers.addrs)
    tk_json = plan_to_json(local)
    owners = shard_of_peer(peers.addrs, split.scan_table)
    # the sharded scan sits below the TopK: path starts at tk.child
    headers = [{
        "kind": "collect",
        "plan": _set_shard(tk_json, ["child"] + split.scan_path,
                           owners[i], n),
        "snapshot_ts": snap,
        "batch_rows": batch_rows,
        "session_vars": sess_vars or {},
        "shard_table": split.scan_table,
        "account": getattr(catalog, "_acct", None),
    } for i in range(n)]
    results = peers.run(headers)
    _check_sigs(results, peers.addrs)
    arrays: Dict[str, object] = {}
    valid: Dict[str, np.ndarray] = {}
    parts = [arrowio.ipc_to_arrays(blob) for resp, blob in results
             if resp.get("n", 0) > 0]
    if not parts:
        arrays = {n_: [] if d_.is_varlen else np.zeros(0, d_.np_dtype)
                  for n_, d_ in tk.schema}
        mat = P.Materialized(arrays, {n_: np.zeros(0, np.bool_)
                                      for n_, _ in tk.schema}, tk.schema)
        return dataclasses.replace(tk, child=mat)
    for name, dtype in tk.schema:
        if dtype.is_varlen:
            merged: List[Optional[str]] = []
            for a, v in parts:
                col = a[name]
                if isinstance(col, arrowio.DictEncoded):
                    vs = np.asarray(v[name], bool)
                    merged.extend(
                        col.cats[int(c)] if ok else None
                        for c, ok in zip(col.codes.tolist(), vs.tolist()))
                else:
                    merged.extend(col)
            arrays[name] = merged
        else:
            arrays[name] = np.concatenate(
                [np.asarray(a[name]) for a, _ in parts])
        valid[name] = np.concatenate(
            [np.asarray(v[name], bool) for _, v in parts])
    mat = P.Materialized(arrays, valid, tk.schema)
    return dataclasses.replace(tk, child=mat)


# =====================================================================
# shuffle join (reference: plan/shuffle.go determineShuffleMethod +
# colexec/shuffle + dispatch): hash-repartition BOTH sides across the
# peers by join key, each peer joins its bucket locally, the
# coordinator concatenates. Exact for inner equi-joins: equal keys land
# in the same bucket on both sides.
# =====================================================================



def _stable_row_hash(cols: List[object]) -> np.ndarray:
    """Deterministic cross-process row hash of the join key columns
    (strings included) — pandas' siphash with its fixed key, combined
    across columns with an odd multiplier."""
    import pandas as pd
    out = None
    for c in cols:
        if isinstance(c, list):
            arr = np.asarray(c, dtype=object)
        else:
            arr = np.asarray(c)
            # width-normalize: hash_array(int32(-1)) != hash_array(
            # int64(-1)) (pandas zero-extends small ints) — an
            # int32-vs-bigint equi-join would silently drop matches
            if arr.dtype.kind in ("i", "u", "b"):
                arr = arr.astype(np.int64)
            elif arr.dtype.kind == "f":
                arr = arr.astype(np.float64)
        h = pd.util.hash_array(arr, categorize=False)
        out = h if out is None else (out * np.uint64(0x9E3779B1)) ^ h
    return out


class ShuffleStore:
    """Peer-side mailbox for in-flight shuffle buckets, keyed by
    (shuffle_id, side, to): receives pushes from every peer (including
    the local short-circuit) and hands the join phase a complete set.
    The destination index rides in the key so engines SHARED by several
    in-process fragment servers (tests, embed clusters) keep each
    recipient's buckets separate."""

    def __init__(self):
        self._lock = san.lock("ShuffleStore._lock")
        self._cond = san.condition(self._lock)
        self._buckets: Dict[tuple, Dict[int, bytes]] = {}
        self._born: Dict[tuple, float] = {}

    #: stale-mailbox TTL: buckets orphaned by a failed phase 1 (the
    #: coordinator also sends an explicit shuffle_drop, but a dead
    #: coordinator can't) are evicted on later traffic
    TTL_S = 600.0

    def put(self, shuffle_id, side: str, frm: int, to: int,
            blob: bytes) -> None:
        import time as _time
        now = _time.monotonic()
        with self._cond:
            self._prune_locked(now)
            self._buckets.setdefault(
                (shuffle_id, side, to), {})[frm] = blob
            self._born.setdefault((shuffle_id, side, to), now)
            self._cond.notify_all()

    def _prune_locked(self, now: float) -> None:
        for k in [k for k, t0 in self._born.items()
                  if now - t0 > self.TTL_S]:
            self._buckets.pop(k, None)
            self._born.pop(k, None)

    def wait_all(self, shuffle_id, side: str, to: int, expect: int,
                 timeout: float = 120.0) -> Dict[int, bytes]:
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._cond:
            while True:
                got = self._buckets.get((shuffle_id, side, to), {})
                if len(got) >= expect:
                    return dict(got)
                left = deadline - _time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"shuffle {shuffle_id}/{side}->{to}: "
                        f"{len(got)}/{expect} buckets after timeout")
                self._cond.wait(left)

    def drop(self, shuffle_id, to: int) -> None:
        with self._cond:
            for k in [k for k in self._buckets
                      if k[0] == shuffle_id and k[2] == to]:
                del self._buckets[k]
                self._born.pop(k, None)

    def drop_sid(self, shuffle_id) -> None:
        """Coordinator-ordered cleanup after a failed shuffle: every
        bucket of the id, all destinations."""
        with self._cond:
            for k in [k for k in self._buckets if k[0] == shuffle_id]:
                del self._buckets[k]
                self._born.pop(k, None)


def shuffle_store_for(catalog) -> ShuffleStore:
    st = getattr(catalog, "_shuffle_store", None)
    if st is None:
        st = ShuffleStore()
        catalog._shuffle_store = st
    return st


def _schema_to_json(schema) -> list:
    from matrixone_tpu.storage.engine import schema_to_json
    return schema_to_json(schema)


def _schema_from_json(rows):
    from matrixone_tpu.storage.engine import schema_from_json
    return schema_from_json(rows)


def run_shuffle_scan(catalog, header: dict) -> Tuple[dict, bytes]:
    """Phase 1 (peer side): execute the sharded scan subtree, hash rows
    into n buckets by join key, push each bucket to its owner peer
    (direct CN->CN, not through the coordinator), keep own bucket."""
    from matrixone_tpu.cluster.rpc import RpcClient
    from matrixone_tpu.vm.compile import compile_plan
    snapshot_ts = header.get("snapshot_ts")
    consumer = getattr(catalog, "consumer", None)
    if consumer is not None and snapshot_ts is not None:
        consumer.wait_ts(snapshot_ts)
    # the mailbox lives on the BASE catalog — the same object the
    # fragment server uses for incoming shuffle_put pushes (a
    # tenant-scoped wrapper would orphan the local bucket)
    store = shuffle_store_for(catalog)
    if header.get("account"):
        from matrixone_tpu.frontend.auth import ScopedCatalog
        catalog = ScopedCatalog(catalog, header["account"])
    ctx = ExecContext(catalog=catalog, frozen_ts=snapshot_ts,
                      variables={"batch_rows":
                                 int(header.get("batch_rows", 1 << 16)),
                                 **header.get("session_vars", {})})
    plan = plan_from_json(header["plan"])
    op = compile_plan(plan, ctx)
    schema = plan.schema
    key_names = header["key_names"]
    n = int(header["n_buckets"])
    me = int(header["my_index"])
    sid = str(header["shuffle_id"])
    side = header["side"]
    sig = (table_signature(catalog, header["shard_table"], snapshot_ts)
           if header.get("shard_table") else None)
    # materialize the shard's rows host-side (strings decoded) —
    # directly as arrays: only the per-destination BUCKETS serialize
    arrays, valid, n_rows = _collect_arrays(op, schema)
    if n_rows == 0:
        arrays = {nm: ([] if d.is_varlen else np.zeros(0, d.np_dtype))
                  for nm, d in schema}
        valid = {nm: np.zeros(0, np.bool_) for nm, _ in schema}
    if n_rows:
        hashes = _stable_row_hash([arrays[k] for k in key_names])
        buckets = (hashes % np.uint64(n)).astype(np.int64)
    else:
        buckets = np.zeros(0, np.int64)
    sent = 0
    for j in range(n):
        rowsel = np.nonzero(buckets == j)[0]
        ba = {}
        bv = {}
        for nm, d in schema:
            if d.is_varlen:
                src = arrays[nm]
                ba[nm] = [src[int(r)] for r in rowsel]
            else:
                ba[nm] = np.asarray(arrays[nm])[rowsel]
            bv[nm] = np.asarray(valid[nm])[rowsel]
        bblob = arrowio.arrays_to_ipc(ba, bv)
        if j == me:
            store.put(sid, side, me, me, bblob)
        else:
            c = RpcClient(tuple(header["peer_addrs"][j]), timeout=60.0)
            try:
                # idempotent: a retried put overwrites the same bucket
                # key with the same bytes
                r, _ = c.call({"op": "shuffle_put", "shuffle_id": sid,
                               "side": side, "from": me, "to": j},
                              bblob, retryable=True)
                if not r.get("ok"):
                    raise RuntimeError(r.get("err"))
            finally:
                c.close()
            sent += len(rowsel)
    out = {"ok": True, "n": n_rows, "pushed": sent}
    if sig is not None:
        after = table_signature(catalog, header["shard_table"],
                                snapshot_ts)
        if after != sig:
            raise RuntimeError("table layout changed during shuffle "
                               "scan (merge resync)")
        out["table_sig"] = sig
    return out, b""


def run_shuffle_join(catalog, header: dict) -> Tuple[dict, bytes]:
    """Phase 2 (peer side): assemble this peer's buckets of both sides,
    run the join locally, return the joined rows."""
    from matrixone_tpu.sql.serde import expr_from_json
    from matrixone_tpu.vm.compile import compile_plan
    store = shuffle_store_for(catalog)   # base catalog: same mailbox
    # as the fragment server's shuffle_put handler
    sid = str(header["shuffle_id"])
    expect = int(header["n_buckets"])
    me = int(header["my_index"])
    lschema = _schema_from_json(header["left_schema"])
    rschema = _schema_from_json(header["right_schema"])
    try:
        lparts = store.wait_all(sid, "L", me, expect)
        rparts = store.wait_all(sid, "R", me, expect)
        lmat = _concat_ipc_parts(lparts, lschema)
        rmat = _concat_ipc_parts(rparts, rschema)
    finally:
        store.drop(sid, me)
    join = P.Join(
        kind="inner",
        left=P.Materialized(lmat[0], lmat[1], lschema),
        right=P.Materialized(rmat[0], rmat[1], rschema),
        left_keys=[expr_from_json(k) for k in header["left_keys"]],
        right_keys=[expr_from_json(k) for k in header["right_keys"]],
        residual=None,
        schema=_schema_from_json(header["out_schema"]))
    ctx = ExecContext(catalog=catalog,
                      variables={"batch_rows":
                                 int(header.get("batch_rows", 1 << 16)),
                                 **header.get("session_vars", {})})
    op = compile_plan(join, ctx)
    return _run_collect(op, join.schema)


def _concat_ipc_parts(parts: Dict[int, bytes], schema):
    arrays_l: Dict[str, list] = {nm: [] for nm, _ in schema}
    valid_l: Dict[str, list] = {nm: [] for nm, _ in schema}
    for frm in sorted(parts):
        a, v = arrowio.ipc_to_arrays(parts[frm])
        if not v:
            continue
        for nm, d in schema:
            arrays_l[nm].append(a[nm])
            valid_l[nm].append(np.asarray(v[nm]))
    arrays = {}
    valid = {}
    for nm, d in schema:
        if d.is_varlen:
            merged: list = []
            for p in arrays_l[nm]:
                merged.extend(p)
            arrays[nm] = merged
        else:
            arrays[nm] = (np.concatenate(arrays_l[nm]) if arrays_l[nm]
                          else np.zeros(0, d.np_dtype))
        valid[nm] = (np.concatenate(valid_l[nm]) if valid_l[nm]
                     else np.zeros(0, np.bool_))
    return arrays, valid


def _shuffle_cleanup(peers: "FragmentPeers", sid) -> None:
    """Best-effort mailbox cleanup after a failed shuffle: peers with
    delivered buckets must not hold them until TTL (leak under repeated
    failing queries)."""
    from matrixone_tpu.cluster.rpc import RpcClient, parse_addr
    for a in peers.addrs:
        try:
            c = RpcClient(parse_addr(a), timeout=5.0)
            try:
                c.call({"op": "shuffle_drop", "shuffle_id": sid})
            finally:
                c.close()
        except Exception:      # noqa: BLE001 — cleanup is best-effort
            pass


def _dist_shuffle_join(split: _Split, catalog, snap,
                       peers: FragmentPeers, batch_rows: int,
                       sess_vars=None) -> P.Materialized:
    from matrixone_tpu.cluster.rpc import parse_addr
    import uuid as _uuid
    join: P.Join = split.split
    n = len(peers.addrs)
    # globally unique: several CN coordinators may shuffle concurrently
    # through the same peers — a per-process counter would mix their
    # mailboxes
    sid = _uuid.uuid4().hex
    peer_addrs = [list(parse_addr(a)) for a in peers.addrs]
    lkeys = [k.name for k in join.left_keys]
    rkeys = [k.name for k in join.right_keys]
    ljson = plan_to_json(join.left)
    rjson = plan_to_json(join.right)
    common = {
        "snapshot_ts": snap, "batch_rows": batch_rows,
        "session_vars": sess_vars or {},
        "account": getattr(catalog, "_acct", None),
        "shuffle_id": sid, "n_buckets": n, "peer_addrs": peer_addrs,
    }
    # phase 1: both sides scatter concurrently (all 2n fragments in one
    # pool run — the left side's buckets stream while the right scans)
    lowners = shard_of_peer(peers.addrs, split.scan_table)
    rowners = shard_of_peer(peers.addrs, split.right_table)
    headers = []
    for i in range(n):
        headers.append({**common, "kind": "shuffle_scan",
                        "plan": _set_shard(ljson, split.scan_path,
                                           lowners[i], n),
                        "side": "L", "my_index": i,
                        "key_names": lkeys,
                        "shard_table": split.scan_table})
    for i in range(n):
        headers.append({**common, "kind": "shuffle_scan",
                        "plan": _set_shard(rjson, split.right_path,
                                           rowners[i], n),
                        "side": "R", "my_index": i,
                        "key_names": rkeys,
                        "shard_table": split.right_table})
    try:
        results = peers.run(headers)
        _check_sigs(results[:n], peers.addrs)
        _check_sigs(results[n:], peers.addrs)
    except Exception:   # noqa: BLE001 — peer-side shuffle-state GC for
        # ANY phase-1 failure (transport, sig mismatch); re-raised
        _shuffle_cleanup(peers, sid)
        raise
    # phase 2: every peer joins its bucket
    jheaders = [{**common, "kind": "shuffle_join", "my_index": i,
                 "left_schema": _schema_to_json(join.left.schema),
                 "right_schema": _schema_to_json(join.right.schema),
                 "out_schema": _schema_to_json(join.schema),
                 "left_keys": [expr_to_json(k) for k in join.left_keys],
                 "right_keys": [expr_to_json(k) for k in join.right_keys]}
                for i in range(n)]
    try:
        jres = peers.run(jheaders)
    except Exception:   # noqa: BLE001 — peer-side shuffle-state GC,
        _shuffle_cleanup(peers, sid)    # then re-raised
        raise
    parts = {i: blob for i, (resp, blob) in enumerate(jres)
             if resp.get("n", 0) > 0}
    arrays, valid = _concat_ipc_parts(parts, join.schema)
    return P.Materialized(arrays, valid, join.schema)

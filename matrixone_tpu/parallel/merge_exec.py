"""Cross-shard partial-aggregate merge kernels (mergegroup).

The device-shard executor (`parallel/dist_query.py`) runs one fused
fragment per shard and collects partial group tables; the kernels here
fold those partials in ONE traced dispatch — the reference's
`colexec/mergegroup` stage:

  * `_general_merge` — sorted-hash group tables of any key shape:
    concatenate every shard's rep rows inside the trace, re-group once
    (`ops.agg.group_ids`), segment-reduce each partial field.  One
    `jax.jit` program.
  * `_dense_merge`   — same-key-space dense accumulators: elementwise
    `psum` over the mesh, one `shard_map` program.
  * `_scalar_combine`— scalar (ungrouped) aggregate algebra.

Compiled merge programs live in `_MERGE_CACHE`, keyed by (kind,
n_shards, per-shard state layout, mesh axis, partition spec) and
audited per hit as the mokey site `parallel/merge_exec.py:merge` —
every static shape a program bakes (`mg_out`, field layout) is a
runtime-audited dep, so a key collision is caught at the colliding hit.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from matrixone_tpu.ops import agg as A
from matrixone_tpu.parallel.mesh import make_mesh
from matrixone_tpu.utils import keys as keyaudit

SITE_MERGE = "parallel/merge_exec.py:merge"

#: compiled cross-shard merge programs, keyed by (kind, n_shards,
#: per-shard state layout, mesh axis, partition spec) — the sharded-
#: fragment compile-cache site audited by mokey
_MERGE_CACHE: dict = {}

#: test hook: merge-program invocations (the one-dispatch contract)
_MERGE_CALLS = {"count": 0}


class ShardDegrade(RuntimeError):
    """A shard-side condition the merge cannot absorb (divergent
    dictionaries, unmergeable partial fields): the caller re-runs the
    whole query single-device — degrade, never a wrong answer."""


def _merge_program(key, build, deps_fn):
    fn = _MERGE_CACHE.get(key)
    if fn is None:
        fn = build()
        _MERGE_CACHE[key] = fn
    if keyaudit.armed():
        keyaudit.audit(SITE_MERGE, key, deps_fn())
    return fn


def _seg_op(field: str):
    if field in ("sum", "count", "sumsq"):
        return A.seg_sum
    if field == "min":
        return A.seg_min
    if field == "max":
        return A.seg_max
    raise ShardDegrade(f"unmergeable partial field {field!r}")


def _general_merge(states, aggs, psig):
    """mergegroup over the shards' general group tables as ONE jitted
    program: concatenate every shard's rep rows (inside the trace),
    re-group once, segment-reduce each partial field."""
    n_sh = len(states)
    nkeys = len(states[0]["keys"])
    mgs = tuple(int(st["present"].shape[0]) for st in states)
    mg_out = 1 << max(sum(mgs) - 1, 1).bit_length()
    kdts = tuple(str(states[0]["keys"][i].dtype) for i in range(nkeys))
    fl = tuple(tuple(sorted(states[0]["partials"][j].keys()))
               for j in range(len(aggs)))
    fdts = tuple(tuple(str(states[0]["partials"][j][f].dtype)
                       for f in fs) for j, fs in enumerate(fl))
    for f in (f for fs in fl for f in fs):
        _seg_op(f)              # reject unmergeable layouts up front
    key = ("general", n_sh, mgs, mg_out, kdts, fl, fdts, "shard", psig)

    def build():
        def run(keys_ss, kvalid_ss, present_s, fields_ss):
            kd = [jnp.concatenate(ks) for ks in keys_ss]
            kv = [jnp.concatenate(vs) for vs in kvalid_ss]
            mask = jnp.concatenate(present_s)
            gi = A.group_ids(kd, kv, mask, mg_out)
            rep_k, rep_v = A.gather_keys(kd, kv, gi.rep_rows)
            present = jnp.arange(mg_out, dtype=jnp.int32) < gi.num_groups
            outs = []
            for fs, per_field in zip(fl, fields_ss):
                outs.append(tuple(
                    _seg_op(f)(jnp.concatenate(arrs), gi.gids, mask,
                               mg_out)
                    for f, arrs in zip(fs, per_field)))
            return (tuple(rep_k), tuple(rep_v), present, tuple(outs),
                    gi.num_groups)
        return jax.jit(run)

    def deps():
        return {"mesh_shape": (n_sh,), "shard_axis": "shard",
                "partition_spec": psig, "mg_out": mg_out, "fl": fl,
                "state_layout": (mgs, kdts, fl, fdts)}

    fn = _merge_program(key, build, deps)
    args = (tuple(tuple(st["keys"][i] for st in states)
                  for i in range(nkeys)),
            tuple(tuple(st["kvalid"][i] for st in states)
                  for i in range(nkeys)),
            tuple(st["present"] for st in states),
            tuple(tuple(tuple(st["partials"][j][f] for st in states)
                        for f in fl[j]) for j in range(len(aggs))))
    _MERGE_CALLS["count"] += 1
    rep_k, rep_v, present, outs, ng = fn(*args)
    partials = [{f: o for f, o in zip(fl[j], outs[j])}
                for j in range(len(aggs))]
    return {"keys": list(rep_k), "kvalid": list(rep_v),
            "present": present, "partials": partials, "n": ng}


def _dense_merge(helper, denses, psig):
    """Merge same-shape dense accumulators with a psum over the mesh —
    the mview delta partial-aggregate merge kernel family: elementwise
    adds of (G,)-sized partials, one shard_map program."""
    n_sh = len(denses)
    sizes = denses[0]["sizes"]
    aggs = helper.node.aggs
    layout = [("rows", None)]
    for j, a in enumerate(aggs):
        for _c, f in type(helper)._dense_fields(a):
            layout.append((f, j))

    def flat(d):
        out = [d["rows"]]
        for f, j in layout[1:]:
            out.append(d["partials"][j][f])
        return out

    flats = [flat(d) for d in denses]
    dts = tuple(str(a.dtype) for a in flats[0])
    g = int(flats[0][0].shape[0])
    key = ("dense", n_sh, g, dts, "shard", psig)

    def build():
        mesh = make_mesh(n_sh)

        def body(*cols):
            return tuple(jax.lax.psum(c[0], "shard") for c in cols)

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=tuple([P("shard")] * len(dts)),
            out_specs=tuple([P()] * len(dts)))

    def deps():
        return {"mesh_shape": (n_sh,), "shard_axis": "shard",
                "partition_spec": psig,
                "state_layout": (g, dts)}

    fn = _merge_program(key, build, deps)
    stacked = [jnp.stack([fl[i] for fl in flats])
               for i in range(len(dts))]
    _MERGE_CALLS["count"] += 1
    merged = fn(*stacked)
    out = {"sizes": sizes, "rows": merged[0],
           "partials": [dict(p) for p in denses[0]["partials"]]}
    for (f, j), arr in zip(layout[1:], merged[1:]):
        out["partials"][j][f] = arr
    return helper._dense_to_state(out)


def _merge_key_dicts(kds, nkeys: int):
    out: List[Optional[list]] = [None] * nkeys
    for i in range(nkeys):
        for kd in kds:
            d = kd[i]
            if d is None:
                continue
            cur = out[i]
            if cur is None or (d is not cur and len(d) > len(cur)):
                if cur is not None and list(d[:len(cur)]) != list(cur):
                    raise ShardDegrade(
                        "divergent group-key dictionaries across shards")
                out[i] = d
            elif d is not cur and list(d) != list(cur[:len(d)]):
                raise ShardDegrade(
                    "divergent group-key dictionaries across shards")
    return out


def _merge_trackers(trackers, aggs):
    """min/max-over-strings dictionaries must AGREE across shards:
    collation ranks are only comparable against one frozen dict."""
    from matrixone_tpu.vm.operators import _AggDictTracker
    out = _AggDictTracker(aggs)
    for tr in trackers:
        for name, d in tr.dicts.items():
            cur = out.dicts.get(name)
            if cur is None:
                out.dicts[name] = d
                out._sizes[name] = len(d)
            elif d is not cur and list(d) != list(cur):
                raise ShardDegrade(
                    "divergent min/max string dictionaries across shards")
    return out


def _scalar_combine(a, s1, s2):
    if a.func == "count" and a.arg is None:
        return s1 + s2
    if a.func == "count":
        return s1 + s2
    if a.func in ("sum", "avg"):
        return (s1[0] + s2[0], s1[1] + s2[1])
    if a.func == "min":
        return (jnp.minimum(s1[0], s2[0]), s1[1] + s2[1])
    if a.func == "max":
        return (jnp.maximum(s1[0], s2[0]), s1[1] + s2[1])
    # stddev/variance family: (sum, sumsq, count)
    return (s1[0] + s2[0], s1[1] + s2[1], s1[2] + s2[2])

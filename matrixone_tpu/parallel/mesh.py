"""Device mesh construction (reference analogue: cluster topology).

The reference scales by adding stateless CNs and shipping operator subtrees
over morpc (`pkg/sql/compile/remoterun.go:86`); the TPU-native equivalent is
a `jax.sharding.Mesh` whose axes carry the same roles:

  axis "shard"  — data placement: table rows / index vectors partitioned
                  across devices (reference: pkg/shardservice + ParallelRun
                  DOP splitting, compile/scope.go:504)

Collectives over ICI replace the shuffle/dispatch/merge operator trio
(`colexec/{shuffle,dispatch,merge}`).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map only exists as a top-level name on newer jax; this image
# ships 0.4.37 where it lives in jax.experimental and the replication
# check is spelled check_rep, not check_vma.  Every mesh consumer imports
# this module, so the shim installs before any shard_map call site runs.
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    jax.shard_map = _compat_shard_map


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "shard") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_rows(mesh: Mesh, arr, axis_name: str = "shard"):
    """Place a [n, ...] array row-sharded over the mesh."""
    spec = P(axis_name, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P()))

"""Remote pipeline scopes: fan table chunks out to worker PROCESSES over
gRPC and merge partial aggregation states on the coordinator.

Reference analogue: `pkg/sql/compile/remoterun.go:86 encodeScope` — the
reference serializes operator subtrees as protobuf and ships them to peer
CNs over morpc; here the stage descriptor is the sql/serde JSON form of
bound expressions + agg calls, shipped over the worker gRPC seam
(`worker/server.py`), and the merge half is the same sort/segment
mergegroup kernel the local AggOp uses — a worker is a remote pipeline
fragment, not a special case.

The partial-agg contract is exact for the decomposable aggregates
(sum/count/min/max int64-exact, avg as sum+count), so a distributed run
returns bit-identical results to the single-process plan.
"""

from __future__ import annotations

from concurrent import futures
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.ops import agg as A
from matrixone_tpu.sql.expr import AggCall, BoundExpr
from matrixone_tpu.sql.serde import agg_to_json, dtype_to_json, expr_to_json
from matrixone_tpu.storage import arrowio
from matrixone_tpu.worker.client import WorkerClient


class RemoteScopeCoordinator:
    """Ship group-aggregate scopes to N worker processes, merge partials.

    Workers are addressed by gRPC endpoints ("127.0.0.1:PORT"); each chunk
    of the scan becomes one Run(group_aggregate) call; per-chunk partial
    states (representative keys + decomposable partial fields) merge on
    the coordinator exactly like AggOp._merge folds per-batch partials."""

    def __init__(self, addrs: Sequence[str], max_groups: int = 65536):
        self.clients = [WorkerClient(a) for a in addrs]
        self.max_groups = max_groups

    def close(self) -> None:
        for c in self.clients:
            c.close()

    # ------------------------------------------------------------ scope
    def group_aggregate(
            self,
            chunks,                        # iterable of (arrays, validity)
            schema: Dict[str, dt.DType],   # column -> dtype (codes INT32)
            group_keys: List[BoundExpr],
            aggs: List[AggCall],
            filters: Optional[List[BoundExpr]] = None,
            out_dtypes: Optional[List[dt.DType]] = None,
    ) -> Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray], int]:
        """Returns (key_arrays, key_valids, agg_arrays, n_groups)."""
        header = {
            "op": "group_aggregate",
            "schema": {c: dtype_to_json(d) for c, d in schema.items()},
            "group_keys": [expr_to_json(k) for k in group_keys],
            "aggs": [agg_to_json(a) for a in aggs],
            "max_groups": self.max_groups,
        }
        if filters:
            # workers apply filters by masking rows before grouping: fold
            # them into the group step by pre-masking via filter_project?
            # -> simplest exact form: AND all filters into the row mask by
            # shipping them as an extra "filters" field the worker applies
            header["filters"] = [expr_to_json(f) for f in filters]

        def run_one(i_chunk):
            from matrixone_tpu.cluster.rpc import TransportError
            i, (arrays, validity) = i_chunk
            blob = arrowio.arrays_to_ipc(arrays, validity)
            n = len(self.clients)
            # chunk-level failover: the stage is pure compute over the
            # shipped chunk, so when a worker stays unreachable after
            # the client's own retries the chunk reroutes to the next
            # worker instead of failing the whole distributed scope
            last: Exception = None
            for hop in range(n):
                client = self.clients[(i + hop) % n]
                try:
                    # client.run raises RuntimeError on worker error
                    # headers (non-transport: never rerouted)
                    rh, rblob = client.run(header, blob)
                    break
                except (TransportError, ConnectionError) as e:
                    last = e
            else:
                raise last
            parts, _ = arrowio.ipc_to_arrays(rblob)
            return rh["n_groups"], parts

        with futures.ThreadPoolExecutor(
                max_workers=max(2, len(self.clients))) as pool:
            results = list(pool.map(run_one, enumerate(chunks)))

        nk, na = len(group_keys), len(aggs)
        results = [(n, p) for n, p in results if n > 0]
        if not results:
            return [np.empty(0)] * nk, [np.empty(0, bool)] * nk, \
                [np.empty(0)] * na, 0
        # concat per-chunk partial states, trimmed to their live groups
        keys = [np.concatenate([p[f"_g{i}"][:n] for n, p in results])
                for i in range(nk)]
        kvalid = [np.concatenate([
            np.asarray(p.get(f"_gv{i}", np.ones(n, bool)))[:n]
            for n, p in results]) for i in range(nk)]
        fields: List[Dict[str, np.ndarray]] = []
        for j in range(na):
            fs = {}
            for fname in {k.split("_", 2)[2] for n, p in results
                          for k in p if k.startswith(f"_a{j}_")}:
                fs[fname] = np.concatenate(
                    [p[f"_a{j}_{fname}"][:n] for n, p in results])
            fields.append(fs)
        return self._merge_states(keys, kvalid, fields, aggs, out_dtypes)

    def _merge_states(self, keys, kvalid, fields, aggs, out_dtypes):
        """mergegroup over concatenated partial rows (AggOp._merge's
        kernel, applied once at the coordinator)."""
        from matrixone_tpu.vm.operators import _grouped_merge
        n = len(keys[0])
        mg = self.max_groups
        kd = [jnp.asarray(k) for k in keys]
        kv = [jnp.asarray(v) for v in kvalid]
        mask = jnp.ones((n,), jnp.bool_)
        gi = A.group_ids(kd, kv, mask, mg)
        ng = int(jax.device_get(gi.num_groups))
        if ng > mg:
            raise RuntimeError(f"merged group count {ng} > {mg}")
        rep_k, rep_v = A.gather_keys(kd, kv, gi.rep_rows)
        merged = []
        for j, a in enumerate(aggs):
            half = {f: jnp.asarray(v) for f, v in fields[j].items()}
            # _grouped_merge concatenates two halves; here the concat is
            # already done, so merge "half" with an empty second state
            part = {}
            for f, vals in half.items():
                if f in ("sum", "count", "sumsq"):
                    part[f] = A.seg_sum(vals, gi.gids, mask, mg)
                elif f == "min":
                    part[f] = A.seg_min(vals, gi.gids, mask, mg)
                elif f == "max":
                    part[f] = A.seg_max(vals, gi.gids, mask, mg)
            merged.append(part)
        out_vals = []
        from matrixone_tpu.vm.operators import _grouped_final
        for j, a in enumerate(aggs):
            dtype = out_dtypes[j] if out_dtypes else dt.FLOAT64
            col = _grouped_final(a, merged[j], dtype)
            out_vals.append(np.asarray(jax.device_get(col.data))[:ng])
        return ([np.asarray(jax.device_get(k))[:ng] for k in rep_k],
                [np.asarray(jax.device_get(v))[:ng] for v in rep_v],
                out_vals, ng)

"""Publications & subscriptions: share tables across clusters.

Reference analogue: MatrixOne's publication/subscription surface
(`CREATE PUBLICATION` / `CREATE DATABASE ... FROM ... PUBLICATION`,
mo_pubs/mo_subs in pkg/frontend + pkg/catalog). Redesign: a publication
is a durable named table set on the publisher engine; a subscription
materializes mirrors on the subscriber and keeps them synced with one
CdcTask per table (backfill for initial state, logtail subscription for
liveness — the same machinery the reference's publication sync rides).

Scope note (honest): live sync requires the publisher's in-process
logtail hook, so publisher and subscriber must share a process (two
embed Clusters / Engines). A cross-process subscriber would ride the
same CdcTask over a logtail RPC feed — the seam is `engine.subscribe`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from matrixone_tpu.cdc import CdcTask, SQLSink


def create_table_ddl(meta, name: Optional[str] = None) -> str:
    """CREATE TABLE DDL from a TableMeta (mirror bootstrap)."""
    cols = []
    for c, d in meta.schema:
        extra = " auto_increment" if c == meta.auto_increment else ""
        if c in (meta.not_null or []):
            extra += " not null"
        cols.append(f"`{c}` {d}{extra}")
    if meta.primary_key:
        cols.append("primary key (" + ", ".join(meta.primary_key) + ")")
    return (f"create table `{name or meta.name}` ("
            + ", ".join(cols) + ")")


class Subscription:
    """Live mirror of one publication into a subscriber session."""

    def __init__(self, name: str, publisher_engine, publication: str,
                 subscriber_session):
        pubs = getattr(publisher_engine, "publications", {})
        if publication not in pubs:
            raise ValueError(f"no such publication {publication!r}")
        self.name = name
        self.publication = publication
        self.publisher = publisher_engine
        self.session = subscriber_session
        self.tables: List[str] = list(pubs[publication])
        self._tasks: List[CdcTask] = []

    def start(self) -> "Subscription":
        for t in self.tables:
            meta = self.publisher.get_table(t).meta
            self.session.execute(create_table_ddl(meta))
            task = CdcTask(self.publisher, t,
                           SQLSink(self.session, target_table=t))
            # subscribe FIRST, then backfill from the pre-subscribe
            # watermark: a commit landing between the two is delivered
            # twice at worst (the PK sink upserts), never zero times —
            # backfill-then-subscribe would lose it
            wm0 = task.watermark
            task.start()
            task.backfill(from_ts=wm0)
            self._tasks.append(task)
        return self

    def stop(self) -> None:
        for t in self._tasks:
            t.stop()
        self._tasks = []


def subscribe(name: str, publisher_engine, publication: str,
              subscriber_session) -> Subscription:
    sub = Subscription(name, publisher_engine, publication,
                       subscriber_session).start()
    subs = getattr(subscriber_session.catalog, "subscriptions", None)
    if subs is None:
        subs = subscriber_session.catalog.subscriptions = {}
    subs[name] = sub
    return sub

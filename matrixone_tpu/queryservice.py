"""Query service: cluster-wide process list + query cancellation.

Reference analogue: `pkg/queryservice` (cross-CN query/kill RPC behind
SHOW PROCESSLIST and KILL, frontend/mysql_cmd_executor kill handling).
Redesign: sessions of one engine share a ProcessRegistry keyed by
connection id; KILL flips a flag the executor's pull loop checks between
device batches — cancellation lands at batch granularity, which is the
natural preemption point of the batch-at-a-time XLA execution model
(mid-batch interruption would mean cancelling a compiled computation).
"""

from __future__ import annotations

import threading

from matrixone_tpu.utils import san
import time
from typing import Dict, Optional


class QueryKilled(RuntimeError):
    pass


def account_of(user: str) -> str:
    """Tenant account of a registered user label. Sessions register as
    "account:user" (frontend/session.py); bare labels are engine-internal
    (embed, tests) and belong to the sys tenant."""
    return user.split(":", 1)[0] if ":" in user else "sys"


class ProcessRegistry:
    def __init__(self):
        self._lock = san.lock("ProcessRegistry._lock")
        self._next_id = 1
        # conn_id -> record
        self._procs: Dict[int, dict] = {}

    def register(self, user: str = "root") -> int:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            self._procs[cid] = {"id": cid, "user": user, "state": "idle",
                                "query": "", "started": 0.0,
                                "killed": False, "terminated": False}
            return cid

    def unregister(self, cid: int) -> None:
        with self._lock:
            self._procs.pop(cid, None)

    def start_query(self, cid: int, sql: str) -> None:
        with self._lock:
            rec = self._procs.get(cid)
            if rec is not None:
                rec.update(state="running", query=sql,
                           started=time.monotonic(), killed=False)

    def end_query(self, cid: int) -> None:
        with self._lock:
            rec = self._procs.get(cid)
            if rec is not None:
                rec.update(state="idle", query="", killed=False)

    def set_queued(self, cid: int, queued: bool) -> None:
        """Admission control (serving/admission.py) flips the visible
        state while a statement waits for a slot, so SHOW PROCESSLIST
        distinguishes queue time from execute time."""
        with self._lock:
            rec = self._procs.get(cid)
            if rec is not None and rec["state"] in ("running", "queued"):
                rec["state"] = "queued" if queued else "running"

    def kill(self, cid: int, query_only: bool = True) -> bool:
        """KILL QUERY interrupts the current statement; plain KILL (the
        MySQL connection form) additionally marks the connection
        terminated — every later statement on it fails until the owner
        closes it."""
        with self._lock:
            rec = self._procs.get(cid)
            if rec is None:
                return False
            rec["killed"] = True
            if not query_only:
                rec["terminated"] = True
            return True

    def check_killed(self, cid: int) -> None:
        with self._lock:
            rec = self._procs.get(cid)
            killed = rec is not None and (rec["killed"] or rec["terminated"])
        if killed:
            raise QueryKilled(f"query of connection {cid} was killed")

    def owner_account(self, cid: int) -> Optional[str]:
        """Tenant account owning a connection; None if no such conn."""
        with self._lock:
            rec = self._procs.get(cid)
            return None if rec is None else account_of(rec["user"])

    def is_terminated(self, cid: int) -> bool:
        with self._lock:
            rec = self._procs.get(cid)
            return rec is not None and rec["terminated"]

    def processlist(self):
        with self._lock:
            now = time.monotonic()
            return [{"Id": r["id"], "User": r["user"], "State": r["state"],
                     "Time": (round(now - r["started"], 3)
                              if r["state"] == "running" else 0.0),
                     "Query": r["query"]}
                    for r in sorted(self._procs.values(),
                                    key=lambda r: r["id"])]


def registry_for(engine) -> ProcessRegistry:
    reg = getattr(engine, "_queryservice", None)
    if reg is None:
        reg = ProcessRegistry()
        engine._queryservice = reg
    return reg

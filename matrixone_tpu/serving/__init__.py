"""Serving layer: plan cache + snapshot-consistent result cache +
admission control (one per CN process / engine).

The session execute path (frontend/session.py) consults this state for
every statement; `mo_ctl('serving', ...)` exposes runtime status and
control. Knobs:

  MO_PLAN_CACHE=0            disable the plan cache (default: on)
  MO_PLAN_CACHE_SIZE=N       plan cache entries (default 256, LRU)
  MO_RESULT_CACHE_MB=N       result cache budget in MB (default 0 = off)
  MO_RESULT_CACHE=0          force the result cache off
  MO_ADMISSION_SLOTS=N       concurrent statements (default 0 = off)
  MO_ADMISSION_QUEUE_MS      interactive queue budget (default 5000)
  MO_ADMISSION_BG_QUEUE_MS   background queue budget (default 500)
  MO_ADMISSION_ACCOUNT_SLOTS per-account concurrency (default 0 = inf)
"""

from __future__ import annotations

import os

from matrixone_tpu.serving.admission import (AdmissionController,
                                             AdmissionRejected)
from matrixone_tpu.serving.plan_cache import NONDET_FUNCS, PlanCache
from matrixone_tpu.serving.result_cache import ResultCache

__all__ = ["ServingState", "serving_for", "AdmissionRejected",
           "PlanCache", "ResultCache", "AdmissionController",
           "NONDET_FUNCS"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ServingState:
    """The per-engine bundle the session execute path consults."""

    def __init__(self):
        self.plan_cache = PlanCache(
            max_entries=_env_int("MO_PLAN_CACHE_SIZE", 256),
            enabled=os.environ.get("MO_PLAN_CACHE", "1") != "0")
        mb = _env_int("MO_RESULT_CACHE_MB", 0)
        if os.environ.get("MO_RESULT_CACHE") == "0":
            mb = 0
        self.result_cache = ResultCache(max_bytes=mb << 20)
        self.admission = AdmissionController(
            slots=_env_int("MO_ADMISSION_SLOTS", 0),
            queue_ms=_env_float("MO_ADMISSION_QUEUE_MS", 5000.0),
            bg_queue_ms=_env_float("MO_ADMISSION_BG_QUEUE_MS", 500.0),
            account_slots=_env_int("MO_ADMISSION_ACCOUNT_SLOTS", 0))

    def status(self) -> dict:
        return {"plan_cache": self.plan_cache.stats(),
                "result_cache": self.result_cache.stats(),
                "admission": self.admission.stats()}

    def clear(self) -> None:
        self.plan_cache.clear()
        self.result_cache.clear()


def serving_for(catalog) -> ServingState:
    """One ServingState per engine facade on this process: tenant
    sessions (ScopedCatalog) share their engine's state — cache keys
    carry the account scope — and a CN's RemoteCatalog gets its own
    (serving is per-CN, like the reference's proxy tier)."""
    host = getattr(catalog, "_inner", catalog)
    sv = getattr(host, "_serving", None)
    if sv is None:
        sv = ServingState()
        try:
            host._serving = sv
        except Exception:       # noqa: BLE001 — facade refuses attrs:
            pass                # serve uncached rather than fail
    return sv

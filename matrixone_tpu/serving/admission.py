"""Admission control: two-lane priority queue + per-account quotas +
load shedding.

Reference analogue: the proxy/queryservice tier that gates every session
in the reference deployment — here a per-CN `AdmissionController` that
workload statements (SELECT/DML/LOAD) pass through before executing:

  * two lanes: `interactive` (default) and `background`
    (`SET query_priority = 'background'`).  Freed slots go to the
    interactive lane first; background admits only when no interactive
    query is waiting.
  * per-account concurrency quotas (accounts from frontend/auth.py):
    an account at its quota queues even while global slots are free,
    WITHOUT blocking other accounts behind it (per-waiter eligibility,
    not head-of-line).
  * queue wait is bounded: the lane budget (`MO_ADMISSION_QUEUE_MS`,
    background `MO_ADMISSION_BG_QUEUE_MS`) capped by the PR-2 deadline
    budget (`cluster.rpc.current_deadline`).  On exhaustion the query
    is SHED with `AdmissionRejected` — a clean retryable error instead
    of a collapsing pile-up.
  * KILL integration: a queued query polls its ProcessRegistry slot, so
    `KILL QUERY <id>` removes it from the queue (QueryKilled) instead
    of letting a dead client occupy a waiting slot.

Disabled by default (`MO_ADMISSION_SLOTS=0`); arm via env or
`mo_ctl('serving', 'slots:<n>')`.  Every submitted query lands in
exactly one `mo_admission_total{lane,outcome}` bucket:
admitted | shed_capacity | shed_timeout | shed_deadline | killed.
"""

from __future__ import annotations

import threading

from matrixone_tpu.utils import san
import time
from collections import deque
from typing import Optional

from matrixone_tpu.queryservice import QueryKilled

#: wait-slice granularity: KILL/deadline reaction time while queued
_SLICE_S = 0.05

LANES = ("interactive", "background")


class AdmissionRejected(RuntimeError):
    """Load shed — safe to retry on this or another CN."""
    retryable = True


class _Waiter:
    __slots__ = ("account", "lane", "admitted", "enq")

    def __init__(self, account: str, lane: str):
        self.account = account
        self.lane = lane
        self.admitted = False
        self.enq = time.monotonic()


class _Ticket:
    """Held while the admitted statement runs; release() frees the slot."""
    __slots__ = ("ctl", "account", "queue_wait_s", "_done")

    def __init__(self, ctl, account: str, queue_wait_s: float):
        self.ctl = ctl
        self.account = account
        self.queue_wait_s = queue_wait_s
        self._done = False

    def release(self) -> None:
        if self._done:
            return
        self._done = True
        self.ctl._release(self.account)


class AdmissionController:
    def __init__(self, slots: int = 0, queue_ms: float = 5000.0,
                 bg_queue_ms: float = 500.0, account_slots: int = 0,
                 max_queue: int = 256):
        self._cv = san.condition("AdmissionController._cv")
        san.guard(self, self._cv, name="AdmissionController")
        self.slots = slots                  # 0 = admission disabled
        self.queue_ms = queue_ms
        self.bg_queue_ms = bg_queue_ms
        self.account_slots = account_slots  # 0 = unlimited per account
        self.max_queue = max_queue
        self.running = 0
        self._by_account: dict = {}
        self._queues = {lane: deque() for lane in LANES}

    @property
    def enabled(self) -> bool:
        return self.slots > 0

    # ---------------------------------------------------------- internals
    def _account_free(self, account: str) -> bool:
        return (self.account_slots <= 0
                or self._by_account.get(account, 0) < self.account_slots)

    def _dispatch(self) -> None:
        """Admit eligible waiters, interactive lane first (under _cv).
        Background admits only when no interactive waiter is CURRENTLY
        eligible — but interactive waiters stuck on their account quota
        must not starve other work while global slots sit free (after
        the interactive scan, anyone still queued is quota-blocked)."""
        san.mutating(self)
        for lane in LANES:
            q = self._queues[lane]
            for w in list(q):
                if self.running >= self.slots:
                    return      # slots gone: priority order preserved
                if not self._account_free(w.account):
                    continue        # quota-blocked: skip, don't block lane
                q.remove(w)
                w.admitted = True
                self.running += 1
                self._by_account[w.account] = \
                    self._by_account.get(w.account, 0) + 1

    def _release(self, account: str) -> None:
        from matrixone_tpu.utils import metrics as M
        with self._cv:
            san.mutating(self)
            self.running -= 1
            n = self._by_account.get(account, 1) - 1
            if n <= 0:
                self._by_account.pop(account, None)
            else:
                self._by_account[account] = n
            self._dispatch()
            self._cv.notify_all()
            M.admission_running.set(self.running)
            M.admission_queued.set(
                sum(len(q) for q in self._queues.values()))

    # ------------------------------------------------------------ acquire
    def acquire(self, account: str = "sys", lane: str = "interactive",
                conn_id: Optional[int] = None, registry=None) -> _Ticket:
        """Block until admitted; raise AdmissionRejected on shed and
        QueryKilled when the queued query is killed."""
        from matrixone_tpu.utils import metrics as M
        if lane not in LANES:
            lane = "interactive"
        if not self.enabled:
            # pre-released: this ticket never incremented any counter, so
            # its release() must not decrement one (an operator flipping
            # slots mid-flight would otherwise corrupt `running` forever)
            t = _Ticket(self, account, 0.0)
            t._done = True
            return t

        budget_s = (self.bg_queue_ms if lane == "background"
                    else self.queue_ms) / 1000.0
        try:
            from matrixone_tpu.cluster.rpc import current_deadline
            dl = current_deadline()
        except Exception:       # noqa: BLE001 — rpc layer optional here
            dl = None
        if dl is not None:
            rem = dl.remaining()
            if rem <= 0:
                M.admission_total.inc(lane=lane, outcome="shed_deadline")
                raise AdmissionRejected(
                    "admission: deadline exhausted before execution; "
                    "retry with a fresh deadline")
            budget_s = min(budget_s, rem)

        with self._cv:
            # fast path: a free slot and an empty (or quota-eligible) queue
            if self.running < self.slots and self._account_free(account) \
                    and not self._queues["interactive"] \
                    and (lane == "interactive"
                         or not self._queues["background"]):
                self.running += 1
                self._by_account[account] = \
                    self._by_account.get(account, 0) + 1
                M.admission_total.inc(lane=lane, outcome="admitted")
                M.admission_running.set(self.running)
                return _Ticket(self, account, 0.0)
            if sum(len(q) for q in self._queues.values()) >= self.max_queue:
                M.admission_total.inc(lane=lane, outcome="shed_capacity")
                raise AdmissionRejected(
                    f"admission: queue full ({self.max_queue} waiting); "
                    f"server overloaded, retry later")
            w = _Waiter(account, lane)
            san.mutating(self)
            self._queues[lane].append(w)
            M.admission_queued.set(
                sum(len(q) for q in self._queues.values()))
            if registry is not None and conn_id is not None:
                registry.set_queued(conn_id, True)
            from matrixone_tpu.utils import motrace
            self._dispatch()     # may admit immediately (e.g. the only
            deadline = time.monotonic() + budget_s   # blockers are
            try:                                     # quota-blocked)
                with motrace.span("admission.queue", lane=lane):
                    while not w.admitted:
                        if registry is not None and conn_id is not None:
                            try:
                                registry.check_killed(conn_id)
                            except QueryKilled:
                                # only a REAL kill counts as
                                # outcome=killed; an internal registry
                                # error must surface as itself, not
                                # skew the shed accounting
                                M.admission_total.inc(lane=lane,
                                                      outcome="killed")
                                raise
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            M.admission_total.inc(lane=lane,
                                                  outcome="shed_timeout")
                            raise AdmissionRejected(
                                f"admission: no {lane} slot within "
                                f"{budget_s * 1000:.0f} ms "
                                f"({self.running}/{self.slots} running); "
                                f"server busy, retry later")
                        self._cv.wait(min(remaining, _SLICE_S))
                        self._dispatch()
            except BaseException:    # noqa: BLE001 — cleanup-only,
                # re-raised below; incl. KeyboardInterrupt so an
                # interrupted waiter never leaks its queue ticket.
                # not admitted: leave the queue; admitted mid-exception
                # (can't happen once removed, but belt and braces):
                # release the slot
                if w.admitted:
                    self.running -= 1
                    n = self._by_account.get(account, 1) - 1
                    if n <= 0:
                        self._by_account.pop(account, None)
                    else:
                        self._by_account[account] = n
                    self._dispatch()
                    self._cv.notify_all()
                else:
                    try:
                        self._queues[lane].remove(w)
                    except ValueError:
                        pass
                M.admission_queued.set(
                    sum(len(q) for q in self._queues.values()))
                if registry is not None and conn_id is not None:
                    registry.set_queued(conn_id, False)
                raise
            if registry is not None and conn_id is not None:
                registry.set_queued(conn_id, False)
            wait_s = time.monotonic() - w.enq
            M.admission_total.inc(lane=lane, outcome="admitted")
            M.admission_queue_seconds.observe(wait_s)
            M.admission_running.set(self.running)
            M.admission_queued.set(
                sum(len(q) for q in self._queues.values()))
            return _Ticket(self, account, wait_s)

    # ------------------------------------------------------------- status
    def stats(self) -> dict:
        from matrixone_tpu.utils import metrics as M
        with self._cv:
            queued = {lane: len(q) for lane, q in self._queues.items()}
            return {
                "slots": self.slots, "running": self.running,
                "queued": queued,
                "account_slots": self.account_slots,
                "queue_ms": self.queue_ms,
                "bg_queue_ms": self.bg_queue_ms,
                "by_account": dict(self._by_account),
                "admitted": {lane: int(M.admission_total.get(
                    lane=lane, outcome="admitted")) for lane in LANES},
                "shed": {lane: int(
                    M.admission_total.get(lane=lane,
                                          outcome="shed_capacity")
                    + M.admission_total.get(lane=lane,
                                            outcome="shed_timeout")
                    + M.admission_total.get(lane=lane,
                                            outcome="shed_deadline"))
                    for lane in LANES},
                "killed": {lane: int(M.admission_total.get(
                    lane=lane, outcome="killed")) for lane in LANES},
                "enabled": self.enabled,
            }

"""Plan cache: normalize statements (literals -> parameters) and reuse
bound+optimized plans across executions.

Reference analogue: the frontend's prepared-statement plan reuse plus
`pkg/sql/plan/function` plan caching — a repeated ad-hoc point query and
a prepared statement both skip parse -> bind -> optimize and jump to a
cached plan with fresh parameter values patched in.

Design (and why it is safe):

  * `normalize(sql)` works on the LEXER token stream: literal tokens
    become `?`, their values become the parameter list, and the rebuilt
    template text is the cache key.  Structural literal positions the
    parser demands a literal token for (LIMIT/OFFSET counts, INTERVAL
    counts, AS OF TIMESTAMP/SNAPSHOT, DATE '...' literals, type args
    like decimal(10,2), LIKE patterns) are skipped; if a position is
    missed anyway, parsing the template FAILS and the statement is
    recorded non-cacheable — never silently mis-planned.
  * the cached artifact is the bound+optimized plan where every literal
    that came from a parameter carries a `_param_idx` tag (threaded
    through `_substitute_params` -> `_bind_literal`).  On a hit the plan
    is deep-copied and each tagged literal re-derives its value through
    the SAME bind transform (`_bind_literal(_param_literal(v))`); a
    dtype change (e.g. a float parameter with a different decimal
    scale) rejects the hit instead of patching a wrong-typed value.
  * storing VERIFIES the tags: every parameter index must surface in
    the plan as a tagged literal.  Any bind-time transform that folds,
    coerces or absorbs a parameter (constant folding, IN-list value
    extraction, vector index rewrites baking the query vector into a
    VectorTopK node) loses the tag, fails verification, and marks the
    template non-cacheable — correctness degrades to the normal path,
    never to a stale constant.
  * keys carry (tenant scope, template, parameter type signature, cbo
    flag) and entries pin (ddl_gen, stats_gen): any DDL or ANALYZE
    orphans the plan.

`MO_PLAN_CACHE=0` disables; `MO_PLAN_CACHE_SIZE` bounds entries (LRU).
"""

from __future__ import annotations

import copy
import dataclasses
import threading

from matrixone_tpu.utils import san
from collections import OrderedDict
from typing import List, Optional, Tuple

from matrixone_tpu.sql.lexer import LexError, Token, tokenize

#: template noted once but not yet activated (see template_ast)
_SEEN = object()

#: type names whose parenthesized args are structural (decimal(10,2));
#: mirrors binder._TYPE_NAMES keys that take args
_TYPE_ARG_NAMES = {"decimal", "numeric", "char", "varchar", "vecf32",
                   "vecf64"}

#: keyword contexts whose FOLLOWING literal must stay literal: the
#: parser consumes a literal token there (no expression allowed)
_SKIP_AFTER_KW = {"limit", "offset", "interval", "snapshot", "date",
                  "timestamp", "like", "lists", "op_type", "using"}

#: function calls whose result depends on time/session/randomness —
#: results (and bind-time-folded plans) must never be cached
NONDET_FUNCS = frozenset({
    "now", "current_timestamp", "sysdate", "localtimestamp",
    "utc_timestamp", "curdate", "current_date", "utc_date", "curtime",
    "current_time", "rand", "uuid", "connection_id", "last_insert_id",
    "user", "current_user", "session_user", "system_user", "database",
    "schema", "mo_ctl", "llm_chat", "llm_embed", "load_file",
    "match_against", "sample",
})


@dataclasses.dataclass
class Normalized:
    """One statement reduced to its shape.  `slots` records, per `?` in
    the template, whether the value comes from the client's parameter
    list (a pre-existing `?` — prepared statements) or was extracted
    from a literal; `full_params` merges both in template order."""
    template: str                 # literal-free SQL text (cache key)
    slots: list                   # ("c",) client | ("x", value) extracted
    nondet: bool                  # references a non-deterministic func
    n_stmts: int = 1

    def full_params(self, client: Optional[list]) -> list:
        client = list(client or [])
        out, ci = [], 0
        for s in self.slots:
            if s[0] == "c":
                out.append(client[ci])     # IndexError -> caller bails
                ci += 1
            else:
                out.append(s[1])
        if ci != len(client):
            raise ValueError("parameter arity mismatch")
        return out

    def sig_for(self, full: list) -> Tuple[str, ...]:
        return tuple(_param_sig(p) for p in full)


def _param_sig(v) -> str:
    """Type signature of one parameter value — floats carry the decimal
    scale `repr` would bind to, so 0.5 and 0.05 key different plans
    (their bound dtypes differ: decimal64(18,1) vs (18,2))."""
    if v is None:
        return "n"
    if isinstance(v, bool):
        return "b"
    if isinstance(v, int):
        return "i"
    if isinstance(v, float):
        text = repr(v)
        if "e" not in text.lower() and "." in text:
            frac = text.split(".", 1)[1]
            if len(frac) <= 8:
                return f"d{len(frac)}"
        return "f"
    if isinstance(v, str):
        return "s"
    return type(v).__name__


def _render(tokens: List[Token]) -> str:
    """Tokens back to canonical SQL text (keywords lowercased, comments
    and whitespace gone — raises the hit rate across formatting)."""
    out = []
    for t in tokens:
        if t.kind == "eof":
            break
        if t.kind == "str":
            out.append("'" + t.value.replace("\\", "\\\\")
                       .replace("'", "''") + "'")
        elif t.kind == "ident":
            out.append(f"`{t.value}`")
        elif t.kind == "sysvar":
            out.append(f"@@{t.value}")
        else:
            out.append(t.value)
    return " ".join(out)


def normalize(sql: str,
              extra_nondet: frozenset = frozenset()
              ) -> Optional[Normalized]:
    """Parameterize one statement's literals. Returns None when the text
    cannot be normalized (lex error) — callers fall back to raw SQL.
    `extra_nondet` adds dynamically-registered nondeterministic function
    names (UDFs) to the static NONDET_FUNCS set."""
    try:
        tokens = tokenize(sql)
    except LexError:
        return None
    n_stmts = 1 + sum(1 for i, t in enumerate(tokens)
                      if t.kind == "op" and t.value == ";"
                      and tokens[i + 1].kind != "eof")
    out: List[Token] = []
    slots: list = []
    nondet = False
    type_depth = 0          # >0: inside decimal(...)-style type args
    skip_next_literal = False
    for i, t in enumerate(tokens):
        nxt = tokens[i + 1] if i + 1 < len(tokens) else None
        if t.kind == "ident" and nxt is not None \
                and nxt.kind == "op" and nxt.value == "(":
            low = t.value.lower()
            if low in NONDET_FUNCS or low in extra_nondet:
                nondet = True
            if low in _TYPE_ARG_NAMES:
                type_depth += 1     # consume literals until the ")"
        if t.kind == "kw":
            if t.value in NONDET_FUNCS and nxt is not None \
                    and nxt.kind == "op" and nxt.value == "(":
                nondet = True
            if t.value in _SKIP_AFTER_KW:
                skip_next_literal = True
                out.append(t)
                continue
        if type_depth and t.kind == "op" and t.value == ")":
            type_depth -= 1
        if t.kind == "op" and t.value == "?":
            slots.append(("c",))        # client-supplied parameter
            out.append(t)
            continue
        if t.kind == "op" and t.value == "=" and skip_next_literal:
            out.append(t)               # `lists = 2`: the skip context
            continue                    # survives the option's "="
        if t.kind in ("int", "float", "str"):
            if skip_next_literal or type_depth:
                out.append(t)
                skip_next_literal = False
                continue
            if t.kind != "str" and out and out[-1].kind == "op" \
                    and out[-1].value in ("-", "+") \
                    and not (len(out) > 1 and (
                        out[-2].kind in ("ident", "int", "float", "str",
                                         "sysvar")
                        or (out[-2].kind == "op"
                            and out[-2].value in (")", "?"))
                        or (out[-2].kind == "kw" and out[-2].value in
                            ("null", "true", "false", "end")))):
                # unary sign: the parser folds `-1` into one literal;
                # `- ?` would bind as neg() and break literal-only
                # positions (lag/lead defaults, sample counts) — keep
                # signed literals literal
                out.append(t)
                continue
            if t.kind == "float":
                # parameterize only text that round-trips through the
                # param path (repr): "0.050" / "1e3" would re-bind at a
                # different decimal scale or dtype than the raw parse —
                # those stay literal in the template
                try:
                    ok = repr(float(t.value)) == t.value
                except ValueError:
                    ok = False
                if not ok:
                    out.append(t)
                    continue
                slots.append(("x", float(t.value)))
            elif t.kind == "int":
                slots.append(("x", int(t.value)))
            else:
                slots.append(("x", t.value))
            out.append(Token("op", "?", t.pos))
            continue
        skip_next_literal = False
        out.append(t)
    return Normalized(template=_render(out), slots=slots,
                      nondet=nondet, n_stmts=n_stmts)


# --------------------------------------------------------------- plans

def iter_plan_values(node, _seen=None):
    """Every dataclass/list/tuple-reachable object in a plan tree —
    generic so new node kinds are covered by construction."""
    if _seen is None:
        _seen = set()
    if id(node) in _seen:
        return
    _seen.add(id(node))
    yield node
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name, None)
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, (list, tuple)):
                        for y in x:
                            yield from iter_plan_values(y, _seen)
                    elif _walkable(x):
                        yield from iter_plan_values(x, _seen)
            elif _walkable(v):
                yield from iter_plan_values(v, _seen)


def _walkable(v) -> bool:
    return dataclasses.is_dataclass(v) and not isinstance(v, type)


def tagged_literals(plan) -> dict:
    """param index -> [BoundLiteral] that carry its value in the plan."""
    from matrixone_tpu.sql.expr import BoundLiteral
    found: dict = {}
    for v in iter_plan_values(plan):
        if isinstance(v, BoundLiteral):
            idx = getattr(v, "_param_idx", None)
            if idx is not None:
                found.setdefault(idx, []).append(v)
    return found


def plan_is_cacheable(plan, n_params: int) -> bool:
    """Verify the plan can be re-parameterized: every parameter index
    surfaces as a tagged literal, and no node bakes values outside the
    literal protocol (vector/fulltext rewrites copy the query constant
    into plain node fields).  Plans calling a NON-deterministic UDF take
    the same uncacheable-tombstone path (normalization already flags
    them by name; this is the backstop for bodies that turn
    nondeterministic via OR REPLACE between normalize and store)."""
    from matrixone_tpu.sql import plan as P
    from matrixone_tpu.sql.expr import BoundUdfCall
    for v in iter_plan_values(plan):
        if isinstance(v, (P.VectorTopK, P.FulltextTopK, P.Materialized)):
            return False
        if isinstance(v, BoundUdfCall) and not v.deterministic:
            return False
    if n_params == 0:
        return True
    found = tagged_literals(plan)
    return set(found) == set(range(n_params))


class _Entry:
    __slots__ = ("plan", "n_params", "ddl_gen", "stats_gen", "cacheable",
                 "tables", "tree", "tree_vars")

    def __init__(self, plan, n_params, ddl_gen, stats_gen,
                 cacheable=True, tables=()):
        self.plan = plan
        self.n_params = n_params
        self.ddl_gen = ddl_gen
        self.stats_gen = stats_gen
        self.cacheable = cacheable
        self.tables = tuple(tables)
        #: compiled operator tree of the LAST completed execution (the
        #: {"op", "plan"} pair) — popped on take, stored back after a
        #: successful run, same identity-guard discipline as the result
        #: cache: a concurrent taker finds None and rebuilds
        self.tree = None
        self.tree_vars = None


class PlanCache:
    """LRU of (scope, template, sig, cbo) -> bound+optimized plan."""

    def __init__(self, max_entries: int = 256, enabled: bool = True):
        self.max_entries = max_entries
        self.enabled = enabled
        self._lock = san.lock("PlanCache._lock", category="cache")
        san.guard(self, self._lock, name="PlanCache")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._norm_cache: "OrderedDict[str, Optional[Normalized]]" = \
            OrderedDict()
        #: dynamically-registered nondeterministic function names (UDFs)
        self.dynamic_nondet: frozenset = frozenset()
        # template text -> parsed AST; _SEEN: noted once, not yet
        # activated; False: template does not parse (a literal landed in
        # a structural position) — raw path serves it
        self._ast_cache: "OrderedDict[str, object]" = OrderedDict()

    def template_ast(self, template: str):
        """Second-occurrence activation: the FIRST sight of a template
        only notes it (returns None -> the raw parse path runs with zero
        added cost); a repeat parses and caches the template AST.  The
        suite-shaped workload (thousands of one-shot statements) thus
        never pays template machinery; serving workloads (repeats)
        activate on the second execution and hit from the third."""
        with self._lock:
            hit = self._ast_cache.get(template, None)
            if hit is None:
                self._ast_cache[template] = _SEEN
                while len(self._ast_cache) > 1024:
                    self._ast_cache.popitem(last=False)
                return None
            self._ast_cache.move_to_end(template)
            if hit not in (_SEEN, False):
                return hit
            if hit is False:
                return None
        from matrixone_tpu.sql.parser import parse
        try:
            stmts = parse(template)
            node = stmts[0] if len(stmts) == 1 else False
        except Exception:        # noqa: BLE001 — any parse/lex failure
            node = False         # means "serve via the raw SQL text"
        with self._lock:
            self._ast_cache[template] = node
            while len(self._ast_cache) > 1024:
                self._ast_cache.popitem(last=False)
        return node if node is not False else None

    def set_dynamic_nondet(self, names: frozenset) -> None:
        """Swap the dynamic nondet set (CREATE/DROP FUNCTION with
        'deterministic'='false'); cached Normalized entries carry stale
        nondet flags, so the normalization cache resets with it."""
        with self._lock:
            if self.dynamic_nondet == names:
                return
            self.dynamic_nondet = names
            self._norm_cache.clear()

    # ------------------------------------------------------- normalize
    def normalized(self, sql: str) -> Optional[Normalized]:
        """normalize() with a small raw-text LRU in front: the common
        serving workload repeats byte-identical statements."""
        _MISS = object()
        with self._lock:
            hit = self._norm_cache.get(sql, _MISS)
            if hit is not _MISS:
                self._norm_cache.move_to_end(sql)
                return hit
        norm = normalize(sql, self.dynamic_nondet)
        with self._lock:
            self._norm_cache[sql] = norm
            while len(self._norm_cache) > 512:
                self._norm_cache.popitem(last=False)
        return norm

    # ----------------------------------------------------------- cache
    def lookup(self, key: tuple, ddl_gen: int, stats_gen: int,
               params: list):
        """-> ("hit", plan) | ("uncacheable", None) | ("miss", None).
        A hit returns a fresh deep copy with parameter values patched."""
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
        if e is None:
            M.plan_cache_ops.inc(outcome="miss")
            return "miss", None
        if e.ddl_gen != ddl_gen or e.stats_gen != stats_gen:
            # gen check runs first so uncacheable tombstones expire too:
            # the DDL that made a template uncacheable (e.g. a vector
            # index) may have been reverted since.  Pop only OUR stale
            # entry — a concurrent store() may have already replaced it
            # with a fresh current-gen plan
            with self._lock:
                if self._entries.get(key) is e:
                    san.mutating(self)
                    self._entries.pop(key)
            M.plan_cache_ops.inc(outcome="invalidated")
            return "miss", None
        if not e.cacheable:
            M.plan_cache_ops.inc(outcome="uncacheable")
            return "uncacheable", None
        plan = self._instantiate(e, params)
        if plan is None:
            M.plan_cache_ops.inc(outcome="miss")
            return "miss", None
        M.plan_cache_ops.inc(outcome="hit")
        return "hit", plan

    @staticmethod
    def _instantiate(e: _Entry, params: list):
        from matrixone_tpu.frontend.session import _param_literal
        from matrixone_tpu.sql import ast
        from matrixone_tpu.sql.binder import _bind_literal
        plan = copy.deepcopy(e.plan)
        if e.n_params == 0:
            return plan
        found = tagged_literals(plan)
        for idx in range(e.n_params):
            lits = found.get(idx)
            if not lits:
                return None
            try:
                src = _param_literal(params[idx])
                if not isinstance(src, ast.Literal):
                    return None     # date params re-bind the long way
                fresh = _bind_literal(src)
            except Exception:       # noqa: BLE001 — full re-bind instead
                return None
            for lit in lits:
                if lit.dtype != fresh.dtype:
                    return None     # type signature drift: full re-bind
                lit.value = fresh.value
        return plan

    # ---------------------------------------------- compiled op trees
    def take_tree(self, key: tuple, ddl_gen: int, stats_gen: int,
                  vars_sig) -> Optional[dict]:
        """Pop the cached compiled operator tree for this plan key.
        POP semantics (not peek): operator trees hold per-execution
        state and must never run concurrently — a second taker finds
        None and compiles its own tree.  Gen or session-variable drift
        drops the tree (the plan entry itself is invalidated by the
        ordinary lookup path)."""
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.tree is None:
                return None
            tree, e.tree = e.tree, None
            if e.ddl_gen != ddl_gen or e.stats_gen != stats_gen \
                    or e.tree_vars != vars_sig:
                return None           # stale: dropped, caller rebuilds
        M.plan_cache_ops.inc(outcome="tree_hit")
        return tree

    def put_tree(self, key: tuple, tree: dict, ddl_gen: int,
                 stats_gen: int, vars_sig) -> None:
        """Store a compiled tree back after a successful execution —
        only onto the entry it was built against (same gens); a raced
        DDL orphans the tree along with the plan."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or not e.cacheable \
                    or e.ddl_gen != ddl_gen or e.stats_gen != stats_gen:
                return
            e.tree = tree
            e.tree_vars = vars_sig

    @staticmethod
    def rebind_tree(tree: dict, params: list):
        """Patch fresh parameter values into a cached compiled tree's
        tagged literals IN PLACE (the operator tree references the same
        BoundLiteral objects as its plan).  Returns the operator root,
        or None when the tree cannot be safely re-parameterized (the
        caller rebuilds; the popped tree is discarded)."""
        from matrixone_tpu.sql import ast
        from matrixone_tpu.sql.binder import BindError, _bind_literal
        op, plan = tree["op"], tree["plan"]
        if not params:
            return op
        from matrixone_tpu.frontend.session import _param_literal
        found = tagged_literals(plan)
        if set(found) != set(range(len(params))):
            return None
        for idx, v in enumerate(params):
            try:
                src = _param_literal(v)
                if not isinstance(src, ast.Literal):
                    return None       # date params re-bind the long way
                fresh = _bind_literal(src)
            except BindError:
                return None
            for lit in found[idx]:
                if lit.dtype != fresh.dtype:
                    return None       # dtype drift: full rebuild
                lit.value = fresh.value
        return op

    def store(self, key: tuple, plan, n_params: int, ddl_gen: int,
              stats_gen: int, tables=()) -> None:
        from matrixone_tpu.utils import metrics as M
        if plan is not None and not plan_is_cacheable(plan, n_params):
            self.mark_uncacheable(key, ddl_gen, stats_gen)
            return
        entry = _Entry(copy.deepcopy(plan), n_params, ddl_gen,
                       stats_gen, tables=tables)
        with self._lock:
            san.mutating(self)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            M.plan_cache_entries.set(len(self._entries))

    def mark_uncacheable(self, key: tuple, ddl_gen: int = 0,
                         stats_gen: int = 0) -> None:
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            san.mutating(self)
            self._entries[key] = _Entry(None, 0, ddl_gen, stats_gen,
                                        cacheable=False)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            M.plan_cache_entries.set(len(self._entries))

    def clear(self) -> None:
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            san.mutating(self)
            self._entries.clear()
            self._norm_cache.clear()
            self._ast_cache.clear()
            M.plan_cache_entries.set(0)

    def stats(self) -> dict:
        from matrixone_tpu.utils import metrics as M
        hits = M.plan_cache_ops.get(outcome="hit")
        misses = M.plan_cache_ops.get(outcome="miss")
        with self._lock:
            n = len(self._entries)
        return {"entries": n, "hits": int(hits), "misses": int(misses),
                "uncacheable": int(
                    M.plan_cache_ops.get(outcome="uncacheable")),
                "invalidated": int(
                    M.plan_cache_ops.get(outcome="invalidated")),
                "hit_rate": (hits / (hits + misses)
                             if hits + misses else 0.0),
                "enabled": self.enabled}

"""Snapshot-consistent result cache.

Reference analogue: MatrixOne's proxy/queryservice tier caches nothing —
this is the piece a serving deployment adds in front of it. Correctness
falls out of MVCC, not TTLs:

  * an entry is keyed on (tenant scope, statement template, parameter
    values) and pins the PER-TABLE VERSION of every table the plan
    scanned: `(last_commit_ts, n_segments, n_tombstone_batches)` plus
    the engine's ddl_gen.  Any commit touching a referenced table bumps
    its `last_commit_ts` (storage/engine.py apply_segment /
    apply_tombstones — the single funnel shared by direct commits, WAL
    replay and the CN logtail), so the entry silently orphans: the next
    lookup sees a version mismatch, drops it, and re-executes against
    the fresh frontier.
  * `AS OF SNAPSHOT/TIMESTAMP` scans read an immutable past — their
    version component is the constant as-of timestamp, so those entries
    live until evicted (ddl_gen still guards snapshot-name remapping).
  * versions are captured BEFORE the execution snapshot is frozen: a
    commit racing the execution can only make the stored versions
    OLDER than the result, never newer — a stale entry can be
    under-cached (harmless re-execution), never served.

Bypass (the caller enforces, see frontend/session.py): statements with
non-deterministic functions (now/rand/uuid/current_user/...), external
tables, in-transaction reads (the txn workspace is invisible to the
frontier key), and multi-statement texts.

`MO_RESULT_CACHE_MB` bounds the cache in bytes (LRU; 0 = disabled,
which is the default — enable per deployment or via
`mo_ctl('serving','result:on')`).  `MO_RESULT_CACHE=0` force-disables.
"""

from __future__ import annotations

import threading

from matrixone_tpu.utils import san
from collections import OrderedDict
from typing import Optional


def batch_nbytes(batch) -> int:
    """Approximate host footprint of a result Batch (column arrays +
    dictionary strings)."""
    total = 0
    for name in batch.columns:
        v = batch.columns[name]
        data = getattr(v, "data", None)
        total += int(getattr(data, "nbytes", 64))
        for s in getattr(v, "dict", None) or []:
            total += len(s) if isinstance(s, str) else 8
    return total + 256


class _Entry:
    __slots__ = ("batch", "versions", "nbytes")

    def __init__(self, batch, versions, nbytes):
        self.batch = batch
        self.versions = versions
        self.nbytes = nbytes


class ResultCache:
    """LRU over result batches, bounded by bytes."""

    def __init__(self, max_bytes: int = 0):
        self.max_bytes = max_bytes
        self._lock = san.lock("ResultCache._lock", category="cache")
        san.guard(self, self._lock, name="ResultCache")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def get(self, key: tuple, current_versions) -> Optional[tuple]:
        """current_versions: stored_versions -> versions tuple recomputed
        by the caller against the live catalog.  Returns (batch,
        stored_versions) — the versions carry the scanned table names so
        the caller can re-run privilege checks — or None."""
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
        if e is None:
            M.result_cache_ops.inc(outcome="miss")
            return None
        now = current_versions(e.versions)
        if now != e.versions:
            with self._lock:
                # evict only if OUR stale entry is still resident — a
                # concurrent put() may have replaced it with a fresh one
                # while we recomputed versions outside the lock, and
                # popping that would both drop a live result and subtract
                # the wrong nbytes from the budget
                if self._entries.get(key) is e:
                    san.mutating(self)
                    self._entries.pop(key)
                    self._bytes -= e.nbytes
                M.result_cache_entries.set(len(self._entries))
                M.result_cache_bytes.set(self._bytes)
            M.result_cache_ops.inc(outcome="stale")
            return None
        M.result_cache_ops.inc(outcome="hit")
        return e.batch, e.versions

    def put(self, key: tuple, batch, versions) -> None:
        from matrixone_tpu.utils import metrics as M
        nb = batch_nbytes(batch)
        if nb > self.max_bytes // 4 or nb > self.max_bytes:
            return                      # one giant result must not wipe
        with self._lock:                # the whole working set
            san.mutating(self)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(batch, versions, nb)
            self._bytes += nb
            while self._bytes > self.max_bytes and self._entries:
                _, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                M.result_cache_evictions.inc()
            M.result_cache_entries.set(len(self._entries))
            M.result_cache_bytes.set(self._bytes)

    def set_max_bytes(self, nb: int) -> None:
        """Resize the budget; shrinking evicts immediately (a read-hot
        workload may never call put(), so the put()-side loop alone
        would hold the old budget's memory indefinitely)."""
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            san.mutating(self)
            self.max_bytes = nb
            while self._bytes > self.max_bytes and self._entries:
                _, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                M.result_cache_evictions.inc()
            M.result_cache_entries.set(len(self._entries))
            M.result_cache_bytes.set(self._bytes)

    def clear(self) -> None:
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            san.mutating(self)
            self._entries.clear()
            self._bytes = 0
            M.result_cache_entries.set(0)
            M.result_cache_bytes.set(0)

    def stats(self) -> dict:
        from matrixone_tpu.utils import metrics as M
        hits = M.result_cache_ops.get(outcome="hit")
        misses = (M.result_cache_ops.get(outcome="miss")
                  + M.result_cache_ops.get(outcome="stale"))
        with self._lock:
            n, b = len(self._entries), self._bytes
        return {"entries": n, "bytes": b, "max_bytes": self.max_bytes,
                "hits": int(hits), "misses": int(misses),
                "stale": int(M.result_cache_ops.get(outcome="stale")),
                "evictions": int(M.result_cache_evictions.get()),
                "hit_rate": (hits / (hits + misses)
                             if hits + misses else 0.0),
                "enabled": self.enabled}

from matrixone_tpu.sql import ast, binder, expr, lexer, parser, plan

__all__ = ["ast", "binder", "expr", "lexer", "parser", "plan"]

"""SQL AST (reference: pkg/sql/parsers/tree — redesigned, minimal dataclasses).

The reference generates its parser from a 15k-line goyacc grammar
(`parsers/dialect/mysql/mysql_sql.y`); this project uses a hand-written
recursive-descent parser over a small AST — the grammar subset grows with
the engine instead of importing MySQL's full surface up front.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# ----------------------------------------------------------------- exprs

@dataclasses.dataclass
class Literal(Node):
    value: object            # int | float | str | bool | None
    kind: str                # 'int' | 'float' | 'str' | 'bool' | 'null'


@dataclasses.dataclass
class DateLiteral(Node):
    days: int                # days since unix epoch


@dataclasses.dataclass
class IntervalLiteral(Node):
    value: int
    unit: str                # 'day' | 'month' | 'year'


@dataclasses.dataclass
class ColumnRef(Node):
    name: str
    table: Optional[str] = None


@dataclasses.dataclass
class BinaryOp(Node):
    op: str                  # + - * / % and or = != < <= > >= like
    left: Node
    right: Node


@dataclasses.dataclass
class UnaryOp(Node):
    op: str                  # - not
    operand: Node


@dataclasses.dataclass
class WindowSpec(Node):
    partition_by: List[Node] = dataclasses.field(default_factory=list)
    order_by: List["OrderItem"] = dataclasses.field(default_factory=list)
    #: explicit frame: ("rows", lo, hi) with bounds
    #: ("unbounded_preceding"|"unbounded_following"|"current"|
    #:  "preceding"|"following", k_or_None); None = SQL default frame
    frame: Optional[tuple] = None


@dataclasses.dataclass
class FuncCall(Node):
    name: str
    args: List[Node]
    distinct: bool = False
    star: bool = False       # count(*)
    window: Optional[WindowSpec] = None   # fn(...) OVER (...)


@dataclasses.dataclass
class Cast(Node):
    expr: Node
    type_name: str
    type_args: Tuple[int, ...] = ()


@dataclasses.dataclass
class Case(Node):
    whens: List[Tuple[Node, Node]]
    else_: Optional[Node]


@dataclasses.dataclass
class InList(Node):
    expr: Node
    items: List[Node]
    negated: bool = False


@dataclasses.dataclass
class Between(Node):
    expr: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass
class IsNull(Node):
    expr: Node
    negated: bool = False


@dataclasses.dataclass
class Star(Node):
    table: Optional[str] = None


@dataclasses.dataclass
class Subquery(Node):
    select: "Select"


@dataclasses.dataclass
class Exists(Node):
    select: "Select"
    negated: bool = False


@dataclasses.dataclass
class Param(Node):
    index: int               # ? placeholders for prepared statements


@dataclasses.dataclass
class SysVar(Node):
    name: str                # @@name (session scope)


@dataclasses.dataclass
class ShowVariables(Node):
    like: Optional[str] = None


# ------------------------------------------------------------ statements

@dataclasses.dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclasses.dataclass
class TableRef(Node):
    name: str
    alias: Optional[str] = None
    snapshot: Optional[str] = None       # AS OF SNAPSHOT 'name'
    as_of_ts: Optional[int] = None       # AS OF TIMESTAMP <hlc>


@dataclasses.dataclass
class SubqueryRef(Node):
    select: "Select"
    alias: str


@dataclasses.dataclass
class Join(Node):
    kind: str                # 'inner' | 'left' | 'right' | 'full' | 'cross'
    left: Node
    right: Node
    on: Optional[Node] = None


@dataclasses.dataclass
class SampleRef(Node):
    """FROM t SAMPLE n ROWS | SAMPLE p PERCENT (colexec/sample analogue)."""
    child: Node
    value: float
    unit: str                # 'rows' | 'percent'


@dataclasses.dataclass
class OrderItem(Node):
    expr: Node
    descending: bool = False


@dataclasses.dataclass
class Select(Node):
    items: List[SelectItem]
    from_: Optional[Node] = None
    where: Optional[Node] = None
    group_by: List[Node] = dataclasses.field(default_factory=list)
    having: Optional[Node] = None
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    ctes: List[Tuple[str, "Select"]] = dataclasses.field(
        default_factory=list)          # WITH name AS (select ...)
    semijoins: List["SemiJoinSpec"] = dataclasses.field(
        default_factory=list)          # decorrelated EXISTS predicates
    # GROUP BY ... FILL(PREV | LINEAR | VALUE, x): (mode, const_or_None)
    fill: Optional[Tuple[str, Optional[float]]] = None


@dataclasses.dataclass
class SemiJoinSpec(Node):
    """A decorrelated [NOT] EXISTS: semi/anti-join the outer plan against
    `select` on outer_keys[i] = the i-th projected column of `select`;
    `residual` (if any) references outer columns + projected residual
    columns of `select` and must hold for a pair to count as a match."""
    select: "Select"
    outer_keys: List[Node]
    n_keys: int
    residual: Optional[Node]
    negated: bool
    alias: str               # unique tag; projected cols are {alias}_k{i}


@dataclasses.dataclass
class Union(Node):
    selects: List["Select"]
    alls: List[bool]         # alls[i]: UNION ALL between selects[i], [i+1]
    order_by: List["OrderItem"] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclasses.dataclass
class ColumnDef(Node):
    name: str
    type_name: str
    type_args: Tuple[int, ...] = ()
    not_null: bool = False
    primary_key: bool = False
    default: Optional[Node] = None
    auto_increment: bool = False


@dataclasses.dataclass
class CreateTable(Node):
    name: str
    columns: List[ColumnDef]
    primary_key: List[str] = dataclasses.field(default_factory=list)
    if_not_exists: bool = False
    # raw PARTITION BY parse: {"kind":"hash","column",...,"n"} |
    # {"kind":"range","column",...,"parts":[(name, bound|None), ...]}
    partition_by: Optional[dict] = None


@dataclasses.dataclass
class DropTable(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass
class CreateIndex(Node):
    name: str
    table: str
    columns: List[str]
    using: Optional[str] = None          # 'ivfflat' | 'hnsw' | 'fulltext' ...
    options: dict = dataclasses.field(default_factory=dict)  # lists=..., op_type=...


@dataclasses.dataclass
class Insert(Node):
    table: str
    columns: List[str]
    rows: Optional[List[List[Node]]] = None   # VALUES
    select: Optional[Select] = None           # INSERT ... SELECT


@dataclasses.dataclass
class Delete(Node):
    table: str
    where: Optional[Node] = None


@dataclasses.dataclass
class Update(Node):
    table: str
    assignments: List[Tuple[str, Node]]
    where: Optional[Node] = None


@dataclasses.dataclass
class Explain(Node):
    stmt: Node
    analyze: bool = False


@dataclasses.dataclass
class ShowTables(Node):
    pass


@dataclasses.dataclass
class CreateSnapshot(Node):
    name: str


@dataclasses.dataclass
class DropSnapshot(Node):
    name: str


@dataclasses.dataclass
class ShowSnapshots(Node):
    pass


@dataclasses.dataclass
class ShowTrace(Node):
    """SHOW TRACE — recent motrace span trees (utils/motrace.py)."""
    pass


@dataclasses.dataclass
class ShowAccounts(Node):
    pass


@dataclasses.dataclass
class RestoreTable(Node):
    table: str
    snapshot: str


@dataclasses.dataclass
class ShowCreateTable(Node):
    name: str


@dataclasses.dataclass
class ShowColumns(Node):
    name: str


@dataclasses.dataclass
class ShowIndexes(Node):
    name: str


@dataclasses.dataclass
class AnalyzeTable(Node):
    name: str


@dataclasses.dataclass
class AlterPartition(Node):
    table: str
    action: str              # 'truncate' | 'drop'
    part: str


@dataclasses.dataclass
class ShowPartitions(Node):
    name: str


@dataclasses.dataclass
class ShowProcesslist(Node):
    pass


@dataclasses.dataclass
class Kill(Node):
    conn_id: int
    query_only: bool = False     # KILL QUERY id vs KILL id (connection)


@dataclasses.dataclass
class LoadData(Node):
    path: str                    # local path | file:// | fs:// | stage://
    table: str
    fmt: str                     # 'csv' | 'parquet' (from suffix if '')


@dataclasses.dataclass
class CreateStage(Node):
    name: str
    url: str


@dataclasses.dataclass
class DropStage(Node):
    name: str


@dataclasses.dataclass
class ShowStages(Node):
    pass


@dataclasses.dataclass
class CreateExternalTable(Node):
    name: str
    columns: List["ColumnDef"]
    location: str
    fmt: str
    snapshot: Optional[int] = None   # iceberg time travel


@dataclasses.dataclass
class CreatePublication(Node):
    name: str
    tables: List[str]


@dataclasses.dataclass
class DropPublication(Node):
    name: str


@dataclasses.dataclass
class ShowPublications(Node):
    pass


@dataclasses.dataclass
class CreateSource(Node):
    name: str
    columns: List["ColumnDef"]


@dataclasses.dataclass
class CreateDynamicTable(Node):
    name: str
    select: Node
    sql_text: str            # the defining SELECT, verbatim (re-run on
                             # every refresh)


@dataclasses.dataclass
class RefreshDynamicTable(Node):
    name: str


@dataclasses.dataclass
class CreateMaterializedView(Node):
    """CREATE MATERIALIZED VIEW name AS SELECT ... — persisted in the
    system_mview catalog; maintainable shapes update incrementally from
    commit deltas, the rest full-refresh (matrixone_tpu/mview)."""
    name: str
    select: Node
    sql_text: str            # the defining SELECT, verbatim


@dataclasses.dataclass
class DropMaterializedView(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass
class ShowMaterializedViews(Node):
    pass


@dataclasses.dataclass
class RefreshMaterializedView(Node):
    name: str


@dataclasses.dataclass
class CreateFunction(Node):
    """CREATE [OR REPLACE] [AGGREGATE] FUNCTION f(x FLOAT, ...)
    RETURNS FLOAT LANGUAGE PYTHON [PROPERTIES ('k'='v', ...)]
    AS $$ body $$ (reference: mo_user_defined_function DDL)."""
    name: str
    args: List[Tuple[str, str, Tuple[int, ...]]]  # (name, type, targs)
    ret_type: str
    ret_args: Tuple[int, ...]
    language: str
    body: str
    properties: dict = dataclasses.field(default_factory=dict)
    or_replace: bool = False
    aggregate: bool = False


@dataclasses.dataclass
class DropFunction(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass
class ShowFunctions(Node):
    pass


@dataclasses.dataclass
class SetVariable(Node):
    name: str
    value: Node


# ---- accounts / users / roles / privileges (frontend/authenticate.go)
@dataclasses.dataclass
class CreateAccount(Node):
    name: str
    admin_user: str
    admin_password: str
    if_not_exists: bool = False


@dataclasses.dataclass
class DropAccount(Node):
    name: str


@dataclasses.dataclass
class CreateUser(Node):
    name: str
    password: str
    if_not_exists: bool = False


@dataclasses.dataclass
class DropUser(Node):
    name: str


@dataclasses.dataclass
class CreateRole(Node):
    name: str


@dataclasses.dataclass
class DropRole(Node):
    name: str


@dataclasses.dataclass
class GrantPriv(Node):
    privs: list          # ["select", ...]
    obj: str             # table name or "*"
    role: str


@dataclasses.dataclass
class RevokePriv(Node):
    privs: list
    obj: str
    role: str


@dataclasses.dataclass
class GrantRole(Node):
    role: str
    user: str


@dataclasses.dataclass
class RevokeRole(Node):
    role: str
    user: str


@dataclasses.dataclass
class ShowGrants(Node):
    user: "str | None" = None


@dataclasses.dataclass
class BeginTxn(Node):
    pass


@dataclasses.dataclass
class CommitTxn(Node):
    pass


@dataclasses.dataclass
class RollbackTxn(Node):
    pass

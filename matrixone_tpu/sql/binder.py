"""Binder: AST -> typed logical plan.

Reference analogue: `pkg/sql/plan/query_builder.go:3555 bindSelect` +
`build.go:378 BuildPlan`, compressed to the passes that matter for a
vectorized TPU pipeline:

  bind FROM/joins -> WHERE -> two-phase aggregate binding -> HAVING ->
  projection -> DISTINCT -> ORDER BY (alias/ordinal/hidden-column) ->
  LIMIT; then: filter pushdown into Scan, ORDER BY+LIMIT -> TopK fusion,
  vector-index rewrite (apply_indices_ivfflat.go analogue, done in
  compile when an index exists).

Literal typing is MySQL-flavored: `0.05` is DECIMAL(_,2), not float, so
decimal comparisons and arithmetic stay in the exact int64 domain.
"""

from __future__ import annotations

import datetime
import itertools
from typing import Dict, List, Optional, Tuple

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.sql import ast, plan
from matrixone_tpu.sql.expr import (AggCall, BoundCase, BoundCast, BoundCol,
                                    BoundExpr, BoundFunc, BoundInList,
                                    BoundIsNull, BoundLike, BoundLiteral,
                                    and_all)

from matrixone_tpu.sql.parser import (AGG_FUNCS, BASIC_AGGS, BIT_AGGS,
                                      STDDEV_AGGS)

# SAMPLE seeds: each bound Sample node (and each re-bind of the same
# query) draws an independent random stream
_sample_seed = itertools.count(1)
WINDOW_ONLY_FUNCS = {"row_number", "rank", "dense_rank", "ntile",
                     "lag", "lead", "first_value", "last_value",
                     "nth_value"}
#: rank family: no arguments, ignores frames
_RANK_FUNCS = {"row_number", "rank", "dense_rank"}
#: value functions: first arg is the value expression
_VALUE_FUNCS = {"lag", "lead", "first_value", "last_value", "nth_value"}

_TYPE_NAMES = {
    "bool": lambda a: dt.BOOL, "boolean": lambda a: dt.BOOL,
    "tinyint": lambda a: dt.INT8, "smallint": lambda a: dt.INT16,
    "int": lambda a: dt.INT32, "integer": lambda a: dt.INT32,
    "bigint": lambda a: dt.INT64,
    "float": lambda a: dt.FLOAT32, "double": lambda a: dt.FLOAT64,
    "real": lambda a: dt.FLOAT64,
    "decimal": lambda a: dt.decimal64(*(a or (18, 2))),
    "numeric": lambda a: dt.decimal64(*(a or (18, 2))),
    "date": lambda a: dt.DATE, "datetime": lambda a: dt.DATETIME,
    "timestamp": lambda a: dt.TIMESTAMP,
    "char": lambda a: dt.DType(dt.TypeOid.CHAR, width=(a[0] if a else 1)),
    "varchar": lambda a: dt.varchar(a[0] if a else 65535),
    "text": lambda a: dt.TEXT,
    "vecf32": lambda a: dt.vecf32(a[0] if a else 0),
    "vecf64": lambda a: dt.vecf64(a[0] if a else 0),
}


class BindError(ValueError):
    pass


def type_from_name(name: str, args: Tuple[int, ...]) -> DType:
    try:
        return _TYPE_NAMES[name](args)
    except KeyError:
        raise BindError(f"unknown type {name!r}")


class Scope:
    """Name resolution scope: (table_alias, column, dtype) entries."""

    def __init__(self):
        self.entries: List[Tuple[Optional[str], str, DType]] = []

    def add(self, table: Optional[str], col: str, dtype: DType):
        self.entries.append((table, col, dtype))

    def resolve(self, name: str, table: Optional[str]) -> Tuple[str, DType]:
        hits = [(t, c, d) for (t, c, d) in self.entries
                if c == name and (table is None or t == table)]
        if not hits:
            raise BindError(f"unknown column {table + '.' if table else ''}{name}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column {name}")
        t, c, d = hits[0]
        return (f"{t}.{c}" if t else c), d

    def qualified_names(self) -> List[str]:
        # output column key used in DeviceBatch dicts
        return [f"{t}.{c}" if t else c for (t, c, _) in self.entries]


class Binder:
    def __init__(self, catalog):
        self.catalog = catalog
        #: name -> (definition_index, body) — index enforces that a CTE
        #: body only sees EARLIER ctes (no forward refs, standard WITH)
        self._ctes: Dict[str, tuple] = {}

    def bind_statement(self, stmt) -> plan.PlanNode:
        if isinstance(stmt, ast.Union):
            return self.bind_union(stmt)
        return self.bind_select(stmt)

    def bind_union(self, u: ast.Union) -> plan.PlanNode:
        children = [self.bind_select(s) for s in u.selects]
        base = children[0].schema
        for c in children[1:]:
            if len(c.schema) != len(base):
                raise BindError("UNION arms have different column counts")
        # output types: promote numerics column-wise
        out_schema = []
        for i, (name, d0) in enumerate(base):
            out_t = d0
            for c in children[1:]:
                d1 = c.schema[i][1]
                if d1.oid != out_t.oid:
                    if d1.is_numeric and out_t.is_numeric:
                        out_t = dt.promote(out_t, d1)
                    elif d1.is_varlen and out_t.is_varlen:
                        pass
                    else:
                        raise BindError(
                            f"UNION column {name}: incompatible types "
                            f"{out_t} vs {d1}")
            out_schema.append((name, out_t))
        # MySQL semantics: a plain UNION dedups everything up to and
        # including its position; UNION ALL arms AFTER the last plain
        # UNION append duplicates
        last_distinct = -1
        for i, is_all in enumerate(u.alls):
            if not is_all:
                last_distinct = i
        if last_distinct >= 0:
            head = children[:last_distinct + 2]
            node = plan.Distinct(plan.Union(head, out_schema), out_schema)
            tail = children[last_distinct + 2:]
            if tail:
                node = plan.Union([node] + tail, out_schema)
        else:
            node = plan.Union(children, out_schema)
        if u.order_by:
            keys, descs = [], []
            names = [n for n, _ in out_schema]
            for o in u.order_by:
                descs.append(o.descending)
                if isinstance(o.expr, ast.Literal) and o.expr.kind == "int":
                    idx = int(o.expr.value) - 1
                    if not 0 <= idx < len(names):
                        raise BindError("ORDER BY ordinal out of range")
                elif isinstance(o.expr, ast.ColumnRef) and \
                        o.expr.name in names:
                    idx = names.index(o.expr.name)
                else:
                    raise BindError(
                        "UNION ORDER BY supports output names/ordinals")
                keys.append(BoundCol(names[idx], out_schema[idx][1]))
            if u.limit is not None:
                node = plan.TopK(node, keys, descs, u.limit, u.offset or 0,
                                 out_schema)
            else:
                node = plan.Sort(node, keys, descs, out_schema)
        elif u.limit is not None or u.offset:
            node = plan.Limit(node, u.limit, u.offset or 0, out_schema)
        return node

    # ------------------------------------------------------------- select
    def bind_select(self, sel: ast.Select) -> plan.PlanNode:
        outer_ctes = dict(self._ctes)
        base = len(outer_ctes)
        for i, (name, sub) in enumerate(sel.ctes):
            if name in self._ctes and self._ctes[name][0] >= base:
                raise BindError(f"duplicate CTE name {name!r}")
            self._ctes[name] = (base + i, sub)
        try:
            return self._bind_select_inner(sel)
        finally:
            self._ctes = outer_ctes

    def _bind_select_inner(self, sel: ast.Select) -> plan.PlanNode:
        node, scope = self._bind_from(sel.from_)

        if sel.where is not None:
            pred = _coerce_bool(self.bind_expr(sel.where, scope))
            _require_bool(pred, "WHERE")
            node = plan.Filter(node, pred, node.schema)

        for sj in getattr(sel, "semijoins", ()):
            node = self._bind_semijoin(node, scope, sj)

        # expand stars early
        items: List[ast.SelectItem] = []
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                for (t, c, d) in scope.entries:
                    if it.expr.table is None or t == it.expr.table:
                        items.append(ast.SelectItem(
                            ast.ColumnRef(c, t), alias=c))
            else:
                items.append(it)

        if self._has_udf_agg(items):
            return self._bind_udf_aggregate(node, scope, sel, items)

        has_aggs = any(self._contains_agg(it.expr) for it in items) \
            or (sel.having is not None and self._contains_agg(sel.having)) \
            or any(self._contains_agg(o.expr) for o in sel.order_by) \
            or bool(sel.group_by)

        alias_map = {it.alias: it.expr for it in items if it.alias}

        if has_aggs:
            node, scope, agg_sub = self._bind_aggregate(
                node, scope, sel, items, alias_map)
        else:
            agg_sub = None
            if sel.having is not None:
                raise BindError("HAVING without aggregation")

        # GROUP BY ... FILL(mode): null-fill over the grouped output,
        # ordered by the first group key (reference: colexec/fill)
        if sel.fill is not None:
            agg_node = node
            while isinstance(agg_node, plan.Filter):
                agg_node = agg_node.child
            if not isinstance(agg_node, plan.Aggregate) \
                    or not agg_node.group_keys:
                raise BindError("FILL requires GROUP BY")
            nk = len(agg_node.group_keys)
            key_names = [n for n, _ in agg_node.schema[:nk]]
            mode, const = sel.fill
            node = plan.Fill(node, mode, const, key_names[0], key_names,
                             node.schema)

        # window functions: compute as hidden columns below the projection
        node, scope, win_map = self._bind_windows(node, scope, items,
                                                  agg_sub)

        # projection
        exprs, names = [], []
        for idx, it in enumerate(items):
            e = self._bind_item(it.expr, scope, agg_sub, win_map)
            exprs.append(e)
            names.append(it.alias or _expr_name(it.expr, idx))
        # batches are dict-keyed: disambiguate duplicate output labels
        seen: Dict[str, int] = {}
        taken = set(names)
        for i, n in enumerate(names):
            if n in seen:
                k = seen[n] + 1
                while f"{n}_{k}" in taken:
                    k += 1
                seen[n] = k
                names[i] = f"{n}_{k}"
                taken.add(names[i])
            else:
                seen[n] = 0
        out_schema = list(zip(names, [e.dtype for e in exprs]))
        node = plan.Project(node, exprs, out_schema)

        if sel.distinct:
            node = plan.Distinct(node, node.schema)

        # ORDER BY: resolve by ordinal, output alias, or expression
        n_visible = len(names)
        if sel.order_by:
            keys, descs = [], []
            for o in sel.order_by:
                descs.append(o.descending)
                k = self._bind_order_key(o.expr, node, names, exprs, scope,
                                         agg_sub, alias_map)
                keys.append(k)
            if sel.limit is not None:
                node = plan.TopK(node, keys, descs, sel.limit,
                                 sel.offset or 0, node.schema)
            else:
                node = plan.Sort(node, keys, descs, node.schema)
            if len(names) > n_visible:   # drop hidden sort columns
                vis = node.schema[:n_visible]
                node = plan.Project(
                    node, [BoundCol(n, d) for n, d in vis], list(vis))
        elif sel.limit is not None or sel.offset:
            node = plan.Limit(node, sel.limit, sel.offset or 0, node.schema)

        return self._pushdown_filters(node)

    # -------------------------------------------------------------- from
    def _bind_from(self, from_) -> Tuple[plan.PlanNode, Scope]:
        if from_ is None:
            # SELECT without FROM: single-row dual table
            sc = Scope()
            return plan.Values([[1]], [("__dual", dt.INT64)]), sc
        if isinstance(from_, ast.TableRef) and from_.name in self._ctes:
            if from_.snapshot is not None or from_.as_of_ts is not None:
                raise BindError(
                    f"cannot time-travel a CTE ({from_.name!r}); AS OF "
                    f"applies to stored tables")
            # CTE reference: bind the body as a derived table, visible
            # scope = strictly earlier CTEs (non-recursive, no forward refs)
            my_idx, sub = self._ctes[from_.name]
            alias = from_.alias or from_.name
            saved = self._ctes
            self._ctes = {k: v for k, v in saved.items() if v[0] < my_idx}
            try:
                return self._bind_from(ast.SubqueryRef(sub, alias))
            finally:
                self._ctes = saved
        if isinstance(from_, ast.TableRef):
            meta = self.catalog.get_table(from_.name)
            alias = from_.alias or from_.name
            sc = Scope()
            for col, dtype in meta.schema:
                sc.add(alias, col, dtype)
            as_of = from_.as_of_ts
            if from_.snapshot is not None:
                snaps = getattr(self.catalog, "snapshots", {})
                if from_.snapshot not in snaps:
                    raise BindError(f"no such snapshot {from_.snapshot!r}")
                as_of = snaps[from_.snapshot]
            scan = plan.Scan(from_.name,
                             [c for c, _ in meta.schema],
                             [(f"{alias}.{c}", d) for c, d in meta.schema],
                             as_of_ts=as_of)
            return scan, sc
        if isinstance(from_, ast.SubqueryRef):
            child = self.bind_statement(from_.select)
            sc = Scope()
            for col, dtype in child.schema:
                sc.add(from_.alias, col, dtype)
            # rename child outputs into alias namespace
            exprs = [BoundCol(c, d) for c, d in child.schema]
            schema = [(f"{from_.alias}.{c}", d) for c, d in child.schema]
            return plan.Project(child, exprs, schema), sc
        if isinstance(from_, ast.Join):
            lnode, lscope = self._bind_from(from_.left)
            rnode, rscope = self._bind_from(from_.right)
            sc = Scope()
            sc.entries = lscope.entries + rscope.entries
            schema = lnode.schema + rnode.schema
            kind = from_.kind
            if kind == "right":
                lnode, rnode = rnode, lnode
                lscope, rscope = rscope, lscope
                schema = lnode.schema + rnode.schema
                sc.entries = lscope.entries + rscope.entries
                kind = "left"
            lkeys, rkeys, residual = [], [], None
            if from_.on is not None:
                lkeys, rkeys, residual = self._split_join_on(
                    from_.on, lscope, rscope, sc)
            elif kind == "full":
                raise BindError("FULL OUTER JOIN requires an ON clause")
            elif kind != "cross":
                kind = "cross"
            if kind == "full" and not lkeys:
                raise BindError(
                    "FULL OUTER JOIN requires at least one equi-key")
            return plan.Join(kind, lnode, rnode, lkeys, rkeys, residual,
                             schema), sc
        if isinstance(from_, ast.SampleRef):
            child, sc = self._bind_from(from_.child)
            seed = next(_sample_seed)   # distinct stream per Sample node
            if from_.unit == "rows":
                node = plan.Sample(child, int(from_.value), None,
                                   child.schema, seed=seed)
            else:
                if not (0 < from_.value <= 100):
                    raise BindError("SAMPLE percent must be in (0, 100]")
                node = plan.Sample(child, None, float(from_.value),
                                   child.schema, seed=seed)
            return node, sc
        raise BindError(f"unsupported FROM clause {type(from_).__name__}")

    def _bind_semijoin(self, node, scope, sj: "ast.SemiJoinSpec"):
        """Bind a decorrelated [NOT] EXISTS as a semi/anti join: build side
        = the rewritten subquery plan (projects {alias}_k* key columns and
        {alias}_r* residual columns); probe side = the current plan."""
        subplan = self.bind_select(sj.select) if isinstance(
            sj.select, ast.Select) else self.bind_statement(sj.select)
        left_keys = [self.bind_expr(oe, scope) for oe in sj.outer_keys]
        right_keys = [BoundCol(n, d)
                      for n, d in subplan.schema[:sj.n_keys]]
        residual = None
        if sj.residual is not None:
            combined = Scope()
            combined.entries = list(scope.entries) + [
                (None, n, d) for n, d in subplan.schema]
            residual = self.bind_expr(sj.residual, combined)
            _require_bool(residual, "EXISTS residual")
        kind = "anti" if sj.negated else "semi"
        return plan.Join(kind, node, subplan, left_keys, right_keys,
                         residual, list(node.schema))

    def _split_join_on(self, on, lscope, rscope, full_scope):
        """Split ON into equi-key pairs + residual predicate."""
        conjuncts = _split_and(on)
        lkeys, rkeys, residual = [], [], []
        for c in conjuncts:
            if isinstance(c, ast.BinaryOp) and c.op == "=":
                try:
                    le = self.bind_expr(c.left, lscope)
                    re_ = self.bind_expr(c.right, rscope)
                    lkeys.append(le)
                    rkeys.append(re_)
                    continue
                except BindError:
                    pass
                try:
                    le = self.bind_expr(c.right, lscope)
                    re_ = self.bind_expr(c.left, rscope)
                    lkeys.append(le)
                    rkeys.append(re_)
                    continue
                except BindError:
                    pass
            residual.append(c)
        res = None
        if residual:
            e = residual[0]
            for r in residual[1:]:
                e = ast.BinaryOp("and", e, r)
            res = self.bind_expr(e, full_scope)
        return lkeys, rkeys, res

    # ----------------------------------------------------- aggregate UDFs
    def _udf_agg_of(self, e: ast.Node):
        from matrixone_tpu.udf import catalog as _ucat
        if not (isinstance(e, ast.FuncCall) and e.window is None):
            return None
        u = _ucat.lookup(self.catalog, e.name)
        return u if u is not None and u.kind == "aggregate" else None

    def _has_udf_agg(self, items) -> bool:
        return any(self._udf_agg_of(it.expr) is not None for it in items)

    def _bind_udf_aggregate(self, node, scope, sel, items):
        """SELECT agg_udf(expr), ... FROM t [WHERE ...] — every item must
        be an aggregate-UDF call; the whole (filtered) input reduces to
        one row.  GROUP BY with aggregate UDFs is not supported yet (the
        grouped kernels are built for the fixed aggregate algebra)."""
        if sel.group_by:
            raise BindError(
                "aggregate UDFs with GROUP BY are not supported yet")
        if sel.having is not None:
            raise BindError(
                "HAVING with aggregate UDFs is not supported yet")
        if sel.distinct:
            raise BindError(
                "DISTINCT with aggregate UDFs is not supported")
        calls, schema = [], []
        for idx, it in enumerate(items):
            u = self._udf_agg_of(it.expr)
            if u is None:
                raise BindError(
                    "a query using an aggregate UDF must select only "
                    "aggregate UDF calls")
            args = [self.bind_expr(a, scope) for a in it.expr.args]
            b = _bind_udf_call(u, args)
            calls.append(b)
            schema.append((it.alias or _expr_name(it.expr, idx),
                           b.dtype))
        out = plan.UdfAggregate(node, calls, schema)
        # the result is ONE row: LIMIT/OFFSET still apply (LIMIT 0 /
        # OFFSET 1 must yield zero rows); ORDER BY would need key
        # resolution against the reduced row — reject it rather than
        # silently ignoring the clause
        if sel.order_by:
            raise BindError(
                "ORDER BY with aggregate UDFs is not supported yet")
        if sel.limit is not None or sel.offset:
            out = plan.Limit(out, sel.limit, sel.offset or 0, out.schema)
        return self._pushdown_filters(out)

    # --------------------------------------------------------- aggregates
    def _contains_agg(self, e: ast.Node) -> bool:
        if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS \
                and e.window is None:
            return True
        for f in dataclasses_fields_values(e):
            if isinstance(f, ast.Node) and self._contains_agg(f):
                return True
            if isinstance(f, list):
                for x in f:
                    if isinstance(x, ast.Node) and self._contains_agg(x):
                        return True
                    if isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, ast.Node) and self._contains_agg(y):
                                return True
        return False

    def _bind_aggregate(self, node, scope, sel, items, alias_map):
        # group keys (support alias + ordinal)
        group_asts: List[ast.Node] = []
        for g in sel.group_by:
            if isinstance(g, ast.Literal) and g.kind == "int":
                idx = int(g.value) - 1
                if not 0 <= idx < len(items):
                    raise BindError(
                        f"GROUP BY ordinal {int(g.value)} out of range")
                group_asts.append(items[idx].expr)
            elif isinstance(g, ast.ColumnRef) and g.table is None \
                    and g.name in alias_map:
                group_asts.append(alias_map[g.name])
            else:
                group_asts.append(g)
        group_keys = [self.bind_expr(g, scope) for g in group_asts]

        # collect agg calls from items + having + order by
        agg_calls: List[ast.FuncCall] = []

        def collect(e):
            if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS \
                    and e.window is None:
                agg_calls.append(e)
                return
            if isinstance(e, ast.FuncCall) and e.window is not None:
                # a windowed call is NOT a regular aggregate, but its args
                # and OVER clause may contain ones (share-of-total queries)
                for a in e.args:
                    collect(a)
                for p in e.window.partition_by:
                    collect(p)
                for o in e.window.order_by:
                    collect(o.expr)
                return
            for f in dataclasses_fields_values(e):
                if isinstance(f, ast.Node):
                    collect(f)
                elif isinstance(f, list):
                    for x in f:
                        if isinstance(x, ast.Node):
                            collect(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Node):
                                    collect(y)

        for it in items:
            collect(it.expr)
        if sel.having is not None:
            collect(sel.having)
        for o in sel.order_by:
            collect(o.expr)

        # dedupe by AST equality
        uniq: List[ast.FuncCall] = []
        for a in agg_calls:
            if not any(a == u for u in uniq):
                uniq.append(a)

        # COUNT(DISTINCT x) as the only aggregate: rewrite to
        # Distinct(keys + x) -> count(x) (colexec would use a dedup hash
        # table; Distinct is our sort-based dedup)
        if (len(uniq) == 1 and uniq[0].distinct
                and uniq[0].name == "count"
                and len(uniq[0].args) == 1 and not uniq[0].star):
            a = uniq[0]
            arg = self.bind_expr(a.args[0], scope)
            dedup_exprs = group_keys + [arg]
            dedup_schema = [(f"_g{i}", k.dtype)
                            for i, k in enumerate(group_keys)] + \
                [("_dv", arg.dtype)]
            proj = plan.Project(node, dedup_exprs, dedup_schema)
            node = plan.Distinct(proj, dedup_schema)
            group_keys = [BoundCol(f"_g{i}", k.dtype)
                          for i, k in enumerate(group_keys)]
            bound_aggs = [AggCall("count", BoundCol("_dv", arg.dtype),
                                  False, dt.INT64, out_name="_agg0")]
        else:
            bound_aggs = []
            for i, a in enumerate(uniq):
                if a.distinct:
                    if a.name == "count" and len(uniq) == 1:
                        raise BindError(
                            "count(DISTINCT ...) takes exactly one "
                            "argument")
                    if len(uniq) == 1:
                        raise BindError(
                            f"{a.name}(DISTINCT ...) is not supported yet")
                    raise BindError(
                        f"{a.name}(DISTINCT ...) is not supported yet when "
                        f"mixed with other aggregates")
                if a.star or (not a.args):
                    if a.name != "count":
                        raise BindError(f"{a.name}(*) is not valid")
                    bound_aggs.append(AggCall("count", None, False, dt.INT64,
                                              out_name=f"_agg{i}"))
                    continue
                arg = self.bind_expr(a.args[0], scope)
                fname = "min" if a.name == "any_value" else a.name
                if fname in STDDEV_AGGS | BIT_AGGS and \
                        not arg.dtype.is_numeric:
                    raise BindError(
                        f"{a.name}() requires a numeric argument")
                if fname in BIT_AGGS and not arg.dtype.is_integer:
                    raise BindError(
                        f"{a.name}() requires an integer argument")
                out_t = _agg_result_type(fname, arg.dtype)
                bound_aggs.append(AggCall(fname, arg, a.distinct, out_t,
                                          out_name=f"_agg{i}"))

        key_names = [f"_g{i}" for i in range(len(group_keys))]
        schema = list(zip(key_names, [k.dtype for k in group_keys])) + \
            [(a.out_name, a.dtype) for a in bound_aggs]
        agg_node = plan.Aggregate(node, group_keys, bound_aggs, schema)

        # post-agg scope: group keys by their source AST, aggs by AST
        new_scope = Scope()
        for name, dtype in schema:
            new_scope.add(None, name, dtype)
        agg_sub = {"group_asts": group_asts, "key_names": key_names,
                   "agg_asts": uniq, "aggs": bound_aggs,
                   "scope": new_scope}

        out = agg_node
        if sel.having is not None:
            pred = _coerce_bool(
                self._bind_post_agg(sel.having, new_scope, agg_sub))
            _require_bool(pred, "HAVING")
            out = plan.Filter(out, pred, out.schema)
        return out, new_scope, agg_sub

    def _bind_post_agg(self, e: ast.Node, scope: Scope, agg_sub) -> BoundExpr:
        """Bind an expression above an Aggregate: column refs must match a
        group key AST; agg calls become refs to agg outputs."""
        for g_ast, name in zip(agg_sub["group_asts"], agg_sub["key_names"]):
            if e == g_ast:
                dtype = {c: d for (_, c, d) in agg_sub["scope"].entries}[name]
                return BoundCol(name, dtype)
        if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS:
            for a_ast, bound in zip(agg_sub["agg_asts"], agg_sub["aggs"]):
                if e == a_ast:
                    return BoundCol(bound.out_name, bound.dtype)
            raise BindError("aggregate not collected (internal)")
        if isinstance(e, ast.ColumnRef):
            raise BindError(
                f"column {e.name} must appear in GROUP BY or an aggregate")
        return self._bind_generic(e, scope,
                                  lambda x: self._bind_post_agg(x, scope, agg_sub))

    # ------------------------------------------------------------ order by
    def _bind_item(self, e, scope, agg_sub, win_map):
        """Bind a select item, substituting window calls with their hidden
        columns (win_map: id(ast node) -> BoundCol)."""
        if isinstance(e, ast.FuncCall) and e.window is not None:
            if win_map:
                return win_map[id(e)]
        import dataclasses as dc

        def has_window(x):
            if isinstance(x, ast.FuncCall) and x.window is not None:
                return True
            if dc.is_dataclass(x) and isinstance(x, ast.Node):
                for f in dc.fields(x):
                    v = getattr(x, f.name)
                    vs = v if isinstance(v, list) else [v]
                    for y in vs:
                        if isinstance(y, ast.Node) and has_window(y):
                            return True
            return False
        if has_window(e):
            raise BindError(
                "window functions may only appear as top-level select "
                "items for now")
        if agg_sub:
            return self._bind_post_agg(e, scope, agg_sub)
        return self.bind_expr(e, scope)

    def _bind_windows(self, node, scope, items, agg_sub):
        """Collect fn(...) OVER (...) calls from select items into a
        plan.Window node; returns (node, scope, {id(ast): BoundCol})."""
        calls = [it.expr for it in items
                 if isinstance(it.expr, ast.FuncCall)
                 and it.expr.window is not None]
        if not calls:
            return node, scope, {}
        entries = []
        win_map = {}
        bind = (lambda x: self._bind_post_agg(x, scope, agg_sub)) \
            if agg_sub else (lambda x: self.bind_expr(x, scope))
        schema = list(node.schema)
        for i, fc in enumerate(calls):
            fn = fc.name
            if fn not in BASIC_AGGS and fn not in WINDOW_ONLY_FUNCS:
                raise BindError(f"{fn}() is not a window function")
            if fc.distinct:
                raise BindError(
                    f"{fn}(DISTINCT ...) OVER (...) is not supported yet")
            if fc.star and fn != "count":
                raise BindError(f"{fn}(*) is not valid")
            if fn in _RANK_FUNCS and (fc.args or fc.star):
                raise BindError(f"{fn}() takes no arguments")
            arg = None
            extra = {"frame": fc.window.frame}
            if fn in BASIC_AGGS and not fc.star:
                if not fc.args:
                    raise BindError(f"{fn}() needs an argument")
                arg = bind(fc.args[0])
                if arg.dtype.is_varlen and fn != "count":
                    raise BindError(
                        f"{fn}() over strings in windows is not "
                        f"supported yet")
            elif fn == "ntile":
                if len(fc.args) != 1 or not isinstance(
                        fc.args[0], ast.Literal):
                    raise BindError("ntile(N) needs one integer literal")
                extra["n"] = int(fc.args[0].value)
                if extra["n"] < 1:
                    raise BindError("ntile(N): N must be >= 1")
            elif fn in ("lag", "lead"):
                if not 1 <= len(fc.args) <= 3:
                    raise BindError(
                        f"{fn}(expr [, offset [, default]]) takes 1-3 "
                        f"arguments")
                arg = bind(fc.args[0])
                extra["offset"] = 1
                if len(fc.args) >= 2:
                    if not isinstance(fc.args[1], ast.Literal):
                        raise BindError(
                            f"{fn}() offset must be an integer literal")
                    extra["offset"] = int(fc.args[1].value)
                    if extra["offset"] < 0:
                        raise BindError(f"{fn}() offset must be >= 0")
                if len(fc.args) == 3:
                    dflt = bind(fc.args[2])
                    if not isinstance(dflt, BoundLiteral):
                        raise BindError(
                            f"{fn}() default must be a literal")
                    if dflt.value is None:
                        pass          # NULL default == no default
                    elif arg.dtype.is_varlen:
                        raise BindError(
                            f"{fn}() over strings supports only NULL "
                            f"default")
                    else:
                        extra["default"] = dflt
            elif fn in ("first_value", "last_value"):
                if len(fc.args) != 1:
                    raise BindError(f"{fn}(expr) takes one argument")
                arg = bind(fc.args[0])
            elif fn == "nth_value":
                if len(fc.args) != 2 or not isinstance(
                        fc.args[1], ast.Literal):
                    raise BindError(
                        "nth_value(expr, N) needs an integer literal N")
                arg = bind(fc.args[0])
                extra["n"] = int(fc.args[1].value)
                if extra["n"] < 1:
                    raise BindError("nth_value(expr, N): N must be >= 1")
            if extra["frame"] is not None and \
                    fn in _RANK_FUNCS | {"ntile", "lag", "lead"}:
                raise BindError(
                    f"{fn}() does not accept a frame clause")
            part = [bind(p) for p in fc.window.partition_by]
            okeys = [bind(o.expr) for o in fc.window.order_by]
            odescs = [o.descending for o in fc.window.order_by]
            if fn in BASIC_AGGS:
                out_t = _agg_result_type(fn, arg.dtype) if arg is not None \
                    else dt.INT64
            elif fn in _VALUE_FUNCS:
                out_t = arg.dtype
            else:
                out_t = dt.INT64
            out_name = f"_w{i}"
            entries.append((fn, arg, part, okeys, odescs, out_name,
                            extra))
            win_map[id(fc)] = BoundCol(out_name, out_t)
            schema.append((out_name, out_t))
        wnode = plan.Window(node, entries, schema)
        new_scope = Scope()
        new_scope.entries = list(scope.entries)
        for name, d in schema[len(node.schema):]:
            new_scope.add(None, name, d)
        return wnode, new_scope, win_map

    def _bind_order_key(self, e, node, names, exprs, scope, agg_sub,
                        alias_map):
        if isinstance(e, ast.Literal) and e.kind == "int":
            idx = int(e.value) - 1
            if not 0 <= idx < len(names):
                raise BindError(f"ORDER BY ordinal {idx + 1} out of range")
            return BoundCol(names[idx], exprs[idx].dtype)
        if isinstance(e, ast.ColumnRef) and e.table is None and e.name in names:
            i = names.index(e.name)
            return BoundCol(names[i], exprs[i].dtype)
        bound = self._bind_post_agg(e, scope, agg_sub) if agg_sub \
            else self.bind_expr(e, scope)
        # match an existing projected expression
        for i, pe in enumerate(exprs):
            if pe == bound:
                return BoundCol(names[i], pe.dtype)
        # hidden sort column: widen the projection
        if not isinstance(node, plan.Project):
            raise BindError(
                "ORDER BY expression must appear in the select list when "
                "using DISTINCT")
        hidden = f"_sort{len(node.exprs)}"
        node.exprs.append(bound)
        node.schema.append((hidden, bound.dtype))
        names.append(hidden)
        exprs.append(bound)
        return BoundCol(hidden, bound.dtype)

    # ------------------------------------------------------------- exprs
    def bind_expr(self, e: ast.Node, scope: Scope) -> BoundExpr:
        return self._bind_generic(e, scope,
                                  lambda x: self.bind_expr(x, scope))

    def _bind_generic(self, e: ast.Node, scope: Scope, rec) -> BoundExpr:
        if isinstance(e, ast.Literal):
            return _bind_literal(e)
        if isinstance(e, ast.DateLiteral):
            return BoundLiteral(e.days, dt.DATE)
        if isinstance(e, ast.ColumnRef):
            qname, dtype = scope.resolve(e.name, e.table)
            return BoundCol(qname, dtype)
        if isinstance(e, ast.BinaryOp):
            return self._bind_binary(e, rec)
        if isinstance(e, ast.UnaryOp):
            a = rec(e.operand)
            if e.op == "not":
                a = _coerce_bool(a)
                _require_bool(a, "NOT")
                return BoundFunc("not", [a], dt.BOOL)
            return BoundFunc("neg", [a], a.dtype)
        if isinstance(e, ast.FuncCall):
            return self._bind_func(e, rec)
        if isinstance(e, ast.SysVar):
            # @@name folds to the SESSION's current value at bind time
            # (reference: frontend/variables.go resolution)
            from matrixone_tpu.frontend.session import current_session
            s = current_session()
            val = (s.variables.get(e.name) if s is not None else None)
            if val is None:
                return BoundLiteral(None, dt.INT64)
            if isinstance(val, bool):
                return BoundLiteral(int(val), dt.INT64)
            if isinstance(val, int):
                return BoundLiteral(val, dt.INT64)
            if isinstance(val, float):
                return BoundLiteral(val, dt.FLOAT64)
            return BoundLiteral(str(val), dt.VARCHAR)
        if isinstance(e, ast.Cast):
            a = rec(e.expr)
            return BoundCast(a, type_from_name(e.type_name, e.type_args))
        if isinstance(e, ast.Case):
            whens = [(rec(c), rec(v)) for c, v in e.whens]
            else_ = rec(e.else_) if e.else_ is not None else None
            # result type promotes across EVERY branch, ELSE included:
            # `case when p then w else d end` over (int, double) is
            # double — typing it by the first THEN branch alone made
            # downstream arithmetic and derived table schemas truncate
            # the double branch (moqa seed-1 sqlite + mview findings)
            out_t = whens[0][1].dtype
            branches = [v for _, v in whens[1:]]
            if else_ is not None:
                branches.append(else_)
            for v in branches:
                out_t = dt.promote(out_t, v.dtype) if v.dtype.is_numeric \
                    and out_t.is_numeric else out_t
            return BoundCase(whens, else_, out_t)
        if isinstance(e, ast.InList):
            arg = rec(e.expr)
            vals = []
            for item in e.items:
                b = self._bind_generic(item, scope, rec)
                if not isinstance(b, BoundLiteral):
                    raise BindError("IN list items must be literals")
                vals.append(_literal_in_arg_domain(b, arg.dtype))
            return BoundInList(arg, vals, e.negated, dt.BOOL)
        if isinstance(e, ast.Between):
            arg = rec(e.expr)
            lo, hi = rec(e.low), rec(e.high)
            ge = BoundFunc("ge", [arg, lo], dt.BOOL)
            le = BoundFunc("le", [arg, hi], dt.BOOL)
            both = BoundFunc("and", [ge, le], dt.BOOL)
            if e.negated:
                return BoundFunc("not", [both], dt.BOOL)
            return both
        if isinstance(e, ast.IsNull):
            return BoundIsNull(rec(e.expr), e.negated, dt.BOOL)
        raise BindError(f"unsupported expression {type(e).__name__}")

    def _bind_binary(self, e: ast.BinaryOp, rec) -> BoundExpr:
        if e.op in ("date+", "date-"):
            left = rec(e.left)
            iv = e.right
            assert isinstance(iv, ast.IntervalLiteral)
            if isinstance(left, BoundLiteral) and left.dtype.oid == TypeOid.DATE:
                base = datetime.date(1970, 1, 1) + datetime.timedelta(days=left.value)
                sign = 1 if e.op == "date+" else -1
                if iv.unit == "day":
                    nd = base + datetime.timedelta(days=sign * iv.value)
                elif iv.unit == "month":
                    m = base.month - 1 + sign * iv.value
                    nd = base.replace(year=base.year + m // 12,
                                      month=m % 12 + 1)
                elif iv.unit == "year":
                    nd = base.replace(year=base.year + sign * iv.value)
                else:
                    raise BindError(f"unsupported interval unit {iv.unit}")
                return BoundLiteral((nd - datetime.date(1970, 1, 1)).days,
                                    dt.DATE)
            if iv.unit != "day":
                raise BindError("non-literal date +/- month/year not supported yet")
            delta = BoundLiteral(iv.value if e.op == "date+" else -iv.value,
                                 dt.INT32)
            return BoundFunc("date_add_days", [left, delta], dt.DATE)

        left, right = rec(e.left), rec(e.right)
        if e.op == "like":
            if not isinstance(right, BoundLiteral):
                raise BindError("LIKE pattern must be a literal")
            return BoundLike(left, str(right.value), False, dt.BOOL)
        if e.op in ("and", "or"):
            # typeless NULL / 0-1 integer literals coerce in logic
            # contexts (MySQL: NULL AND 0 is 0)
            left, right = _coerce_bool(left), _coerce_bool(right)
            _require_bool(left, e.op.upper())
            _require_bool(right, e.op.upper())
            return BoundFunc(e.op, [left, right], dt.BOOL)
        if e.op in ("=", "!=", "<", "<=", ">", ">="):
            op = {"=": "eq", "!=": "ne", "<": "lt", "<=": "le",
                  ">": "gt", ">=": "ge"}[e.op]
            return BoundFunc(op, [left, right], dt.BOOL)
        if e.op in ("+", "-", "*", "/", "%"):
            op = {"+": "add", "-": "sub", "*": "mul", "/": "div",
                  "%": "mod"}[e.op]
            out = _arith_result(op, left.dtype, right.dtype)
            return BoundFunc(op, [left, right], out)
        raise BindError(f"unsupported operator {e.op}")

    def _bind_func(self, e: ast.FuncCall, rec) -> BoundExpr:
        if e.name in AGG_FUNCS:
            raise BindError(f"aggregate {e.name}() not allowed here")
        # date_add/date_sub take an INTERVAL argument that is not an
        # expression (function_id.go DATE_ADD/DATE_SUB family)
        if e.name in ("date_add", "adddate", "date_sub", "subdate") \
                and len(e.args) == 2 \
                and isinstance(e.args[1], ast.IntervalLiteral):
            iv = e.args[1]
            sign = 1 if e.name in ("date_add", "adddate") else -1
            return _bind_date_add_unit(rec(e.args[0]),
                                       sign * iv.value, iv.unit)
        args = [rec(a) for a in e.args]
        from matrixone_tpu.udf import catalog as _ucat
        u = _ucat.lookup(self.catalog, e.name)
        if u is not None:
            if u.kind == "aggregate":
                raise BindError(
                    f"aggregate UDF {e.name}() is only allowed as a "
                    f"top-level select item")
            return _bind_udf_call(u, args)
        if e.name == "load_file":
            # datalink resolution (reference: load_file over the datalink
            # type): a constant URL reads at bind time through the stage
            # registry + fileservice
            if len(args) != 1 or not (isinstance(args[0], BoundLiteral)
                                      and isinstance(args[0].value, str)):
                raise BindError("load_file() requires a literal URL")
            from matrixone_tpu.storage.external import read_datalink
            return BoundLiteral(read_datalink(self.catalog, args[0].value),
                                dt.TEXT)
        return bind_scalar_function(e.name, args)

    # --------------------------------------------------------- pushdown
    def _pushdown_filters(self, node: plan.PlanNode) -> plan.PlanNode:
        """Move Filter conjuncts directly above a Scan into Scan.filters
        (feeds zonemap pruning in the reader — readutil analogue)."""
        node = self._push_join_predicates(node)
        return self._pushdown_scan_filters(node)

    def _pushdown_scan_filters(self, node):
        for attr in ("child", "left", "right"):
            c = getattr(node, attr, None)
            if c is not None:
                setattr(node, attr, self._pushdown_scan_filters(c))
        if isinstance(node, plan.Filter) and isinstance(node.child, plan.Scan):
            scan = node.child
            scan.filters = scan.filters + _split_bound_and(node.pred)
            return scan
        return node

    def _push_join_predicates(self, node) -> plan.PlanNode:
        """Distribute Filter conjuncts over cross/inner joins: side-local
        conjuncts sink to that side, two-sided equalities become join keys
        (cross -> inner). This is what turns `FROM a, b, c WHERE a.k = b.k
        AND ...` comma joins into hash joins instead of cross products
        (reference: plan/query_builder.go filter pushdown + join condition
        extraction)."""
        if isinstance(node, plan.Filter) and \
                isinstance(node.child, plan.Join) and \
                node.child.kind in ("cross", "inner"):
            j = node.child
            lnames = {n for n, _ in j.left.schema}
            rnames = {n for n, _ in j.right.schema}
            lpush, rpush, keep = [], [], []
            conjs = []
            for c0 in _split_bound_and(node.pred):
                conjs.extend(_split_bound_and(_factor_or(c0)))
            for c in conjs:
                refs = _bound_col_names(c)
                if refs <= lnames:
                    lpush.append(c)
                elif refs <= rnames:
                    rpush.append(c)
                else:
                    eq = _as_equi(c, lnames, rnames)
                    if eq is not None:
                        j.left_keys.append(eq[0])
                        j.right_keys.append(eq[1])
                        j.kind = "inner"
                    else:
                        keep.append(c)
            if lpush:
                j.left = plan.Filter(j.left, and_all(lpush),
                                     j.left.schema)
            if rpush:
                j.right = plan.Filter(j.right, and_all(rpush),
                                      j.right.schema)
            if j.kind == "cross" and j.left_keys:
                j.kind = "inner"
            if keep and j.kind == "cross":
                # no equi keys: evaluate the mixed predicate as the cross
                # join's residual (loopjoin analogue) instead of
                # materializing the full product above it
                res = and_all(keep)
                j.residual = res if j.residual is None else \
                    BoundFunc("and", [j.residual, res], dt.BOOL)
                keep = []
            out = j if not keep else plan.Filter(j, and_all(keep),
                                                 j.schema)
            for attr in ("child", "left", "right"):
                c = getattr(out, attr, None)
                if c is not None:
                    setattr(out, attr, self._push_join_predicates(c))
            return out
        for attr in ("child", "left", "right"):
            c = getattr(node, attr, None)
            if c is not None:
                setattr(node, attr, self._push_join_predicates(c))
        return node


# ------------------------------------------------------------------ helpers

def _bind_udf_call(u, args: List[BoundExpr]) -> BoundExpr:
    """Type-check and coerce a resolved UDF call; the definition is
    snapshot into the bound expression (see BoundUdfCall docstring)."""
    from matrixone_tpu.sql.expr import BoundUdfCall
    if len(args) != len(u.arg_types):
        raise BindError(
            f"{u.name}() takes {len(u.arg_types)} argument(s), "
            f"got {len(args)}")
    coerced = []
    for i, (a, want) in enumerate(zip(args, u.arg_types)):
        if a.dtype == want:
            coerced.append(a)
        elif a.dtype.is_numeric and want.is_numeric:
            coerced.append(BoundCast(a, want))
        else:
            raise BindError(
                f"{u.name}() argument {i + 1}: {a.dtype} is not "
                f"compatible with declared type {want}")
    return BoundUdfCall(
        u.name.lower(), coerced, u.ret_type, u.body,
        list(u.arg_names), list(u.arg_types), u.body_hash,
        u.deterministic, u.vectorized, u.kind == "aggregate")


def dataclasses_fields_values(e):
    import dataclasses as dc
    if not dc.is_dataclass(e):
        return []
    return [getattr(e, f.name) for f in dc.fields(e)]


def _expr_name(e: ast.Node, idx: int) -> str:
    if isinstance(e, ast.ColumnRef):
        return e.name
    if isinstance(e, ast.FuncCall):
        return f"{e.name}(*)" if e.star else f"{e.name}(...)"
    return f"_col{idx}"


def _split_and(e: ast.Node) -> List[ast.Node]:
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _split_bound_and(e: BoundExpr) -> List[BoundExpr]:
    if isinstance(e, BoundFunc) and e.op == "and":
        return _split_bound_and(e.args[0]) + _split_bound_and(e.args[1])
    return [e]


def _bound_col_names(e: BoundExpr) -> set:
    out = set()

    def walk(x):
        if isinstance(x, BoundCol):
            out.add(x.name)
        for f in dataclasses_fields_values(x):
            if isinstance(f, BoundExpr):
                walk(f)
            elif isinstance(f, list):
                for y in f:
                    if isinstance(y, BoundExpr):
                        walk(y)
                    elif isinstance(y, tuple):
                        for z in y:
                            if isinstance(z, BoundExpr):
                                walk(z)
    walk(e)
    return out


def _as_equi(c: BoundExpr, lnames: set, rnames: set):
    """eq(one-side expr, other-side expr) -> (left_expr, right_expr)."""
    if not (isinstance(c, BoundFunc) and c.op == "eq" and len(c.args) == 2):
        return None
    a, b = c.args
    ra, rb = _bound_col_names(a), _bound_col_names(b)
    if not ra or not rb:
        return None
    if ra <= lnames and rb <= rnames:
        return a, b
    if ra <= rnames and rb <= lnames:
        return b, a
    return None


def _factor_or(e: BoundExpr) -> BoundExpr:
    """(A and X) or (A and Y) -> A and (X or Y): pull conjuncts common to
    every OR arm out, so shared equi-join predicates (TPC-H Q19's
    p_partkey = l_partkey in each arm) become join keys."""
    if not (isinstance(e, BoundFunc) and e.op == "or"):
        return e
    arms = _split_bound_or(e)
    arm_conjs = [_split_bound_and(a) for a in arms]
    common = [c for c in arm_conjs[0]
              if all(any(c == d for d in conj) for conj in arm_conjs[1:])]
    if not common:
        return e
    rest_arms = []
    for conj in arm_conjs:
        rest = [c for c in conj if not any(c == d for d in common)]
        rest_arms.append(and_all(rest) if rest
                         else BoundLiteral(True, dt.BOOL))
    ored = rest_arms[0]
    for r in rest_arms[1:]:
        ored = BoundFunc("or", [ored, r], dt.BOOL)
    return and_all(common + [ored])


def _split_bound_or(e: BoundExpr) -> List[BoundExpr]:
    if isinstance(e, BoundFunc) and e.op == "or":
        return _split_bound_or(e.args[0]) + _split_bound_or(e.args[1])
    return [e]


def _coerce_bool(e: BoundExpr) -> BoundExpr:
    if isinstance(e, BoundLiteral) and e.dtype.oid != TypeOid.BOOL:
        if e.value is None:
            return BoundLiteral(None, dt.BOOL)
        if isinstance(e.value, int):
            return BoundLiteral(bool(e.value), dt.BOOL)
    if isinstance(e, BoundFunc) and e.op == "match_against":
        # MySQL: MATCH ... AGAINST in a boolean context is truthy when
        # the relevance score is positive
        return BoundFunc("gt", [e, BoundLiteral(0.0, dt.FLOAT64)],
                         dt.BOOL)
    return e


def _require_bool(e: BoundExpr, where: str):
    if e.dtype.oid != TypeOid.BOOL:
        raise BindError(f"{where} requires a boolean expression")


def _bind_literal(e: ast.Literal) -> BoundLiteral:
    out = None
    if e.kind == "int":
        out = BoundLiteral(int(e.value), dt.INT64)
    elif e.kind == "float":
        text = str(e.value)
        if "e" not in text.lower() and "." in text:
            frac = text.split(".", 1)[1]
            if len(frac) <= 8:
                scale = len(frac)
                scaled = int(round(float(text) * 10 ** scale))
                out = BoundLiteral(scaled, dt.decimal64(18, scale))
        if out is None:
            out = BoundLiteral(float(text), dt.FLOAT64)
    elif e.kind == "str":
        out = BoundLiteral(str(e.value), dt.VARCHAR)
    elif e.kind == "bool":
        out = BoundLiteral(bool(e.value), dt.BOOL)
    elif e.kind == "null":
        out = BoundLiteral(None, dt.INT64)  # typeless null; cast on use
    else:
        raise BindError(f"unknown literal kind {e.kind}")
    # serving plan cache: parameter-derived literals keep their index so
    # a cached plan can re-derive the value through this SAME transform
    # (serving/plan_cache.py PlanCache._instantiate); transforms that
    # build NEW literals drop the tag, which verifiably marks the plan
    # non-cacheable rather than ever patching a wrong value
    idx = getattr(e, "_param_idx", None)
    if idx is not None:
        out._param_idx = idx
    return out


def _literal_in_arg_domain(lit: BoundLiteral, arg_t: DType):
    if arg_t.oid == TypeOid.DECIMAL64 and lit.dtype.oid == TypeOid.DECIMAL64:
        return lit.value * 10 ** (arg_t.scale - lit.dtype.scale)
    if arg_t.oid == TypeOid.DECIMAL64 and lit.dtype.is_integer:
        return lit.value * 10 ** arg_t.scale
    return lit.value


def _arith_result(op: str, a: DType, b: DType) -> DType:
    if op == "div":
        return dt.FLOAT64
    if op in ("add", "sub"):
        if TypeOid.DECIMAL64 in (a.oid, b.oid) and not (a.is_float or b.is_float):
            sa = a.scale if a.oid == TypeOid.DECIMAL64 else 0
            sb = b.scale if b.oid == TypeOid.DECIMAL64 else 0
            return dt.decimal64(18, max(sa, sb))
        if a.oid == TypeOid.DATE and b.is_integer:
            return dt.DATE
    if op == "mul":
        if TypeOid.DECIMAL64 in (a.oid, b.oid) and not (a.is_float or b.is_float):
            sa = a.scale if a.oid == TypeOid.DECIMAL64 else 0
            sb = b.scale if b.oid == TypeOid.DECIMAL64 else 0
            return dt.decimal64(18, sa + sb)
    if not (a.is_numeric and b.is_numeric):
        if a.oid == b.oid:
            return a
        raise BindError(f"cannot apply {op} to {a} and {b}")
    return dt.promote(a, b)


def _agg_result_type(func: str, arg: DType) -> DType:
    if func == "count":
        return dt.INT64
    if func == "avg":
        return dt.FLOAT64
    if func == "sum":
        if arg.oid == TypeOid.DECIMAL64:
            return arg
        if arg.is_integer:
            return dt.INT64
        return dt.FLOAT64
    if func in STDDEV_AGGS:
        return dt.FLOAT64
    if func in BIT_AGGS:
        return dt.UINT64
    return arg  # min / max


_SCALAR_FUNCS = {
    "mod": ("mod", lambda ts: _arith_result("mod", ts[0], ts[1])),
    "abs": ("abs", lambda ts: ts[0]),
    "floor": ("floor", lambda ts: dt.FLOAT64),
    "ceil": ("ceil", lambda ts: dt.FLOAT64),
    "ceiling": ("ceil", lambda ts: dt.FLOAT64),
    "sqrt": ("sqrt", lambda ts: dt.FLOAT64),
    "exp": ("exp", lambda ts: dt.FLOAT64),
    "ln": ("ln", lambda ts: dt.FLOAT64),
    "log": ("ln", lambda ts: dt.FLOAT64),
    "sin": ("sin", lambda ts: dt.FLOAT64),
    "cos": ("cos", lambda ts: dt.FLOAT64),
    "power": ("power", lambda ts: dt.FLOAT64),
    "pow": ("power", lambda ts: dt.FLOAT64),
    "round": ("round", lambda ts: ts[0]),
    "coalesce": ("coalesce", lambda ts: ts[0]),
    "year": ("year", lambda ts: dt.INT32),
    "month": ("month", lambda ts: dt.INT32),
    "day": ("day", lambda ts: dt.INT32),
    "upper": ("upper", lambda ts: ts[0]),
    "ucase": ("upper", lambda ts: ts[0]),
    "lower": ("lower", lambda ts: ts[0]),
    "lcase": ("lower", lambda ts: ts[0]),
    "length": ("length", lambda ts: dt.INT64),
    "char_length": ("length", lambda ts: dt.INT64),
    "reverse": ("reverse", lambda ts: ts[0]),
    "trim": ("trim", lambda ts: ts[0]),
    "ltrim": ("ltrim", lambda ts: ts[0]),
    "rtrim": ("rtrim", lambda ts: ts[0]),
    "concat": ("concat", lambda ts: dt.VARCHAR),
    "substring": ("substring", lambda ts: dt.VARCHAR),
    "substr": ("substring", lambda ts: dt.VARCHAR),
    "replace": ("replace", lambda ts: dt.VARCHAR),
    "starts_with": ("starts_with", lambda ts: dt.BOOL),
    "ends_with": ("ends_with", lambda ts: dt.BOOL),
    "match_against": ("match_against", lambda ts: dt.FLOAT64),
    # timewin role (colexec/timewin): tumbling time windows via bucketed
    # GROUP BY — time_bucket(ts_col, width) floors to the window start
    "time_bucket": ("time_bucket", lambda ts: ts[0]),
    # ---- math long tail
    "tan": ("tan", lambda ts: dt.FLOAT64),
    "asin": ("asin", lambda ts: dt.FLOAT64),
    "acos": ("acos", lambda ts: dt.FLOAT64),
    "atan": ("atan", lambda ts: dt.FLOAT64),
    "atan2": ("atan2", lambda ts: dt.FLOAT64),
    "cot": ("cot", lambda ts: dt.FLOAT64),
    "degrees": ("degrees", lambda ts: dt.FLOAT64),
    "radians": ("radians", lambda ts: dt.FLOAT64),
    "log2": ("log2", lambda ts: dt.FLOAT64),
    "log10": ("log10", lambda ts: dt.FLOAT64),
    "sign": ("sign", lambda ts: dt.INT64),
    "truncate": ("truncate", lambda ts: ts[0]),
    "greatest": ("greatest", lambda ts: _common_numeric(ts)),
    "least": ("least", lambda ts: _common_numeric(ts)),
    # ---- string long tail (dictionary-level evaluation, vm/exprs.py)
    "lpad": ("lpad", lambda ts: dt.VARCHAR),
    "rpad": ("rpad", lambda ts: dt.VARCHAR),
    "repeat": ("repeat", lambda ts: dt.VARCHAR),
    "space": ("space", lambda ts: dt.VARCHAR),
    "instr": ("instr", lambda ts: dt.INT64),
    "locate": ("locate", lambda ts: dt.INT64),
    "position": ("locate", lambda ts: dt.INT64),
    "ascii": ("ascii", lambda ts: dt.INT64),
    "bit_length": ("bit_length", lambda ts: dt.INT64),
    "hex": ("hex", lambda ts: dt.VARCHAR),
    "unhex": ("unhex", lambda ts: dt.VARCHAR),
    "md5": ("md5", lambda ts: dt.VARCHAR),
    "sha1": ("sha1", lambda ts: dt.VARCHAR),
    "sha": ("sha1", lambda ts: dt.VARCHAR),
    "sha2": ("sha2", lambda ts: dt.VARCHAR),
    "crc32": ("crc32", lambda ts: dt.INT64),
    "to_base64": ("to_base64", lambda ts: dt.VARCHAR),
    "from_base64": ("from_base64", lambda ts: dt.VARCHAR),
    "substring_index": ("substring_index", lambda ts: dt.VARCHAR),
    "field": ("field", lambda ts: dt.INT64),
    "find_in_set": ("find_in_set", lambda ts: dt.INT64),
    "strcmp": ("strcmp", lambda ts: dt.INT64),
    "soundex": ("soundex", lambda ts: dt.VARCHAR),
    "quote": ("quote", lambda ts: dt.VARCHAR),
    "bin": ("bin", lambda ts: dt.VARCHAR),
    "oct": ("oct", lambda ts: dt.VARCHAR),
    "conv": ("conv", lambda ts: dt.VARCHAR),
    # ---- regexp family (Python re semantics on dictionary entries)
    "regexp_like": ("regexp_like", lambda ts: dt.BOOL),
    "regexp_instr": ("regexp_instr", lambda ts: dt.INT64),
    "regexp_substr": ("regexp_substr", lambda ts: dt.VARCHAR),
    "regexp_replace": ("regexp_replace", lambda ts: dt.VARCHAR),
    # ---- geo family (WKT, planar — pkg/geo role)
    "st_geomfromtext": ("st_geomfromtext", lambda ts: dt.VARCHAR),
    "st_astext": ("st_astext", lambda ts: dt.VARCHAR),
    "st_x": ("st_x", lambda ts: dt.FLOAT64),
    "st_y": ("st_y", lambda ts: dt.FLOAT64),
    "st_distance": ("st_distance", lambda ts: dt.FLOAT64),
    "st_within": ("st_within", lambda ts: dt.BOOL),
    "st_contains": ("st_contains", lambda ts: dt.BOOL),
    "st_area": ("st_area", lambda ts: dt.FLOAT64),
    "st_geohash": ("st_geohash", lambda ts: dt.VARCHAR),
    # ---- JSON family
    "json_extract": ("json_extract", lambda ts: dt.VARCHAR),
    "json_unquote": ("json_unquote", lambda ts: dt.VARCHAR),
    "json_valid": ("json_valid", lambda ts: dt.BOOL),
    "json_length": ("json_length", lambda ts: dt.INT64),
    "json_type": ("json_type", lambda ts: dt.VARCHAR),
    "json_keys": ("json_keys", lambda ts: dt.VARCHAR),
    # ---- date/time long tail
    "weekday": ("weekday", lambda ts: dt.INT32),
    "dayofweek": ("dayofweek", lambda ts: dt.INT32),
    "dayofmonth": ("day", lambda ts: dt.INT32),
    "dayofyear": ("dayofyear", lambda ts: dt.INT32),
    "quarter": ("quarter", lambda ts: dt.INT32),
    "week": ("week", lambda ts: dt.INT32),
    "last_day": ("last_day", lambda ts: dt.DATE),
    "to_days": ("to_days", lambda ts: dt.INT64),
    "from_days": ("from_days", lambda ts: dt.DATE),
    "datediff": ("datediff", lambda ts: dt.INT64),
    "hour": ("hour", lambda ts: dt.INT32),
    "minute": ("minute", lambda ts: dt.INT32),
    "second": ("second", lambda ts: dt.INT32),
    "date": ("date", lambda ts: dt.DATE),
    "unix_timestamp": ("unix_timestamp", lambda ts: dt.INT64),
    "from_unixtime": ("from_unixtime", lambda ts: dt.DATETIME),
    "monthname": ("monthname", lambda ts: dt.VARCHAR),
    "dayname": ("dayname", lambda ts: dt.VARCHAR),
    "l2_distance": ("l2_distance", lambda ts: dt.FLOAT64),
    "l2_distance_sq": ("l2_distance_sq", lambda ts: dt.FLOAT64),
    "cosine_distance": ("cosine_distance", lambda ts: dt.FLOAT64),
    "inner_product": ("inner_product", lambda ts: dt.FLOAT64),
    "cosine_similarity": ("cosine_similarity", lambda ts: dt.FLOAT64),
    # ---- r5 long tail: string family (dictionary-level eval)
    "left": ("left", lambda ts: dt.VARCHAR),
    "right": ("right", lambda ts: dt.VARCHAR),
    "mid": ("substring", lambda ts: dt.VARCHAR),
    "ord": ("ord", lambda ts: dt.INT64),
    "insert": ("insert_str", lambda ts: dt.VARCHAR),
    "elt": ("elt", lambda ts: dt.VARCHAR),
    "concat_ws": ("concat_ws", lambda ts: dt.VARCHAR),
    "split_part": ("split_part", lambda ts: dt.VARCHAR),
    "octet_length": ("octet_length", lambda ts: dt.INT64),
    "inet_aton": ("inet_aton", lambda ts: dt.INT64),
    # ---- r5: numeric -> string presentation (unique-value LUT)
    "inet_ntoa": ("inet_ntoa", lambda ts: dt.VARCHAR),
    "format": ("format_num", lambda ts: dt.VARCHAR),
    "sec_to_time": ("sec_to_time", lambda ts: dt.VARCHAR),
    "date_format": ("date_format", lambda ts: dt.VARCHAR),
    # ---- r5: date/time long tail
    "str_to_date": ("str_to_date", lambda ts: dt.DATE),
    "time_to_sec": ("time_to_sec", lambda ts: dt.INT64),
    "microsecond": ("microsecond", lambda ts: dt.INT32),
    "yearweek": ("yearweek", lambda ts: dt.INT64),
    "makedate": ("makedate", lambda ts: dt.DATE),
    "period_add": ("period_add", lambda ts: dt.INT64),
    "period_diff": ("period_diff", lambda ts: dt.INT64),
    "timestampdiff": ("timestampdiff", lambda ts: dt.INT64),
    "timestampadd": ("timestampadd", lambda ts: dt.DATETIME),
    "datetime": ("to_datetime", lambda ts: dt.DATETIME),
    # ---- r5: misc
    "bit_count": ("bit_count", lambda ts: dt.INT64),
    "uuid": ("uuid", lambda ts: dt.VARCHAR),
    "rand": ("rand", lambda ts: dt.FLOAT64),
    # ---- r6 long tail (serving PR): date/time
    "weekofyear": ("weekofyear", lambda ts: dt.INT32),
    "to_seconds": ("to_seconds", lambda ts: dt.INT64),
    "timediff": ("timediff", lambda ts: dt.VARCHAR),
    "addtime": ("addtime", lambda ts: dt.VARCHAR),
    "subtime": ("subtime", lambda ts: dt.VARCHAR),
    "time_format": ("time_format", lambda ts: dt.VARCHAR),
    "maketime": ("maketime", lambda ts: dt.VARCHAR),
    # ---- r6: string / net / json
    "is_ipv4": ("is_ipv4", lambda ts: dt.BOOL),
    "is_ipv6": ("is_ipv6", lambda ts: dt.BOOL),
    "inet6_aton": ("inet6_aton", lambda ts: dt.VARCHAR),
    "inet6_ntoa": ("inet6_ntoa", lambda ts: dt.VARCHAR),
    "json_quote": ("json_quote", lambda ts: dt.VARCHAR),
    "json_contains": ("json_contains", lambda ts: dt.BOOL),
    "char": ("char_fn", lambda ts: dt.VARCHAR),
    "make_set": ("make_set", lambda ts: dt.VARCHAR),
    "export_set": ("export_set", lambda ts: dt.VARCHAR),
    # ---- LLM family (func_builtin_llm.go role; endpoint-configured)
    "llm_chat": ("llm_chat", lambda ts: dt.VARCHAR),
}


_TIME_UNITS = {"microsecond", "second", "minute", "hour"}
_DATE_UNITS = {"day", "week", "month", "quarter", "year"}


def _bind_date_add_unit(base: BoundExpr, n: int, unit: str) -> BoundExpr:
    unit = unit.lower().rstrip("s")
    if unit not in _TIME_UNITS | _DATE_UNITS:
        raise BindError(f"unsupported interval unit {unit!r}")
    out_t = (dt.DATETIME if unit in _TIME_UNITS
             or base.dtype.oid in (TypeOid.DATETIME, TypeOid.TIMESTAMP)
             else dt.DATE)
    return BoundFunc("date_add_unit",
                     [base, BoundLiteral(int(n), dt.INT64),
                      BoundLiteral(unit, dt.VARCHAR)], out_t)


def _common_numeric(ts: List[DType]) -> DType:
    out = ts[0]
    for t in ts[1:]:
        if out.oid == t.oid and out.oid != TypeOid.DECIMAL64:
            continue
        if out.is_numeric and t.is_numeric:
            if TypeOid.DECIMAL64 in (out.oid, t.oid) \
                    and not (out.is_float or t.is_float):
                so = out.scale if out.oid == TypeOid.DECIMAL64 else 0
                st = t.scale if t.oid == TypeOid.DECIMAL64 else 0
                out = dt.decimal64(18, max(so, st))
            else:
                out = dt.promote(out, t)
    return out


def _session_info(name: str):
    """Info functions resolve against the EXECUTING session (the way the
    reference reads them from the frontend session): frontend/session.py
    publishes the current session in a contextvar during execute()."""
    from matrixone_tpu.frontend.session import current_session
    s = current_session()
    if name == "connection_id":
        return BoundLiteral(int(getattr(s, "conn_id", 0) or 0), dt.INT64)
    if name == "last_insert_id":
        return BoundLiteral(int(getattr(s, "last_insert_id", 0) or 0),
                            dt.INT64)
    if name in ("user", "current_user", "session_user", "system_user"):
        auth = getattr(s, "auth", None)
        u = ("root" if auth is None
             else f"{auth.account}:{auth.user}")
        return BoundLiteral(u + "@localhost", dt.VARCHAR)
    if name == "database":
        return BoundLiteral("mo_catalog", dt.VARCHAR)
    return None


_DATE_ARG_FUNCS = {
    "date", "year", "month", "day", "dayofmonth", "dayofweek",
    "dayofyear", "weekday", "week", "yearweek", "quarter", "last_day",
    "to_days", "datediff", "monthname", "dayname", "hour", "minute",
    "second", "microsecond", "unix_timestamp", "date_format",
    "weekofyear", "to_seconds", "adddate", "subdate",
}


def _coerce_date_literals(name: str, args: List[BoundExpr]) -> None:
    """MySQL accepts date/datetime STRINGS wherever dates go
    ('2024-01-02 10:00:00'); parse literal strings at bind so the
    kernels only ever see typed DATE/DATETIME values."""
    import datetime as _dtm
    if name not in _DATE_ARG_FUNCS:
        return
    for i, a in enumerate(args):
        if not (isinstance(a, BoundLiteral) and isinstance(a.value, str)):
            continue
        s = a.value.strip()
        try:
            if len(s) > 10:
                args[i] = BoundLiteral(dt.epoch_micros_from_iso(s),
                                       dt.DATETIME)
            else:
                args[i] = BoundLiteral(dt.epoch_days_from_iso(s),
                                       dt.DATE)
        except ValueError:
            pass        # not a date string: leave for the kernel/error


def _literal_round_int(a: BoundLiteral) -> int:
    """MySQL-style integer view of a numeric literal: decimals unscale
    first, fractional values round half away from zero."""
    import math
    v = a.value
    if a.dtype.oid == TypeOid.DECIMAL64:
        v = v / 10 ** a.dtype.scale
    x = float(v)
    n = int(math.floor(abs(x) + 0.5))
    return -n if x < 0 else n


def bind_scalar_function(name: str, args: List[BoundExpr]) -> BoundExpr:
    import datetime as _dtm
    import math
    _coerce_date_literals(name, args)
    # sugar rewrites (reference: many of the 554 ids are compositions)
    if name == "pi" and not args:
        return BoundLiteral(math.pi, dt.FLOAT64)
    if name == "version" and not args:
        return BoundLiteral("8.0.30-matrixone-tpu", dt.VARCHAR)
    if name in ("connection_id", "last_insert_id", "user", "current_user",
                "session_user", "system_user", "database", "schema") \
            and not args:
        r = _session_info("database" if name == "schema" else name)
        if r is not None:
            return r
    # statement-time clock literals (MySQL: fixed per statement)
    if name in ("now", "current_timestamp", "sysdate",
                "localtimestamp") and not args:
        return BoundLiteral(dt.epoch_micros(_dtm.datetime.now()),
                            dt.DATETIME)
    if name in ("utc_timestamp",) and not args:
        now = _dtm.datetime.now(_dtm.timezone.utc).replace(tzinfo=None)
        return BoundLiteral(dt.epoch_micros(now), dt.DATETIME)
    if name in ("curdate", "current_date") and not args:
        d = (_dtm.date.today() - _dtm.date(1970, 1, 1)).days
        return BoundLiteral(d, dt.DATE)
    if name in ("utc_date",) and not args:
        d = (_dtm.datetime.now(_dtm.timezone.utc).date()
             - _dtm.date(1970, 1, 1)).days
        return BoundLiteral(d, dt.DATE)
    if name in ("curtime", "current_time") and not args:
        now = _dtm.datetime.now()
        return BoundLiteral(now.strftime("%H:%M:%S"), dt.VARCHAR)
    if name == "log" and len(args) == 2:
        # log(b, x) = ln(x) / ln(b)
        lnx = BoundFunc("ln", [args[1]], dt.FLOAT64)
        lnb = BoundFunc("ln", [args[0]], dt.FLOAT64)
        return BoundFunc("div", [lnx, lnb], dt.FLOAT64)
    if name == "llm_embed":
        # embedding width is session-configured (the endpoint's model
        # decides; the session pins the SQL-visible vector type)
        from matrixone_tpu.frontend.session import current_session
        s = current_session()
        dim = int((s.variables.get("llm_embed_dim", 16)
                   if s is not None else 16))
        if len(args) != 1:
            raise BindError("llm_embed(text) takes one argument")
        return BoundFunc("llm_embed", args, dt.vecf32(dim))
    if name == "hex" and args and args[0].dtype.is_numeric:
        # MySQL: hex(string) dumps bytes, hex(number) rounds to BIGINT
        # and formats — two different functions behind one name
        return BoundFunc("hex_int", args, dt.VARCHAR)
    if name in ("timestampadd", "timestampdiff"):
        if len(args) != 3 or not isinstance(args[0], BoundLiteral):
            raise BindError(f"{name}(unit, a, b) takes a unit keyword "
                            f"and two arguments")
        unit = str(args[0].value).lower().rstrip("s")
        if unit not in _TIME_UNITS | _DATE_UNITS:
            raise BindError(f"unsupported {name} unit {unit!r}")
        if name == "timestampadd" and not (
                isinstance(args[1], BoundLiteral)
                and isinstance(args[1].value, int)):
            raise BindError(
                "timestampadd() count must be an integer literal "
                "(per-row counts are not supported yet)")
    if name in ("adddate", "subdate") and len(args) == 2:
        # MySQL 2-arg form: adddate(d, n) adds n DAYS (the INTERVAL form
        # is rewritten in _bind_func before reaching here).  A literal
        # delta may arrive as a scaled decimal (1.5 -> value 15 at
        # scale 1) or float — unscale and round to whole days (MySQL
        # rounds the day count), never use the scaled integer raw.
        delta = args[1]
        sign = -1 if name == "subdate" else 1
        if isinstance(delta, BoundLiteral):
            if delta.value is None:
                return BoundLiteral(None, dt.INT64)   # MySQL: NULL in -> NULL
            try:
                delta = BoundLiteral(sign * _literal_round_int(delta),
                                     dt.INT64)
            except (TypeError, ValueError):
                raise BindError(f"{name}() day count must be numeric")
        elif not delta.dtype.is_integer:
            raise BindError(
                f"{name}() per-row day counts must be integers")
        elif sign < 0:
            delta = BoundFunc("neg", [delta], delta.dtype)
        return BoundFunc("date_add_days", [args[0], delta], dt.DATE)
    if name == "char":
        # CHAR(65, 66) -> 'AB': each value contributes its big-endian
        # bytes (MySQL); NULLs are skipped. All-literal calls fold.
        if args and all(isinstance(a, BoundLiteral) for a in args):
            bs = b""
            for a in args:
                if a.value is None:
                    continue
                try:
                    n = _literal_round_int(a)
                except (TypeError, ValueError):
                    raise BindError("char() arguments must be numeric")
                if n < 0:
                    # matches the runtime path (vm/exprs char_fn):
                    # negative code points yield NULL
                    return BoundLiteral(None, dt.INT64)
                bs += n.to_bytes(max((n.bit_length() + 7) // 8, 1), "big")
            return BoundLiteral(bs.decode("utf-8", "replace"), dt.VARCHAR)
        if len(args) != 1:
            raise BindError(
                "char() over columns supports a single argument")
    if name == "maketime":
        if len(args) != 3:
            raise BindError("maketime(hour, minute, second)")
        if all(isinstance(a, BoundLiteral) for a in args):
            if any(a.value is None for a in args):
                return BoundLiteral(None, dt.INT64)   # MySQL: NULL in -> NULL
            try:
                h, m, s = (_literal_round_int(a) for a in args)
            except (TypeError, ValueError):
                raise BindError("maketime() arguments must be numeric")
            if not (0 <= m < 60 and 0 <= s < 60):
                # typeless NULL (same convention as _bind_literal: a
                # varchar-typed NULL const has no device representation)
                return BoundLiteral(None, dt.INT64)
            sign = "-" if h < 0 else ""
            return BoundLiteral(f"{sign}{abs(h):02d}:{m:02d}:{s:02d}",
                                dt.VARCHAR)
        if not all(isinstance(a, BoundLiteral) for a in args[1:]):
            raise BindError(
                "maketime() minute/second must be literals for now")
    if name == "if" and len(args) == 3:
        _require_bool(args[0], "if()")
        vt = (args[1].dtype if not (isinstance(args[1], BoundLiteral)
                                    and args[1].value is None)
              else args[2].dtype)
        return BoundCase([(args[0], args[1])], args[2], vt)
    if name == "ifnull" and len(args) == 2:
        name, args = "coalesce", args
    if name == "nullif" and len(args) == 2:
        eqf = BoundFunc("eq", [args[0], args[1]], dt.BOOL)
        return BoundCase([(eqf, BoundLiteral(None, args[0].dtype))],
                         args[0], args[0].dtype)
    if name == "isnull" and len(args) == 1:
        from matrixone_tpu.sql.expr import BoundIsNull
        return BoundIsNull(args[0], False, dt.BOOL)
    if name not in _SCALAR_FUNCS:
        raise BindError(f"unknown function {name}()")
    if name in ("greatest", "least"):
        if len(args) < 2:
            raise BindError(f"{name}() needs at least two arguments")
        if any(not a.dtype.is_numeric for a in args):
            # comparing dictionary codes across columns is meaningless
            raise BindError(
                f"{name}() over non-numeric arguments is not "
                f"supported yet")
    op, result = _SCALAR_FUNCS[name]
    # vector literals arrive as '[1,2,...]' strings (MySQL-client style)
    # — only distance functions take vectors (a regexp character class
    # also starts with '[' and must stay a string)
    if op in ("l2_distance", "l2_distance_sq", "cosine_distance",
              "inner_product", "cosine_similarity"):
        for i, a in enumerate(args):
            if isinstance(a, BoundLiteral) and isinstance(a.value, str) \
                    and a.value.lstrip().startswith("["):
                vec = [float(x)
                       for x in a.value.strip()[1:-1].split(",") if x]
                args[i] = BoundLiteral(vec, dt.vecf32(len(vec)))
        dims = [a.dtype.dim for a in args if a.dtype.is_vector]
        if len(dims) == 2 and dims[0] != dims[1]:
            raise BindError(
                f"{name}() dimension mismatch: {dims[0]} vs {dims[1]}")
    return BoundFunc(op, args, result([a.dtype for a in args]))

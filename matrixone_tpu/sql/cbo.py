"""Cost-based optimizer: cardinality estimation + greedy join reordering.

Reference analogue: `pkg/sql/plan/query_builder.go:2714-2790`
(determineJoinOrder over the equi-join graph using stats.go estimates)
plus the build/probe side decision in `plan/build_constraint_util.go`.
Redesign for this engine's executor:

  * the physical join (`vm/join.py`) STREAMS the probe (left) side and
    MATERIALIZES the build (right) side on device — so the optimizer's
    job here is (a) pick a left-deep order that keeps intermediate
    results small and (b) put the smaller input on the build side;
  * estimation works on the bound plan tree with a per-node column-stats
    environment (Scan seeds it from `sql/stats.py`, Project renames it),
    so join-key NDVs survive through filters/projections;
  * inner-join residual predicates are order-independent (they are just
    filters over match lanes), so the flattener carries them as pending
    predicates and re-attaches each at the first join where its columns
    exist.

The pass is a no-op on trees without inner/cross join regions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.sql import plan as P
from matrixone_tpu.sql.expr import (BoundCase, BoundCast, BoundCol,
                                    BoundExpr, BoundFunc, BoundInList,
                                    BoundIsNull, BoundLike, BoundLiteral,
                                    and_all, columns_used)
from matrixone_tpu.sql.stats import StatsProvider, TableStats

DEFAULT_SEL = 1.0 / 3.0
_EPS = 1e-9


# --------------------------------------------------------------- estimation

@dataclasses.dataclass
class Est:
    rows: float
    # qualified column name -> (ndv, lo, hi); lo/hi None when unknown
    cols: Dict[str, tuple]

    def ndv(self, name: str) -> Optional[float]:
        c = self.cols.get(name)
        return None if c is None else min(c[0], max(self.rows, 1.0))


def _lit_num(e: BoundExpr) -> Optional[float]:
    if isinstance(e, BoundLiteral) and isinstance(e.value, (int, float)) \
            and not isinstance(e.value, bool):
        v = float(e.value)
        if e.dtype.oid == dt.TypeOid.DECIMAL64:
            v /= 10 ** e.dtype.scale
        return v
    return None


def _col_range(env: Est, col: BoundCol) -> tuple:
    c = env.cols.get(col.name)
    if c is None:
        return None, None
    lo, hi = c[1], c[2]
    if lo is not None and col.dtype.oid == dt.TypeOid.DECIMAL64:
        lo, hi = lo / 10 ** col.dtype.scale, hi / 10 ** col.dtype.scale
    return lo, hi


def selectivity(pred: BoundExpr, env: Est) -> float:
    """Fraction of rows surviving `pred` given the column environment."""
    if isinstance(pred, BoundFunc):
        op = pred.op
        if op == "and":
            return selectivity(pred.args[0], env) * \
                selectivity(pred.args[1], env)
        if op == "or":
            a = selectivity(pred.args[0], env)
            b = selectivity(pred.args[1], env)
            return min(1.0, a + b - a * b)
        if op == "not":
            return max(0.0, 1.0 - selectivity(pred.args[0], env))
        if op in ("eq", "ne", "lt", "le", "gt", "ge") and len(pred.args) == 2:
            a, b = pred.args
            if isinstance(b, BoundCol) and not isinstance(a, BoundCol):
                a, b = b, a
                op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                      "eq": "eq", "ne": "ne"}[op]
            if isinstance(a, BoundCol):
                lv = _lit_num(b)
                if op == "eq":
                    if isinstance(b, BoundCol):
                        # correlated equality inside one relation
                        n1, n2 = env.ndv(a.name), env.ndv(b.name)
                        d = max(n1 or 0, n2 or 0)
                        return 1.0 / d if d > 1 else DEFAULT_SEL
                    d = env.ndv(a.name)
                    return 1.0 / d if d and d > 0 else DEFAULT_SEL
                if op == "ne":
                    d = env.ndv(a.name)
                    return 1.0 - (1.0 / d) if d and d > 1 else 1.0
                lo, hi = _col_range(env, a)
                if lv is not None and lo is not None and hi > lo:
                    if op in ("lt", "le"):
                        f = (lv - lo) / (hi - lo)
                    else:
                        f = (hi - lv) / (hi - lo)
                    return min(1.0, max(0.0, f))
            return DEFAULT_SEL
    if isinstance(pred, BoundInList):
        d = env.ndv(pred.arg.name) if isinstance(pred.arg, BoundCol) else None
        s = len(pred.values) / d if d and d > 0 else DEFAULT_SEL
        s = min(1.0, s)
        return 1.0 - s if pred.negated else s
    if isinstance(pred, BoundLike):
        return 0.75 if pred.negated else 0.25
    if isinstance(pred, BoundIsNull):
        return 0.9 if pred.negated else 0.1
    return DEFAULT_SEL


def estimate(node: P.PlanNode, sp: StatsProvider) -> Est:
    """Bottom-up (rows, column-stats) estimate for a plan subtree."""
    if isinstance(node, P.Scan):
        ts = sp.table(node.table)
        if ts is None:
            return Est(1000.0, {})
        cols = {}
        for (qn, _), raw in zip(node.schema, node.columns):
            c = ts.cols.get(raw)
            if c is not None:
                cols[qn] = (c.ndv, c.lo, c.hi)
        env = Est(float(max(ts.row_count, 1)), cols)
        rows = env.rows
        for f in node.filters:
            rows *= selectivity(f, env)
        return Est(max(rows, _EPS), cols)
    if isinstance(node, P.Filter):
        ch = estimate(node.child, sp)
        return Est(max(ch.rows * selectivity(node.pred, ch), _EPS), ch.cols)
    if isinstance(node, P.Project):
        ch = estimate(node.child, sp)
        cols = {}
        for (qn, _), e in zip(node.schema, node.exprs):
            if isinstance(e, BoundCol) and e.name in ch.cols:
                cols[qn] = ch.cols[e.name]
        return Est(ch.rows, cols)
    if isinstance(node, P.Aggregate):
        ch = estimate(node.child, sp)
        if not node.group_keys:
            return Est(1.0, {})
        groups = 1.0
        for k in node.group_keys:
            d = ch.ndv(k.name) if isinstance(k, BoundCol) else None
            groups *= d if d else math.sqrt(max(ch.rows, 1.0))
        return Est(min(groups, ch.rows), ch.cols)
    if isinstance(node, P.Distinct):
        ch = estimate(node.child, sp)
        return Est(ch.rows, ch.cols)
    if isinstance(node, (P.Sort, P.Window)):
        ch = estimate(node.child, sp)
        return Est(ch.rows, ch.cols)
    if isinstance(node, P.TopK):
        ch = estimate(node.child, sp)
        return Est(min(float(node.k), ch.rows), ch.cols)
    if isinstance(node, P.Limit):
        ch = estimate(node.child, sp)
        n = float(node.n) if node.n is not None else ch.rows
        return Est(min(n, ch.rows), ch.cols)
    if isinstance(node, P.Join):
        le = estimate(node.left, sp)
        re_ = estimate(node.right, sp)
        cols = {**le.cols, **re_.cols}
        rows = _join_rows(node.kind, le, re_, node.left_keys,
                          node.right_keys)
        if node.residual is not None and node.kind in ("inner", "cross"):
            rows *= selectivity(node.residual, Est(rows, cols))
        if node.kind in ("semi", "anti", "left"):
            cols = dict(cols) if node.kind == "left" else le.cols
        return Est(max(rows, _EPS), cols)
    if isinstance(node, P.Sample):
        ch = estimate(node.child, sp)
        if node.n_rows is not None:
            return Est(min(float(node.n_rows), ch.rows), ch.cols)
        return Est(ch.rows * node.percent / 100.0, ch.cols)
    if isinstance(node, P.Fill):
        ch = estimate(node.child, sp)
        return Est(ch.rows, ch.cols)
    if isinstance(node, P.Union):
        rows = sum(estimate(c, sp).rows for c in node.children)
        return Est(rows, {})
    if isinstance(node, P.Values):
        return Est(float(len(node.rows)), {})
    if isinstance(node, (P.VectorTopK, P.FulltextTopK)):
        return Est(float(node.k), {})
    ch = getattr(node, "child", None)
    if ch is not None:
        return estimate(ch, sp)
    return Est(1000.0, {})


def _join_rows(kind: str, le: Est, re_: Est, lkeys, rkeys) -> float:
    if kind == "cross":
        return le.rows * re_.rows
    if kind in ("semi", "anti"):
        base = _equi_rows(le, re_, lkeys, rkeys)
        frac = min(1.0, base / max(le.rows, _EPS))
        return le.rows * (frac if kind == "semi" else (1.0 - frac * 0.9))
    inner = _equi_rows(le, re_, lkeys, rkeys)
    if kind == "left":
        return max(inner, le.rows)
    if kind == "full":
        return max(inner, le.rows, re_.rows)
    return inner


def _equi_rows(le: Est, re_: Est, lkeys, rkeys) -> float:
    denom = 1.0
    for lk, rk in zip(lkeys or [], rkeys or []):
        dl = le.ndv(lk.name) if isinstance(lk, BoundCol) else None
        dr = re_.ndv(rk.name) if isinstance(rk, BoundCol) else None
        d = max(dl or 0.0, dr or 0.0)
        if d <= 0:
            d = math.sqrt(max(min(le.rows, re_.rows), 1.0))
        denom = max(denom, d)
    if not lkeys:
        return le.rows * re_.rows
    return le.rows * re_.rows / denom


# ---------------------------------------------------------------- reorder

@dataclasses.dataclass
class _Edge:
    a: BoundExpr             # key expr over leaf set A
    b: BoundExpr
    a_leaf: int
    b_leaf: int


def _flatten_region(j: P.Join, leaves: list, edges_raw: list,
                    pending: list) -> None:
    """Collect the maximal inner/cross join region rooted at `j`."""
    for side in (j.left, j.right):
        if isinstance(side, P.Join) and side.kind in ("inner", "cross") :
            _flatten_region(side, leaves, edges_raw, pending)
        else:
            leaves.append(side)
    for lk, rk in zip(j.left_keys or [], j.right_keys or []):
        edges_raw.append((lk, rk))
    if j.residual is not None:
        pending.append(j.residual)


def _leaf_of(expr: BoundExpr, leaf_names: List[set]) -> Optional[int]:
    used = set(columns_used(expr))
    if not used:
        return None
    owners = [i for i, names in enumerate(leaf_names) if used <= names]
    return owners[0] if len(owners) == 1 else None


def reorder_joins(node: P.PlanNode, sp: StatsProvider) -> P.PlanNode:
    """Recursively reorder every maximal inner/cross join region using a
    greedy smallest-intermediate heuristic, and place the smaller side of
    every rebuilt join on the build (right) side."""
    if isinstance(node, P.Join) and node.kind in ("inner", "cross"):
        leaves: list = []
        edges_raw: list = []
        pending: list = []
        _flatten_region(node, leaves, edges_raw, pending)
        leaves = [reorder_joins(l, sp) for l in leaves]
        leaf_names = [{n for n, _ in l.schema} for l in leaves]
        edges: List[_Edge] = []
        for a, b in edges_raw:
            ia, ib = _leaf_of(a, leaf_names), _leaf_of(b, leaf_names)
            if ia is None or ib is None:
                pending.append(BoundFunc("eq", [a, b], dt.BOOL))
            else:
                edges.append(_Edge(a, b, ia, ib))
        return _greedy_build(leaves, edges, pending, sp)
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if c is not None:
            setattr(node, attr, reorder_joins(c, sp))
    if getattr(node, "children", None):
        node.children = [reorder_joins(c, sp) for c in node.children]
    return node


def _greedy_build(leaves, edges, pending, sp) -> P.PlanNode:
    ests = [estimate(l, sp) for l in leaves]
    n = len(leaves)
    remaining = set(range(n))
    # start from the smallest leaf that has at least one edge (a pure
    # cross-product island starts only if nothing is connected)
    connected = {e.a_leaf for e in edges} | {e.b_leaf for e in edges}
    order_pool = sorted(remaining,
                        key=lambda i: (i not in connected, ests[i].rows))
    start = order_pool[0]
    acc = leaves[start]
    acc_est = ests[start]
    acc_set = {start}
    remaining.discard(start)
    pending = list(pending)

    while remaining:
        best = None          # (rows, leaf_idx, keys)
        for i in remaining:
            keys = _keys_between(edges, acc_set, i)
            if not keys:
                continue
            le, re_ = acc_est, ests[i]
            rows = _equi_rows(le, re_, [a for a, _ in keys],
                              [b for _, b in keys])
            if best is None or rows < best[0]:
                best = (rows, i, keys)
        if best is None:
            # disconnected: cross-join the smallest remaining leaf
            i = min(remaining, key=lambda i: ests[i].rows)
            best = (acc_est.rows * ests[i].rows, i, [])
        rows, i, keys = best
        left, right = acc, leaves[i]
        lkeys = [a for a, _ in keys]
        rkeys = [b for _, b in keys]
        left_est, right_est = acc_est, ests[i]
        # build side = smaller input (vm/join materializes the right side)
        if right_est.rows > left_est.rows * 1.2:
            left, right = right, left
            lkeys, rkeys = rkeys, lkeys
            left_est, right_est = right_est, left_est
        kind = "inner" if keys else "cross"
        j = P.Join(kind, left, right, lkeys, rkeys, None,
                   left.schema + right.schema)
        acc_set.add(i)
        remaining.discard(i)
        # attach any pending residuals whose columns are now in scope
        avail = {nm for nm, _ in j.schema}
        still = []
        for pr in pending:
            if set(columns_used(pr)) <= avail:
                j.residual = pr if j.residual is None else \
                    BoundFunc("and", [j.residual, pr], dt.BOOL)
            else:
                still.append(pr)
        pending = still
        acc = j
        acc_est = estimate(j, sp)
    if pending:
        acc = P.Filter(acc, and_all(pending), acc.schema)
    return acc


def _keys_between(edges: List[_Edge], acc_set: set, i: int):
    out = []
    for e in edges:
        if e.a_leaf in acc_set and e.b_leaf == i:
            out.append((e.a, e.b))
        elif e.b_leaf in acc_set and e.a_leaf == i:
            out.append((e.b, e.a))
    return out


def optimize_plan(node: P.PlanNode, catalog) -> P.PlanNode:
    """Entry point for the session: stats-driven join reordering."""
    from matrixone_tpu.sql.stats import provider_for
    return reorder_joins(node, provider_for(catalog))

"""Decorrelation: rewrite correlated subqueries into joins, pre-bind.

Reference analogue: the plan builder's subquery flattening
(`pkg/sql/plan/build_dml_util.go` / `query_builder.go` turn EXISTS into
semi joins and correlated scalar aggregates into grouped derived tables).
Here the rewrite is AST -> AST so the ordinary binder/optimizer handles
the result:

  [NOT] EXISTS (SELECT ... WHERE inner_k = outer_k AND p [AND mixed])
      -> ast.SemiJoinSpec on the enclosing Select (bound as a semi/anti
         join; `mixed` non-equi outer-referencing conjuncts become the
         join residual — TPC-H Q21's l2.l_suppkey <> l1.l_suppkey)

  expr CMP (SELECT agg(x) FROM ... WHERE inner_k = outer_k AND p)
      -> derived table (SELECT inner_k, agg(x) FROM ... WHERE p GROUP BY
         inner_k) joined on inner_k = outer_k, CMP against its agg column
         (empty-group rows vanish via the inner join — identical to the
         NULL-compare semantics of the correlated form for non-COUNT
         aggregates; COUNT would need a left join + COALESCE and is
         rejected)

Uncorrelated subqueries are left untouched (the session inlines them by
executing once). Correlation is detected structurally: a column reference
inside the subquery that does not resolve against the subquery's own FROM
but does resolve in the enclosing scope.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Set, Tuple

from matrixone_tpu.sql import ast

_counter = itertools.count()

AGG_NAMES = {"count", "sum", "avg", "min", "max"}


class _Locals:
    """Name environment of one FROM clause: alias -> column set."""

    def __init__(self):
        self.tables: Dict[str, Set[str]] = {}

    @property
    def all_cols(self) -> Set[str]:
        out = set()
        for cols in self.tables.values():
            out |= cols
        return out

    def resolves(self, ref: ast.ColumnRef) -> bool:
        if ref.table is not None:
            return ref.table in self.tables and \
                ref.name in self.tables[ref.table]
        return ref.name in self.all_cols


def _collect_locals(from_, catalog, ctes: Dict[str, ast.Select]) -> _Locals:
    env = _Locals()

    def walk(f):
        if f is None:
            return
        if isinstance(f, ast.TableRef):
            alias = f.alias or f.name
            if f.name in ctes:
                env.tables[alias] = _output_names(ctes[f.name])
                return
            try:
                meta = catalog.get_table(f.name)
            except (KeyError, ValueError):   # unknown table: binder
                env.tables[alias] = set()    # reports it, not us
                return
            env.tables[alias] = {c for c, _ in meta.schema}
        elif isinstance(f, ast.SubqueryRef):
            env.tables[f.alias] = _output_names(f.select)
        elif isinstance(f, ast.Join):
            walk(f.left)
            walk(f.right)
    walk(from_)
    return env


def _output_names(sel: ast.Select) -> Set[str]:
    out = set()
    if isinstance(sel, ast.Union):
        return _output_names(sel.selects[0])
    for i, it in enumerate(sel.items):
        if it.alias:
            out.add(it.alias)
        elif isinstance(it.expr, ast.ColumnRef):
            out.add(it.expr.name)
        else:
            out.add(f"_col{i}")
    return out


def _column_refs(e, out: List[ast.ColumnRef]):
    if isinstance(e, ast.ColumnRef):
        out.append(e)
        return
    if isinstance(e, (ast.Subquery, ast.Exists, ast.SubqueryRef)):
        return   # nested scopes analyzed on their own pass
    if dataclasses.is_dataclass(e) and isinstance(e, ast.Node):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(x, ast.Node):
                    _column_refs(x, out)
                elif isinstance(x, (list, tuple)):
                    for y in x:
                        if isinstance(y, ast.Node):
                            _column_refs(y, out)


def _split_and(e):
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _and_all(cs):
    if not cs:
        return None
    e = cs[0]
    for c in cs[1:]:
        e = ast.BinaryOp("and", e, c)
    return e


def _classify(e, inner: _Locals, outer: _Locals) -> str:
    """'local' | 'outer' | 'mixed' | 'unknown' for one expression."""
    refs: List[ast.ColumnRef] = []
    _column_refs(e, refs)
    if not refs:
        return "local"
    kinds = set()
    for r in refs:
        if inner.resolves(r):
            kinds.add("local")
        elif outer.resolves(r):
            kinds.add("outer")
        else:
            kinds.add("unknown")
    if kinds == {"local"}:
        return "local"
    if kinds == {"outer"}:
        return "outer"
    if "unknown" in kinds:
        return "unknown"
    return "mixed"


def _has_subquery(e) -> bool:
    if isinstance(e, (ast.Subquery, ast.Exists)):
        return True
    if dataclasses.is_dataclass(e) and isinstance(e, ast.Node):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(x, ast.Node) and _has_subquery(x):
                    return True
    return False


def is_correlated(sub: ast.Select, outer: _Locals, catalog, ctes) -> bool:
    inner = _collect_locals(sub.from_, catalog, ctes)
    refs: List[ast.ColumnRef] = []
    for part in [sub.where, sub.having] + [it.expr for it in sub.items]:
        if part is not None:
            _column_refs(part, refs)
    return any(not inner.resolves(r) and outer.resolves(r) for r in refs)


class DecorrelateError(Exception):
    pass


def _split_correlation(sub: ast.Select, outer: _Locals, catalog, ctes):
    """Split sub.where into (inner_only, [(outer_expr, inner_expr)],
    mixed_residual). Raises DecorrelateError when a conjunct can't be
    placed (correlation outside WHERE, unknown names...)."""
    inner = _collect_locals(sub.from_, catalog, ctes)
    inner_keep, pairs, mixed = [], [], []
    for c in _split_and(sub.where) if sub.where is not None else []:
        kind = _classify(c, inner, outer)
        if kind == "local" or _has_subquery(c):
            inner_keep.append(c)
            continue
        if kind == "unknown":
            raise DecorrelateError(f"unresolvable column in {c}")
        if isinstance(c, ast.BinaryOp) and c.op == "=":
            lk = _classify(c.left, inner, outer)
            rk = _classify(c.right, inner, outer)
            if lk == "local" and rk == "outer":
                pairs.append((c.right, c.left))
                continue
            if lk == "outer" and rk == "local":
                pairs.append((c.left, c.right))
                continue
        mixed.append(c)
    # correlation must be confined to WHERE
    for part in [sub.having] + [it.expr for it in sub.items]:
        if part is not None and _classify(part, inner, outer) not in (
                "local",):
            raise DecorrelateError("correlation outside WHERE")
    if not pairs and not mixed:
        raise DecorrelateError("subquery is not correlated")
    return inner_keep, pairs, mixed


def _rewrite_local_refs(e, inner: _Locals, alias: str,
                        res_items: List[ast.SelectItem]):
    """In a mixed conjunct, replace inner-resolving column refs with
    references to projected residual columns of the semi-join build side."""
    if isinstance(e, ast.ColumnRef):
        if inner.resolves(e):
            name = f"{alias}_r{len(res_items)}"
            for it in res_items:      # reuse an existing projection
                if isinstance(it.expr, ast.ColumnRef) and \
                        it.expr.table == e.table and it.expr.name == e.name:
                    name = it.alias
                    break
            else:
                res_items.append(ast.SelectItem(
                    ast.ColumnRef(e.name, e.table), alias=name))
            # unqualified: the {alias}_r* names are globally unique and the
            # binder exposes them table-less in the residual scope
            return ast.ColumnRef(name, None)
        return e
    if dataclasses.is_dataclass(e) and isinstance(e, ast.Node):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, ast.Node):
                setattr(e, f.name,
                        _rewrite_local_refs(v, inner, alias, res_items))
            elif isinstance(v, list):
                setattr(e, f.name, [
                    _rewrite_local_refs(x, inner, alias, res_items)
                    if isinstance(x, ast.Node) else x for x in v])
    return e


def decorrelate_select(sel: ast.Select, catalog,
                       ctes: Optional[Dict[str, ast.Select]] = None) -> None:
    """In-place: rewrite correlated EXISTS / scalar-agg subqueries in
    sel.where into SemiJoinSpecs / grouped derived-table joins. Leaves
    uncorrelated subqueries for the session's inline-once path."""
    if ctes is None:
        ctes = {}
    ctes = {**ctes, **{n: s for n, s in sel.ctes}}
    if sel.where is None:
        return
    outer = _collect_locals(sel.from_, catalog, ctes)
    conjuncts = _split_and(sel.where)
    out: List[ast.Node] = []
    for c in conjuncts:
        rewritten = _try_rewrite(c, sel, outer, catalog, ctes)
        out.extend(rewritten if isinstance(rewritten, list) else [rewritten])
    sel.where = _and_all(out)


def _try_rewrite(c, sel, outer, catalog, ctes):
    # --- [NOT] EXISTS (the parser emits NOT as a wrapping UnaryOp)
    if isinstance(c, ast.UnaryOp) and c.op == "not" and \
            isinstance(c.operand, ast.Exists):
        c = ast.Exists(c.operand.select, negated=not c.operand.negated)
    if isinstance(c, ast.Exists) and is_correlated(c.select, outer,
                                                   catalog, ctes):
        if c.select.limit == 0:
            # EXISTS (... LIMIT 0) is constant: no rows can match
            return ast.Literal(bool(c.negated), "bool")
        try:
            inner_keep, pairs, mixed = _split_correlation(
                c.select, outer, catalog, ctes)
        except DecorrelateError:
            return c
        inner = _collect_locals(c.select.from_, catalog, ctes)
        alias = f"__sj{next(_counter)}"
        items = [ast.SelectItem(ie, alias=f"{alias}_k{i}")
                 for i, (_, ie) in enumerate(pairs)]
        res_items: List[ast.SelectItem] = []
        residual = None
        if mixed:
            mixed = [_rewrite_local_refs(m, inner, alias, res_items)
                     for m in mixed]
            residual = _and_all(mixed)
        if not pairs and mixed:
            # no equi keys: fall back to a constant key (degenerate
            # cross semi join with residual only)
            items = [ast.SelectItem(ast.Literal(1, "int"),
                                    alias=f"{alias}_k0")]
            pairs = [(ast.Literal(1, "int"), None)]
        sub = dataclasses.replace(
            c.select, items=items + res_items,
            where=_and_all(inner_keep), limit=None, order_by=[],
            semijoins=list(c.select.semijoins))
        sel.semijoins.append(ast.SemiJoinSpec(
            select=sub, outer_keys=[oe for oe, _ in pairs],
            n_keys=len(pairs), residual=residual, negated=c.negated,
            alias=alias))
        return []                      # conjunct fully consumed
    # --- expr CMP (scalar agg subquery)  (either side)
    if isinstance(c, ast.BinaryOp) and c.op in ("=", "<>", "<", "<=",
                                                ">", ">="):
        for this, other, flip in ((c.left, c.right, False),
                                  (c.right, c.left, True)):
            if not isinstance(this, ast.Subquery):
                continue
            s = this.select
            if isinstance(s, ast.Union) or not isinstance(s, ast.Select):
                continue
            if not is_correlated(s, outer, catalog, ctes):
                continue
            if len(s.items) != 1 or s.group_by:
                return c
            agg_expr = s.items[0].expr
            if _contains_count(agg_expr):
                return c               # COUNT over empty group is 0, not
                                      # NULL: inner join would be wrong
            try:
                inner_keep, pairs, mixed = _split_correlation(
                    s, outer, catalog, ctes)
            except DecorrelateError:
                return c
            if mixed or not pairs:
                return c
            alias = f"__dc{next(_counter)}"
            d_items = [ast.SelectItem(ie, alias=f"{alias}_k{i}")
                       for i, (_, ie) in enumerate(pairs)]
            d_items.append(ast.SelectItem(agg_expr, alias=f"{alias}_agg"))
            import copy
            derived = dataclasses.replace(
                s, items=d_items, where=_and_all(inner_keep),
                group_by=[copy.deepcopy(ie) for _, ie in pairs],
                limit=None, order_by=[])
            sel.from_ = ast.Join("inner", sel.from_,
                                 ast.SubqueryRef(derived, alias), on=None)
            new = [ast.BinaryOp("=", oe, ast.ColumnRef(f"{alias}_k{i}",
                                                       alias))
                   for i, (oe, _) in enumerate(pairs)]
            aggcol = ast.ColumnRef(f"{alias}_agg", alias)
            # preserve operand order: the subquery's slot gets the agg
            # column (flip=True: the subquery was the RIGHT operand,
            # c.left stays on the left)
            new.append(ast.BinaryOp(c.op, aggcol, other) if not flip
                       else ast.BinaryOp(c.op, other, aggcol))
            return new
    return c


def _contains_count(e) -> bool:
    if isinstance(e, ast.FuncCall) and e.name == "count":
        return True
    if dataclasses.is_dataclass(e) and isinstance(e, ast.Node):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(x, ast.Node) and _contains_count(x):
                    return True
    return False

"""Bound (typed) expressions — the planner/executor IR.

Reference analogue: the protobuf `plan.Expr` tree (`proto/plan.proto`) +
function overload resolution (`pkg/sql/plan/function`). Here an expression
is a small Python tree with a resolved DType; the vm layer compiles it to
jnp kernel calls (ops.scalar) over DeviceBatch columns — an expression tree
evaluates as ONE fused XLA computation, where the reference interprets it
per-operator (`colexec/evalExpression.go`).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from matrixone_tpu.container.dtypes import BOOL, DType


class BoundExpr:
    dtype: DType


@dataclasses.dataclass
class BoundCol(BoundExpr):
    name: str          # column name in the child's schema
    dtype: DType


@dataclasses.dataclass
class BoundLiteral(BoundExpr):
    value: object
    dtype: DType


@dataclasses.dataclass
class BoundFunc(BoundExpr):
    op: str            # kernel name: add/sub/mul/div/mod/eq/lt/.../and/or/not
    args: List[BoundExpr]
    dtype: DType


@dataclasses.dataclass
class BoundUdfCall(BoundExpr):
    """A resolved user-defined function call. The definition is SNAPSHOT
    at bind time (body + hash ride the expression), so a cached plan
    executes the body it was bound against — DROP/REPLACE invalidates
    through ddl_gen, never by mutating in-flight plans."""
    name: str
    args: List[BoundExpr]
    dtype: DType                  # declared RETURNS type
    body: str
    arg_names: List[str]
    arg_types: List[DType]        # declared argument types
    body_hash: str
    deterministic: bool = True
    vectorized: bool = True
    is_aggregate: bool = False


@dataclasses.dataclass
class BoundCast(BoundExpr):
    arg: BoundExpr
    dtype: DType


@dataclasses.dataclass
class BoundCase(BoundExpr):
    whens: List[Tuple[BoundExpr, BoundExpr]]
    else_: Optional[BoundExpr]
    dtype: DType


@dataclasses.dataclass
class BoundInList(BoundExpr):
    arg: BoundExpr
    values: List[object]     # python literals
    negated: bool
    dtype: DType


@dataclasses.dataclass
class BoundIsNull(BoundExpr):
    arg: BoundExpr
    negated: bool
    dtype: DType


@dataclasses.dataclass
class BoundLike(BoundExpr):
    arg: BoundExpr           # varchar column (dict codes on device)
    pattern: str
    negated: bool
    dtype: DType


@dataclasses.dataclass
class AggCall:
    func: str                # sum | count | avg | min | max
    arg: Optional[BoundExpr]  # None for count(*)
    distinct: bool
    dtype: DType             # result type
    out_name: str = ""


def walk(e: BoundExpr):
    yield e
    for child in getattr(e, "args", []) or []:
        yield from walk(child)
    if isinstance(e, BoundCast):
        yield from walk(e.arg)
    if isinstance(e, (BoundInList, BoundIsNull, BoundLike)):
        yield from walk(e.arg)
    if isinstance(e, BoundCase):
        for c, v in e.whens:
            yield from walk(c)
            yield from walk(v)
        if e.else_ is not None:
            yield from walk(e.else_)


def columns_used(e: BoundExpr) -> List[str]:
    return [n.name for n in walk(e) if isinstance(n, BoundCol)]


def and_all(cs: List[BoundExpr]) -> BoundExpr:
    """Fold conjuncts into one left-deep AND tree (canonical helper for
    binder pushdown / CBO residual re-attachment)."""
    e = cs[0]
    for c in cs[1:]:
        e = BoundFunc("and", [e, c], BOOL)
    return e

"""SQL tokenizer (reference: pkg/sql/parsers mysql_lexer.go — redesigned)."""

from __future__ import annotations

import dataclasses
from typing import List

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "between", "like", "is",
    "null", "true", "false", "case", "when", "then", "else", "end", "cast",
    "distinct", "asc", "desc", "join", "inner", "left", "right", "cross",
    "outer", "on", "create", "drop", "table", "index", "insert", "into",
    "values", "delete", "update", "set", "show", "tables", "explain",
    "analyze", "date", "interval", "day", "month", "year", "primary",
    "key", "if", "exists", "using", "begin", "commit", "rollback", "with",
    "union", "all", "default", "lists", "op_type", "count", "sum",
    "snapshot", "snapshots", "restore", "of", "timestamp", "avg",
    "auto_increment", "over", "partition",
    "min", "max", "extract",
}

OPERATORS = ["<=", ">=", "<>", "!=", "||", "=", "<", ">", "+", "-", "*", "/",
             "%", "(", ")", ",", ".", ";", "?"]


@dataclasses.dataclass
class Token:
    kind: str     # 'kw' | 'ident' | 'int' | 'float' | 'str' | 'op' | 'eof'
    value: str
    pos: int


class LexError(ValueError):
    pass


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n and (sql[j].isdigit() or sql[j] in ".eE+-"):
                if sql[j] == ".":
                    if seen_dot:
                        break
                    seen_dot = True
                elif sql[j] in "eE":
                    if seen_exp:
                        break
                    seen_exp = True
                elif sql[j] in "+-" and sql[j - 1] not in "eE":
                    break
                j += 1
            text = sql[i:j]
            out.append(Token("float" if ("." in text or "e" in text.lower())
                             else "int", text, i))
            i = j
            continue
        if c == "@" and sql.startswith("@@", i):
            # system variable reference: @@name / @@session.name
            j = i + 2
            while j < n and (sql[j].isalnum() or sql[j] in "_."):
                j += 1
            out.append(Token("sysvar", sql[i + 2:j].lower(), i))
            i = j
            continue
        if c == "$" and sql.startswith("$$", i):
            # dollar-quoted body (CREATE FUNCTION ... AS $$ ... $$):
            # verbatim text, no escape processing — Python bodies are
            # full of quotes and backslashes
            j = sql.find("$$", i + 2)
            if j < 0:
                raise LexError(f"unterminated $$ body at {i}")
            out.append(Token("str", sql[i + 2:j], i))
            i = j + 2
            continue
        if c == "'" or c == '"':
            quote = c
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == quote:
                    if j + 1 < n and sql[j + 1] == quote:  # escaped ''
                        buf.append(quote)
                        j += 2
                        continue
                    break
                if sql[j] == "\\" and j + 1 < n:
                    esc = sql[j + 1]
                    buf.append({"n": "\n", "t": "\t", "\\": "\\",
                                "'": "'", '"': '"'}.get(esc, esc))
                    j += 2
                    continue
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise LexError(f"unterminated string at {i}")
            out.append(Token("str", "".join(buf), i))
            i = j + 1
            continue
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise LexError(f"unterminated identifier at {i}")
            out.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            low = word.lower()
            out.append(Token("kw" if low in KEYWORDS else "ident",
                             low if low in KEYWORDS else word, i))
            i = j
            continue
        for op in OPERATORS:
            if sql.startswith(op, i):
                out.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise LexError(f"unexpected character {c!r} at {i}")
    out.append(Token("eof", "", n))
    return out

"""Index-aware plan rewrites (reference: pkg/sql/plan/apply_indices*.go).

`apply_indices` rewrites

    TopK(k, key = distance(vec_col, const_vec) ASC)
      -> Project(..., distance(...), ...)
        -> Scan(table)                      [no pushed filters]

into the same tree with the Scan replaced by a VectorTopK source that runs
the IVF index (vectorindex/ivf_flat) and yields only ~k candidate rows
(all table columns fetched by row id + the index distance). The Project
then recomputes the exact distance over k rows (free exact re-rank) and
the TopK re-orders them — so the rewrite can only change WHICH k rows are
returned (index recall), never their values or order semantics.
"""

from __future__ import annotations

from typing import Optional

from matrixone_tpu.sql import plan as P
from matrixone_tpu.sql.expr import BoundCol, BoundFunc, BoundLiteral

_DIST_METRIC = {"l2_distance": "l2", "l2_distance_sq": "l2",
                "cosine_distance": "cosine", "inner_product": "ip"}


def apply_indices(node: P.PlanNode, catalog, nprobe: int = 8,
                  overfetch: int = 3, skip_tables=frozenset()) -> P.PlanNode:
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if c is not None:
            setattr(node, attr, apply_indices(c, catalog, nprobe, overfetch,
                                              skip_tables))
    if not isinstance(node, P.TopK):
        return node
    ft = _try_fulltext(node, catalog, skip_tables)
    if ft is not None:
        return ft
    if len(node.keys) != 1 or node.descendings[0]:
        return node
    key = node.keys[0]
    proj = node.child
    if not (isinstance(key, BoundCol) and isinstance(proj, P.Project)):
        return node
    # resolve the sort key to its projected expression
    try:
        kidx = [n for n, _ in proj.schema].index(key.name)
    except ValueError:
        return node
    dist = proj.exprs[kidx]
    if not (isinstance(dist, BoundFunc) and dist.op in _DIST_METRIC
            and len(dist.args) == 2):
        return node
    col_e, vec_e = dist.args
    if not isinstance(col_e, BoundCol):
        col_e, vec_e = vec_e, col_e
    if not (isinstance(col_e, BoundCol) and isinstance(vec_e, BoundLiteral)
            and isinstance(vec_e.value, list)):
        return node
    scan = proj.child
    if not (isinstance(scan, P.Scan) and not scan.filters
            and scan.as_of_ts is None):
        return node
    if scan.table in skip_tables:
        # txn snapshot / workspace reads: exact scan realizes the txn
        # view, the (frontier-built) index cannot — decline the rewrite
        return node
    # find a matching index on (table, column)
    raw_col = col_e.name.split(".")[-1]
    metric = _DIST_METRIC[dist.op]
    for ix in catalog.indexes_on(scan.table):
        if ix.algo in ("ivfflat", "ivfpq", "hnsw") \
                and ix.columns[0] == raw_col \
                and ix.options.get("_metric", "l2") == metric:
            # PQ candidates need a deeper pool: the exact re-rank above
            # (Project recompute + TopK) recovers ADC quantization loss
            factor = overfetch * (3 if ix.algo == "ivfpq" else 1)
            k = (node.k + node.offset) * factor
            proj.child = P.VectorTopK(
                table=scan.table, index_name=ix.name,
                query_vector=list(vec_e.value), k=k, metric=metric,
                columns=scan.columns, schema=scan.schema, nprobe=nprobe)
            return node
    return node


def _try_fulltext(node: P.TopK, catalog, skip_tables) -> "P.PlanNode | None":
    """TopK(desc, key = match_against(col, 'q')) over Project over Scan ->
    FulltextTopK replacing the whole subtree."""
    if len(node.keys) != 1 or not node.descendings[0]:
        return None
    key = node.keys[0]
    proj = node.child
    if not (isinstance(key, BoundCol) and isinstance(proj, P.Project)):
        return None
    try:
        kidx = [n for n, _ in proj.schema].index(key.name)
    except ValueError:
        return None
    mexpr = proj.exprs[kidx]
    if not (isinstance(mexpr, BoundFunc) and mexpr.op == "match_against"
            and len(mexpr.args) >= 2):
        return None
    col_exprs, q_e = mexpr.args[:-1], mexpr.args[-1]
    if not (all(isinstance(c, BoundCol) for c in col_exprs)
            and isinstance(q_e, BoundLiteral)
            and isinstance(q_e.value, str)):
        return None
    scan = proj.child
    if not (isinstance(scan, P.Scan) and not scan.filters
            and scan.as_of_ts is None
            and scan.table not in skip_tables):
        return None
    raw_cols_wanted = [c.name.split(".")[-1] for c in col_exprs]
    for ix in catalog.indexes_on(scan.table):
        if ix.algo != "fulltext" or ix.columns != raw_cols_wanted:
            continue
        # every projected output must be a plain column or the match expr
        out_exprs = []
        for e in proj.exprs:
            if e == mexpr:
                out_exprs.append(("score",))
            elif isinstance(e, BoundCol):
                out_exprs.append(("col", e.name.split(".")[-1]))
            else:
                return None
        return P.FulltextTopK(
            table=scan.table, index_name=ix.name, query=q_e.value,
            k=node.k, offset=node.offset, columns=scan.columns,
            out_exprs=out_exprs, schema=proj.schema)
    return None
